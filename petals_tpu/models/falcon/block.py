"""Falcon decoder block as a pure jitted JAX function.

Capability parity with the reference's WrappedFalconBlock + optimized layers
(/root/reference/src/petals/models/falcon/block.py:34-480): fused-QKV
de-interleave (all three generations), parallel-attention residual structure,
GQA without the reference's KV expand/collapse permutes (the canonical cache
layout keeps true kv heads; our attention op does the grouping). The
reference's CUDA-graphed rotary/split kernels are unnecessary — the step is a
single XLA program.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from petals_tpu.models.common import KVCache, absolute_positions, layer_norm, mm, update_kv_cache
from petals_tpu.models.falcon.config import FalconBlockConfig
from petals_tpu.models.registry import ModelFamily, register_family
from petals_tpu.ops.alibi import build_alibi_slopes
from petals_tpu.ops.attention import attend_maybe_ring
from petals_tpu.ops.rotary import apply_rotary, rotary_tables


def _activation(x: jnp.ndarray, name: str) -> jnp.ndarray:
    if name == "gelu":
        return jax.nn.gelu(x.astype(jnp.float32), approximate=False).astype(x.dtype)
    if name in ("gelu_pytorch_tanh", "gelu_new"):
        return jax.nn.gelu(x.astype(jnp.float32), approximate=True).astype(x.dtype)
    if name == "relu":
        return jax.nn.relu(x)
    raise NotImplementedError(f"Falcon activation {name!r} is not supported")


def block_apply(
    params: dict,
    hidden_states: jnp.ndarray,  # [batch, seq, hidden]
    kv: Optional[KVCache],
    position,
    cfg: FalconBlockConfig,
    *,
    use_flash: bool = False,
    tp_mesh=None,
    n_valid=None,
    ring_mesh=None,  # "sp" mesh: ring attention (stateless path) or q-sharded prefill (cached)
) -> Tuple[jnp.ndarray, Optional[KVCache]]:
    batch, seq, _ = hidden_states.shape
    hq, hkv, d = cfg.num_attention_heads, cfg.num_kv_heads, cfg.head_dim
    residual = hidden_states

    # HF gates the dual-LN layout on new_decoder_architecture + num_ln==2 only
    # (parallel_attn is NOT consulted there)
    if cfg.new_decoder_architecture and cfg.num_ln_in_parallel_attn == 2:
        attn_ln = layer_norm(hidden_states, params["ln_attn_w"], params["ln_attn_b"], cfg.layer_norm_epsilon)
        mlp_ln = layer_norm(hidden_states, params["ln_mlp_w"], params["ln_mlp_b"], cfg.layer_norm_epsilon)
    else:
        attn_ln = layer_norm(hidden_states, params["ln1_w"], params["ln1_b"], cfg.layer_norm_epsilon)
        mlp_ln = attn_ln  # parallel single-LN case; serial case overwritten below

    q = mm(attn_ln, params["wq"])
    k = mm(attn_ln, params["wk"])
    v = mm(attn_ln, params["wv"])
    if cfg.bias:
        q = q + params["bq"]
        k = k + params["bk"]
        v = v + params["bv"]
    q = q.reshape(batch, seq, hq, d)
    k = k.reshape(batch, seq, hkv, d)
    v = v.reshape(batch, seq, hkv, d)

    alibi_slopes = None
    if cfg.alibi:
        # Falcon scales (scores + alibi) jointly by 1/sqrt(d) — unlike BLOOM,
        # where the bias is added unscaled — so pre-scale the slopes here.
        alibi_slopes = build_alibi_slopes(hq) * (d**-0.5)
    else:
        positions = absolute_positions(position, batch, seq)
        cos, sin = rotary_tables(positions, d, theta=cfg.rope_theta)
        q = apply_rotary(q, cos, sin)
        k = apply_rotary(k, cos, sin)

    k_all, v_all, kv_length = update_kv_cache(kv, k, v, position, n_valid)
    attn = attend_maybe_ring(
        q, k_all, v_all, kv=kv, position=position, n_valid=n_valid,
        kv_length=kv_length, ring_mesh=ring_mesh, use_flash=use_flash,
        tp_mesh=tp_mesh, alibi_slopes=alibi_slopes,
    )
    attn = mm(attn.reshape(batch, seq, hq * d), params["wo"])
    if cfg.bias:
        attn = attn + params["bo"]

    # serial residual structure applies only to old-architecture checkpoints
    # (HF skips it entirely when new_decoder_architecture is set)
    if not cfg.new_decoder_architecture and not cfg.parallel_attn:
        residual = residual + attn
        mlp_ln = layer_norm(residual, params["ln2_w"], params["ln2_b"], cfg.layer_norm_epsilon)

    # HF FalconMLP: dense_h_to_4h -> ACT2FN[config.activation] -> dense_4h_to_h
    mlp = mm(mlp_ln, params["w_up"])
    if cfg.bias:
        mlp = mlp + params["b_up"]
    mlp = _activation(mlp, cfg.activation)
    mlp = mm(mlp, params["w_down"])
    if cfg.bias:
        mlp = mlp + params["b_down"]

    if cfg.new_decoder_architecture or cfg.parallel_attn:
        mlp = mlp + attn

    out = mlp + residual
    new_kv = (k_all, v_all) if kv is not None else None
    return out, new_kv


# ----------------------------------------------------------------------------------
# HF checkpoint mapping
# ----------------------------------------------------------------------------------

_HF_BLOCK_PREFIXES = ("transformer.h.{i}.", "h.{i}.")


def hf_to_block_params(tensors: dict, cfg: FalconBlockConfig) -> dict:
    hq, hkv, d = cfg.num_attention_heads, cfg.num_kv_heads, cfg.head_dim
    hidden = cfg.hidden_size

    qkv_w = np.asarray(tensors["self_attention.query_key_value.weight"])  # [out, hidden]
    group = hq // hkv

    if cfg.new_decoder_architecture:
        # out axis = (hkv, group + 2, d): per kv-group queries then k then v
        w = qkv_w.reshape(hkv, group + 2, d, hidden)
        wq = w[:, :-2].reshape(hq * d, hidden)
        wk = w[:, -2].reshape(hkv * d, hidden)
        wv = w[:, -1].reshape(hkv * d, hidden)
    elif cfg.multi_query:
        # out axis = (hq + 2, d): all queries, then one k, one v
        w = qkv_w.reshape(hq + 2, d, hidden)
        wq = w[:-2].reshape(hq * d, hidden)
        wk = w[-2].reshape(d, hidden)
        wv = w[-1].reshape(d, hidden)
    else:
        # out axis = (hq, 3, d): per-head q,k,v interleave (falcon-rw)
        w = qkv_w.reshape(hq, 3, d, hidden)
        wq = w[:, 0].reshape(hq * d, hidden)
        wk = w[:, 1].reshape(hq * d, hidden)
        wv = w[:, 2].reshape(hq * d, hidden)

    def t(arr):
        return np.ascontiguousarray(arr.T)

    params = {
        "wq": t(wq),
        "wk": t(wk),
        "wv": t(wv),
        "wo": t(np.asarray(tensors["self_attention.dense.weight"])),
        "w_up": t(np.asarray(tensors["mlp.dense_h_to_4h.weight"])),
        "w_down": t(np.asarray(tensors["mlp.dense_4h_to_h.weight"])),
    }

    if cfg.new_decoder_architecture and cfg.num_ln_in_parallel_attn == 2:
        params["ln_attn_w"] = np.asarray(tensors["ln_attn.weight"])
        params["ln_attn_b"] = np.asarray(tensors["ln_attn.bias"])
        params["ln_mlp_w"] = np.asarray(tensors["ln_mlp.weight"])
        params["ln_mlp_b"] = np.asarray(tensors["ln_mlp.bias"])
    else:
        params["ln1_w"] = np.asarray(tensors["input_layernorm.weight"])
        params["ln1_b"] = np.asarray(tensors["input_layernorm.bias"])
        if not cfg.parallel_attn and not cfg.new_decoder_architecture:
            params["ln2_w"] = np.asarray(tensors["post_attention_layernorm.weight"])
            params["ln2_b"] = np.asarray(tensors["post_attention_layernorm.bias"])

    if cfg.bias:
        qkv_b = np.asarray(tensors["self_attention.query_key_value.bias"])
        if cfg.new_decoder_architecture:
            b = qkv_b.reshape(hkv, group + 2, d)
            bq, bk, bv = b[:, :-2].reshape(-1), b[:, -2].reshape(-1), b[:, -1].reshape(-1)
        elif cfg.multi_query:
            b = qkv_b.reshape(hq + 2, d)
            bq, bk, bv = b[:-2].reshape(-1), b[-2], b[-1]
        else:
            b = qkv_b.reshape(hq, 3, d)
            bq, bk, bv = b[:, 0].reshape(-1), b[:, 1].reshape(-1), b[:, 2].reshape(-1)
        params.update(
            bq=bq,
            bk=bk,
            bv=bv,
            bo=np.asarray(tensors["self_attention.dense.bias"]),
            b_up=np.asarray(tensors["mlp.dense_h_to_4h.bias"]),
            b_down=np.asarray(tensors["mlp.dense_4h_to_h.bias"]),
        )
    return params


def block_param_shapes(cfg: FalconBlockConfig, dtype=jnp.bfloat16) -> dict:
    h, hq, hkv, d, f = cfg.hidden_size, cfg.num_attention_heads, cfg.num_kv_heads, cfg.head_dim, cfg.ffn_hidden_size
    S = jax.ShapeDtypeStruct
    shapes = {
        "wq": S((h, hq * d), dtype),
        "wk": S((h, hkv * d), dtype),
        "wv": S((h, hkv * d), dtype),
        "wo": S((hq * d, h), dtype),
        "w_up": S((h, f), dtype),
        "w_down": S((f, h), dtype),
    }
    if cfg.new_decoder_architecture and cfg.num_ln_in_parallel_attn == 2:
        shapes.update(
            ln_attn_w=S((h,), dtype), ln_attn_b=S((h,), dtype),
            ln_mlp_w=S((h,), dtype), ln_mlp_b=S((h,), dtype),
        )
    else:
        shapes.update(ln1_w=S((h,), dtype), ln1_b=S((h,), dtype))
        if not cfg.parallel_attn and not cfg.new_decoder_architecture:
            shapes.update(ln2_w=S((h,), dtype), ln2_b=S((h,), dtype))
    if cfg.bias:
        shapes.update(
            bq=S((hq * d,), dtype), bk=S((hkv * d,), dtype), bv=S((hkv * d,), dtype),
            bo=S((h,), dtype), b_up=S((f,), dtype), b_down=S((h,), dtype),
        )
    return shapes


FAMILY = register_family(
    ModelFamily(
        name="falcon",
        config_from_hf=FalconBlockConfig.from_hf_config,
        block_apply=block_apply,
        hf_block_prefixes=_HF_BLOCK_PREFIXES,
        hf_to_block_params=hf_to_block_params,
        block_param_shapes=block_param_shapes,
        supports_ring_attention=True,
    )
)
