"""Client-side Falcon pieces: word embeddings, final norm, tied LM head
(counterpart of reference src/petals/models/falcon/model.py:26-146)."""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

import petals_tpu.models.falcon.block as block_mod
from petals_tpu.models.common import layer_norm
from petals_tpu.models.falcon.config import FalconBlockConfig
from petals_tpu.models.registry import register_family

CLIENT_PREFIXES = (
    "transformer.word_embeddings.",
    "transformer.ln_f.",
    "word_embeddings.",
    "ln_f.",
    "lm_head.",
)


def _base_client_params(tensors: dict, cfg: FalconBlockConfig) -> dict:
    """Embeddings + final norm (no head) — shared by the LM and cls loaders."""

    def pick(*names):
        for name in names:
            if name in tensors:
                return np.asarray(tensors[name])
        raise KeyError(f"None of {names} found in checkpoint")

    return {
        "embed": pick("transformer.word_embeddings.weight", "word_embeddings.weight"),
        "ln_f_w": pick("transformer.ln_f.weight", "ln_f.weight"),
        "ln_f_b": pick("transformer.ln_f.bias", "ln_f.bias"),
    }


def hf_to_client_params(tensors: dict, cfg: FalconBlockConfig) -> dict:
    params = _base_client_params(tensors, cfg)
    if not cfg.tie_word_embeddings and "lm_head.weight" in tensors:
        params["head"] = np.ascontiguousarray(np.asarray(tensors["lm_head.weight"]).T)
    else:
        params["head"] = np.ascontiguousarray(params["embed"].T)
    return params


def client_embed(params: dict, input_ids, cfg: FalconBlockConfig):
    return jnp.take(params["embed"], jnp.asarray(input_ids), axis=0)


def client_head(params: dict, hidden, cfg: FalconBlockConfig):
    normed = layer_norm(jnp.asarray(hidden), params["ln_f_w"], params["ln_f_b"], cfg.layer_norm_epsilon)
    return jnp.dot(
        normed.astype(jnp.float32),
        params["head"].astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )


# -- sequence classification (HF FalconForSequenceClassification layout:
# score head over ln_f output; reference ships the bloom/llama analogues)

from petals_tpu.models.client_common import ln_f_client_norm, ln_f_cls_head, score_matrix  # noqa: E402

CLS_PREFIXES = tuple(p for p in CLIENT_PREFIXES if p != "lm_head.") + ("score.",)


def hf_to_cls_params(tensors: dict, cfg: FalconBlockConfig) -> dict:
    params = _base_client_params(tensors, cfg)
    params["score"] = score_matrix(tensors)
    return params


def client_norm(params: dict, hidden, cfg):
    return ln_f_client_norm(params, hidden, cfg.layer_norm_epsilon)


def cls_head(params: dict, hidden, cfg: FalconBlockConfig):
    return ln_f_cls_head(params, hidden, cfg.layer_norm_epsilon)


FAMILY = register_family(
    dataclasses.replace(
        block_mod.FAMILY,
        hf_client_prefixes=CLIENT_PREFIXES,
        hf_to_client_params=hf_to_client_params,
        client_embed=client_embed,
        client_head=client_head,
        client_norm=client_norm,
        hf_cls_prefixes=CLS_PREFIXES,
        hf_to_cls_params=hf_to_cls_params,
        cls_head=cls_head,
    )
)
