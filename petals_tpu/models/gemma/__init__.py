"""Gemma (v1) family — beyond the reference's four families.

Architecturally a llama-style decoder with four deltas, all absorbed without
a new block implementation:

- RMSNorm computes ``x_normed * (1 + w)`` (zero-centered weights): folded at
  LOAD time — every norm weight becomes ``1 + w`` in float32, after which the
  llama block's plain ``x_normed * w`` is bit-equivalent.
- MLP activation is tanh-approximate GELU: ``hidden_act`` rides the llama
  block config (models/common.ACTIVATIONS).
- Embeddings scale by sqrt(hidden_size) on the client
  (``gemma_client_embed``), matching HF's normalizer.
- Head is always tied to the embeddings; explicit head_dim (256 on 7B)
  already rides LlamaBlockConfig.from_hf_config.

Gemma 2 is a DIFFERENT architecture (logit softcapping, alternating sliding
windows, post-norms): it has its own block implementation in models/gemma2.
"""

from __future__ import annotations

import dataclasses

import numpy as np

import petals_tpu.models.llama.block as llama_block
import petals_tpu.models.llama.model as llama_model
from petals_tpu.models.client_common import (
    llama_style_client_embed,
    llama_style_hf_to_client_params,
    llama_style_hf_to_cls_params,
)
from petals_tpu.models.llama.config import LlamaBlockConfig
from petals_tpu.models.registry import register_family


def config_from_hf(hf_config) -> LlamaBlockConfig:
    return LlamaBlockConfig.from_hf_config(hf_config)


def _fold_norm(w) -> np.ndarray:
    """Gemma RMSNorm: x_normed * (1 + w) — fold the +1 into the stored weight
    (float32, exact) so the llama block's x_normed * w is equivalent."""
    return np.asarray(w, np.float32) + 1.0


def hf_to_block_params(tensors: dict, cfg: LlamaBlockConfig) -> dict:
    params = llama_block.hf_to_block_params(tensors, cfg)
    params["ln1"] = _fold_norm(params["ln1"])
    params["ln2"] = _fold_norm(params["ln2"])
    return params


def hf_to_client_params(tensors: dict, cfg) -> dict:
    params = llama_style_hf_to_client_params(tensors, cfg)
    params["norm"] = _fold_norm(params["norm"])
    return params


def hf_to_cls_params(tensors: dict, cfg) -> dict:
    # the sequence-classification surface runs the same final norm: fold here
    # too or cls logits would silently use the zero-centered raw weights
    params = llama_style_hf_to_cls_params(tensors, cfg)
    params["norm"] = _fold_norm(params["norm"])
    return params


def client_embed(params: dict, input_ids, cfg):
    h = llama_style_client_embed(params, input_ids, cfg)
    # HF casts the sqrt(hidden) normalizer to the embedding dtype first
    import jax.numpy as jnp

    return h * jnp.asarray(np.sqrt(cfg.hidden_size), h.dtype)


FAMILY = register_family(
    dataclasses.replace(
        llama_model.FAMILY,
        name="gemma",
        config_from_hf=config_from_hf,
        hf_to_block_params=hf_to_block_params,
        hf_to_client_params=hf_to_client_params,
        hf_to_cls_params=hf_to_cls_params,
        client_embed=client_embed,
        # the folded (1+w) norms must stay float32 through the serving-dtype
        # cast: bf16-rounding 1+w loses ~2^-9 per channel that the unfolded
        # form would not (rms_norm upcasts to f32 anyway, so this is free)
        cast_exempt=("ln1", "ln2", "norm"),
    )
)
