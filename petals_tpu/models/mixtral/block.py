"""Mixtral (MoE) decoder block as a pure jitted JAX function.

Capability parity with the reference's WrappedMixtralBlock
(/root/reference/src/petals/models/mixtral/block.py:13-113): all experts live
on the hosting server (no cross-server expert parallelism, matching the
reference), GQA attention with optional sliding window, top-k softmax routing.

TPU-first MoE, two dispatch modes sharing HF-exact routing:

- DENSE (decode + sharded/quantized paths): every expert runs over every token
  (stacked expert weights, one batched einsum per projection) with a top-k
  one-hot combine. At M=1 decode this is free — the step is weight-bandwidth
  bound and dense compute keeps static shapes with zero scatter.
- SPARSE (prefill, round-3): assignments are sorted by expert and the three
  projections run as grouped matmuls via ``jax.lax.ragged_dot`` (static total
  size N*k, dynamic per-expert group sizes), so prefill FLOPs scale with
  top-k instead of num_experts (4x fewer for top-2-of-8) — the
  megablocks-style dispatch expressed in XLA's native ragged op instead of
  CUDA kernels. Tokens are never dropped (no GShard capacity factor);
  outputs match the dense path's to within accumulation precision (the
  sparse combine runs in f32 where the dense combine rounds the routing
  weights to the compute dtype).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from petals_tpu.models.common import KVCache, absolute_positions, mm, rms_norm, silu, update_kv_cache
from petals_tpu.models.mixtral.config import MixtralBlockConfig
from petals_tpu.models.registry import ModelFamily, register_family
from petals_tpu.ops.attention import attend_maybe_ring
from petals_tpu.ops.rotary import apply_rotary, rotary_tables


# prefill chunks at or above this many tokens take the sparse (ragged_dot)
# dispatch; below it (decode especially) dense all-experts compute wins
SPARSE_MIN_SEQ = 8


def _moe_sparse(x, w1, w2, w3, top_idx, top_probs, cfg) -> jnp.ndarray:
    """Grouped-matmul dispatch: FLOPs proportional to N * top_k."""
    b, s, h = x.shape
    E, k = cfg.num_local_experts, cfg.num_experts_per_tok
    n_assign = b * s * k
    xf = x.reshape(b * s, h)
    flat_experts = top_idx.reshape(n_assign)
    order = jnp.argsort(flat_experts, stable=True)  # group assignments by expert
    token_of = order // k
    xg = jnp.take(xf, token_of, axis=0)  # [N*k, h]
    group_sizes = jnp.bincount(flat_experts, length=E).astype(jnp.int32)
    g1 = jax.lax.ragged_dot(xg, w1, group_sizes)
    g3 = jax.lax.ragged_dot(xg, w3, group_sizes)
    out = jax.lax.ragged_dot(silu(g1) * g3, w2, group_sizes)  # [N*k, h]
    wts = jnp.take(top_probs.reshape(n_assign), order).astype(jnp.float32)
    y = jnp.zeros((b * s, h), jnp.float32)
    y = y.at[token_of].add(out.astype(jnp.float32) * wts[:, None])
    return y.astype(x.dtype).reshape(b, s, h)


def moe_apply(
    params: dict, x: jnp.ndarray, cfg: MixtralBlockConfig, *, sparse: bool = False
) -> jnp.ndarray:
    """x: [batch, seq, hidden] -> mixture of top-k experts, HF-exact routing."""
    from petals_tpu.ops.quant import QuantizedLinear, quant_matmul

    router_logits = x @ params["gate"]  # [b, s, E]
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    top_probs, top_idx = jax.lax.top_k(probs, cfg.num_experts_per_tok)  # [b, s, k]
    top_probs = top_probs / top_probs.sum(axis=-1, keepdims=True)

    w1, w2, w3 = params["w1"], params["w2"], params["w3"]
    if sparse and not isinstance(w1, QuantizedLinear):
        return _moe_sparse(x, w1, w2, w3, top_idx, top_probs, cfg)

    # combine weights per expert: [b, s, E]
    one_hot = jax.nn.one_hot(top_idx, cfg.num_local_experts, dtype=top_probs.dtype)
    combine = (one_hot * top_probs[..., None]).sum(axis=2).astype(x.dtype)
    if isinstance(w1, QuantizedLinear):
        # Quantized experts: run each expert through quant_matmul (the fused
        # NF4 kernel on TPU) — dense expert weights are never materialized, so
        # the 4-bit memory budget that sized this span holds at runtime.
        def expert(e):
            def slice_q(q):
                return QuantizedLinear(q.kind, q.data[e], q.scales[e], q.in_features, q.out_features)

            g = silu(quant_matmul(x, slice_q(w1))) * quant_matmul(x, slice_q(w3))
            return quant_matmul(g, slice_q(w2))

        expert_out = jnp.stack([expert(e) for e in range(cfg.num_local_experts)])  # [E, b, s, h]
    else:
        # dense expert compute on stacked weights: w1/w3 [E, h, m], w2 [E, m, h]
        gate_out = jnp.einsum("bsh,ehm->ebsm", x, w1)
        up = jnp.einsum("bsh,ehm->ebsm", x, w3)
        expert_out = jnp.einsum("ebsm,emh->ebsh", silu(gate_out) * up, w2)
    return jnp.einsum("ebsh,bse->bsh", expert_out, combine)


def block_apply(
    params: dict,
    hidden_states: jnp.ndarray,
    kv: Optional[KVCache],
    position,
    cfg: MixtralBlockConfig,
    *,
    use_flash: bool = False,
    tp_mesh=None,
    n_valid=None,
    ring_mesh=None,  # "sp" mesh: ring attention (stateless path) or q-sharded prefill (cached)
) -> Tuple[jnp.ndarray, Optional[KVCache]]:
    batch, seq, _ = hidden_states.shape
    hq, hkv, d = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim

    residual = hidden_states
    x = rms_norm(hidden_states, params["ln1"], cfg.rms_norm_eps)
    q = mm(x, params["wq"]).reshape(batch, seq, hq, d)
    k = mm(x, params["wk"]).reshape(batch, seq, hkv, d)
    v = mm(x, params["wv"]).reshape(batch, seq, hkv, d)

    positions = absolute_positions(position, batch, seq)
    cos, sin = rotary_tables(positions, d, theta=cfg.rope_theta)
    q = apply_rotary(q, cos, sin)
    k = apply_rotary(k, cos, sin)

    k_all, v_all, kv_length = update_kv_cache(kv, k, v, position, n_valid)
    attn = attend_maybe_ring(
        q, k_all, v_all, kv=kv, position=position, n_valid=n_valid,
        kv_length=kv_length, ring_mesh=ring_mesh, use_flash=use_flash,
        tp_mesh=tp_mesh, sliding_window=cfg.sliding_window,
    )
    hidden_states = residual + mm(attn.reshape(batch, seq, hq * d), params["wo"])

    residual = hidden_states
    x = rms_norm(hidden_states, params["ln2"], cfg.rms_norm_eps)
    # sparse dispatch at prefill lengths, single-device only (under an ep/tp
    # mesh the dense einsums carry the expert shardings; ragged groups don't)
    sparse = seq >= SPARSE_MIN_SEQ and tp_mesh is None and ring_mesh is None
    hidden_states = residual + moe_apply(params, x, cfg, sparse=sparse)

    new_kv = (k_all, v_all) if kv is not None else None
    return hidden_states, new_kv


# ----------------------------------------------------------------------------------
# HF checkpoint mapping
# ----------------------------------------------------------------------------------

_HF_BLOCK_PREFIXES = ("model.layers.{i}.",)


def hf_to_block_params(tensors: dict, cfg: MixtralBlockConfig) -> dict:
    def t(name):
        return np.ascontiguousarray(np.asarray(tensors[name]).T)

    E = cfg.num_local_experts
    w1 = np.stack([t(f"block_sparse_moe.experts.{e}.w1.weight") for e in range(E)])
    w2 = np.stack([t(f"block_sparse_moe.experts.{e}.w2.weight") for e in range(E)])
    w3 = np.stack([t(f"block_sparse_moe.experts.{e}.w3.weight") for e in range(E)])
    return {
        "ln1": np.asarray(tensors["input_layernorm.weight"]),
        "wq": t("self_attn.q_proj.weight"),
        "wk": t("self_attn.k_proj.weight"),
        "wv": t("self_attn.v_proj.weight"),
        "wo": t("self_attn.o_proj.weight"),
        "ln2": np.asarray(tensors["post_attention_layernorm.weight"]),
        "gate": t("block_sparse_moe.gate.weight"),
        "w1": w1,
        "w2": w2,
        "w3": w3,
    }


def block_param_shapes(cfg: MixtralBlockConfig, dtype=jnp.bfloat16) -> dict:
    h, hq, hkv, d, m, E = (
        cfg.hidden_size,
        cfg.num_attention_heads,
        cfg.num_key_value_heads,
        cfg.head_dim,
        cfg.intermediate_size,
        cfg.num_local_experts,
    )
    S = jax.ShapeDtypeStruct
    return {
        "ln1": S((h,), dtype),
        "wq": S((h, hq * d), dtype),
        "wk": S((h, hkv * d), dtype),
        "wv": S((h, hkv * d), dtype),
        "wo": S((hq * d, h), dtype),
        "ln2": S((h,), dtype),
        "gate": S((h, E), dtype),
        "w1": S((E, h, m), dtype),
        "w2": S((E, m, h), dtype),
        "w3": S((E, h, m), dtype),
    }


FAMILY = register_family(
    ModelFamily(
        name="mixtral",
        config_from_hf=MixtralBlockConfig.from_hf_config,
        block_apply=block_apply,
        hf_block_prefixes=_HF_BLOCK_PREFIXES,
        hf_to_block_params=hf_to_block_params,
        block_param_shapes=block_param_shapes,
        supports_ring_attention=True,
    )
)
