from petals_tpu.models.mixtral.block import FAMILY as _BLOCK_FAMILY  # noqa: F401
from petals_tpu.models.mixtral.model import FAMILY as _FAMILY  # noqa: F401
from petals_tpu.models.mixtral.config import MixtralBlockConfig

__all__ = ["MixtralBlockConfig"]
