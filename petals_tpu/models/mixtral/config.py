"""Mixtral (MoE) family block config (parity target: reference
src/petals/models/mixtral/config.py:16-36)."""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class MixtralBlockConfig:
    hidden_size: int
    num_attention_heads: int
    num_key_value_heads: int
    head_dim: int
    intermediate_size: int
    num_hidden_layers: int
    num_local_experts: int
    num_experts_per_tok: int
    rms_norm_eps: float
    rope_theta: float = 1e6
    sliding_window: Optional[int] = None
    vocab_size: int = 32000
    tie_word_embeddings: bool = False

    @classmethod
    def from_hf_config(cls, hf_config) -> "MixtralBlockConfig":
        return cls(
            hidden_size=hf_config.hidden_size,
            num_attention_heads=hf_config.num_attention_heads,
            num_key_value_heads=hf_config.num_key_value_heads,
            head_dim=getattr(hf_config, "head_dim", None)
            or hf_config.hidden_size // hf_config.num_attention_heads,
            intermediate_size=hf_config.intermediate_size,
            num_hidden_layers=hf_config.num_hidden_layers,
            num_local_experts=hf_config.num_local_experts,
            num_experts_per_tok=hf_config.num_experts_per_tok,
            rms_norm_eps=hf_config.rms_norm_eps,
            rope_theta=getattr(hf_config, "rope_theta", 1e6),
            sliding_window=getattr(hf_config, "sliding_window", None),
            vocab_size=hf_config.vocab_size,
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings", False),
        )
