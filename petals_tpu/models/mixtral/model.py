"""Client-side Mixtral pieces (counterpart of reference
src/petals/models/mixtral/model.py:26-175) — same embed/norm/head layout as
Llama, shared via models/client_common.py."""

from __future__ import annotations

import dataclasses

import petals_tpu.models.mixtral.block as block_mod
from petals_tpu.models.client_common import (
    LLAMA_STYLE_CLIENT_PREFIXES,
    LLAMA_STYLE_CLS_PREFIXES,
    llama_style_client_embed,
    llama_style_client_head,
    llama_style_client_norm,
    llama_style_cls_head,
    llama_style_hf_to_client_params,
    llama_style_hf_to_cls_params,
)
from petals_tpu.models.registry import register_family

FAMILY = register_family(
    dataclasses.replace(
        block_mod.FAMILY,
        hf_client_prefixes=LLAMA_STYLE_CLIENT_PREFIXES,
        hf_to_client_params=llama_style_hf_to_client_params,
        client_embed=llama_style_client_embed,
        client_head=llama_style_client_head,
        client_norm=llama_style_client_norm,
        hf_cls_prefixes=LLAMA_STYLE_CLS_PREFIXES,
        hf_to_cls_params=llama_style_hf_to_cls_params,
        cls_head=llama_style_cls_head,
    )
)
