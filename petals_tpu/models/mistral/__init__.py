"""Mistral family (beyond the reference's four families).

A llama-style decoder whose only architectural delta is an (optional)
all-layer sliding attention window — exactly the window semantics the
attention stack already implements for Mixtral (kv > q_pos - window), so the
family is the llama block with ``sliding_window`` taken from the checkpoint.
Mistral v0.2+ ships ``sliding_window: null`` and degrades to plain llama.
Sliding windows ride the flash kernel and the ring-attention sp axis alike.
"""

from __future__ import annotations

import dataclasses

import petals_tpu.models.llama.model as llama_model
from petals_tpu.models.llama.config import LlamaBlockConfig
from petals_tpu.models.registry import register_family


def config_from_hf(hf_config) -> LlamaBlockConfig:
    base = LlamaBlockConfig.from_hf_config(hf_config)
    return dataclasses.replace(
        base, sliding_window=getattr(hf_config, "sliding_window", None)
    )


FAMILY = register_family(
    dataclasses.replace(llama_model.FAMILY, name="mistral", config_from_hf=config_from_hf)
)
