"""Model-family registry (counterpart of reference src/petals/utils/auto_config.py:22-52,
which dispatches on HF ``config.model_type``).

Each family registers a ``ModelFamily`` describing how to build block configs,
apply a block, and map HF checkpoint tensors to our parameter trees.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

_FAMILIES: Dict[str, "ModelFamily"] = {}


@dataclasses.dataclass(frozen=True)
class ModelFamily:
    """Everything the framework needs to serve/consume one model family."""

    name: str  # HF model_type, e.g. "llama"
    config_from_hf: Callable[[Any], Any]  # HF PretrainedConfig -> BlockConfig
    block_apply: Callable  # (params, hidden, kv, position, cfg, ...) -> (hidden, kv)
    hf_block_prefixes: tuple  # checkpoint prefixes of block i, with {i} placeholder
    hf_to_block_params: Callable  # (dict[str, np.ndarray], cfg) -> params pytree
    block_param_shapes: Optional[Callable] = None  # cfg -> pytree of jax.ShapeDtypeStruct
    # Underlying block architecture ("" -> same as name). Derived families
    # built via dataclasses.replace (qwen2/mistral over llama) inherit it, so
    # architecture-keyed tables (quantizable leaves, fuse groups in
    # utils/convert_block.py) resolve without per-alias entries.
    block_arch: str = ""
    # leaf NAMES whose loaded dtype is preserved by the param casters (e.g.
    # gemma's (1+w)-folded norms must stay float32 for the fold to be exact
    # under bf16 serving; rms_norm upcasts anyway, so this is free)
    cast_exempt: tuple = ()
    # Client-side (embeddings + final norm + LM head), filled by model.py modules:
    hf_client_prefixes: tuple = ()  # checkpoint prefixes of client-held tensors
    hf_to_client_params: Optional[Callable] = None  # (dict, cfg) -> params pytree
    client_embed: Optional[Callable] = None  # (params, input_ids, cfg) -> hidden
    client_head: Optional[Callable] = None  # (params, hidden, cfg) -> logits (f32)
    client_norm: Optional[Callable] = None  # (params, hidden, cfg) -> final-norm'd hidden
    # Sequence classification (reference models/*/model.py *ForSequenceClassification):
    hf_cls_prefixes: tuple = ()  # checkpoint prefixes incl. the score head
    hf_to_cls_params: Optional[Callable] = None  # (dict, cfg) -> params pytree
    cls_head: Optional[Callable] = None  # (params, hidden, cfg) -> per-position label logits
    # block_apply accepts ring_mesh= for sequence-parallel attention on the
    # stateless (no-KV) path; ALiBi bias and sliding windows ride the ring
    # on global positions (ops/ring_attention.py)
    supports_ring_attention: bool = False


def register_family(family: ModelFamily) -> ModelFamily:
    _FAMILIES[family.name] = family
    return family


def get_family(model_type: str) -> ModelFamily:
    if model_type not in _FAMILIES:
        raise KeyError(
            f"Unsupported model family {model_type!r}; known: {sorted(_FAMILIES)}"
        )
    return _FAMILIES[model_type]


def known_families() -> tuple:
    return tuple(sorted(_FAMILIES))
