"""TCP relay for NAT'd / firewalled servers (the reference's libp2p relay +
client-mode role, src/petals/server/server.py:137-150 and hivemind autorelay,
rebuilt for the framed-msgpack transport).

A server that cannot accept inbound connections keeps ONE outbound control
connection to a relay peer (any reachable peer running ``RelayServer`` — the
bootstrap DHT node by default). When someone wants to reach it:

  client ──TCP──▶ relay : {"t": "relay_dial", "target": <peer_id>}
  relay ──control──▶ hidden server : {"t": "relay_incoming", "token"}
  hidden server ──new outbound TCP──▶ relay : {"t": "relay_accept", "token"}
  relay: sends {"t": "relay_ok"} down both sockets, then splices raw bytes.

After ``relay_ok`` both ends speak the NORMAL rpc protocol end-to-end: the
hidden server runs ``RpcServer._on_connection`` on its outbound socket (a
reverse connection) and the client wraps its socket in an ``RpcClient``. The
identity handshake (hello/auth challenge-response, dht/identity.py) happens
through the splice, so a malicious relay can drop traffic but cannot
impersonate either side or inject into the authenticated session.

Registration is authenticated: the relay challenges the hidden server with a
nonce and verifies an Ed25519 signature binding pub -> peer_id, so nobody can
squat another server's relay slot and black-hole its traffic.
"""

from __future__ import annotations

import asyncio
import dataclasses
import secrets
from typing import Dict, Optional, Tuple

from petals_tpu.data_structures import PeerID
from petals_tpu.rpc.protocol import read_frame, write_frame
from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_REGISTER_CONTEXT = b"ptu-relay-register:"
ACCEPT_TIMEOUT = 15.0
_SPLICE_CHUNK = 1 << 16


def _register_challenge(nonce: bytes, pub: bytes) -> bytes:
    return _REGISTER_CONTEXT + nonce + pub


@dataclasses.dataclass
class _Registration:
    writer: asyncio.StreamWriter
    lock: asyncio.Lock


class RelayServer:
    """Accepts registrations from hidden servers and dials from clients."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host, self._requested_port = host, port
        self._server: Optional[asyncio.AbstractServer] = None
        self._registered: Dict[PeerID, _Registration] = {}
        # token -> (dialer reader, dialer writer, accepted event, splice-done event)
        self._pending: Dict[str, tuple] = {}
        self._conn_tasks: set = set()

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._on_connection, self.host, self._requested_port)
        logger.debug(f"RelayServer listening on {self.host}:{self.port}")

    @property
    def port(self) -> int:
        assert self._server is not None, "relay not started"
        return self._server.sockets[0].getsockname()[1]

    def is_registered(self, peer_id: PeerID) -> bool:
        return peer_id in self._registered

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
        for task in list(self._conn_tasks):
            task.cancel()
        if self._conn_tasks:
            await asyncio.gather(*self._conn_tasks, return_exceptions=True)
        if self._server is not None:
            await self._server.wait_closed()

    async def _on_connection(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        task = asyncio.current_task()
        self._conn_tasks.add(task)
        lock = asyncio.Lock()
        registered_as: Optional[PeerID] = None
        try:
            nonce = secrets.token_bytes(16)
            await write_frame(writer, {"t": "relay_hello", "nonce": nonce.hex()}, lock)
            msg = await asyncio.wait_for(read_frame(reader), ACCEPT_TIMEOUT)
            kind = msg.get("t")
            if kind == "relay_register":
                registered_as = await self._handle_register(msg, nonce, writer, lock)
                if registered_as is not None:
                    # control loop: answer keepalives until the hidden server drops
                    while True:
                        msg = await read_frame(reader)
                        if msg.get("t") == "relay_ping":
                            await write_frame(writer, {"t": "relay_pong"}, lock)
            elif kind == "relay_dial":
                await self._handle_dial(msg, reader, writer, lock)
            elif kind == "relay_accept":
                await self._handle_accept(msg, reader, writer, lock)
            else:
                await write_frame(writer, {"t": "relay_err", "error": f"unknown {kind!r}"}, lock)
        except (asyncio.IncompleteReadError, asyncio.TimeoutError, ConnectionError):
            pass
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("Relay connection failed")
        finally:
            if registered_as is not None and self._registered.get(registered_as, None) is not None:
                if self._registered[registered_as].writer is writer:
                    del self._registered[registered_as]
            writer.close()
            self._conn_tasks.discard(task)

    async def _handle_register(self, msg, nonce, writer, lock) -> Optional[PeerID]:
        from petals_tpu.dht import identity as ident

        try:
            pub = bytes.fromhex(msg.get("pub") or "")
            sig = bytes.fromhex(msg.get("sig") or "")
        except ValueError:
            pub = sig = b""
        if not pub or not ident.verify(pub, sig, _register_challenge(nonce, pub)):
            await write_frame(writer, {"t": "relay_err", "error": "bad registration proof"}, lock)
            return None
        peer_id = ident.peer_id_of(pub)
        self._registered[peer_id] = _Registration(writer, lock)
        await write_frame(writer, {"t": "relay_ok"}, lock)
        logger.info(f"Relay: registered hidden server {peer_id.to_string()[:8]}…")
        return peer_id

    async def _handle_dial(self, msg, reader, writer, lock) -> None:
        try:
            target = PeerID.from_string(msg.get("target") or "")
        except Exception:
            await write_frame(writer, {"t": "relay_err", "error": "bad target"}, lock)
            return
        reg = self._registered.get(target)
        if reg is None:
            await write_frame(writer, {"t": "relay_err", "error": "target not registered"}, lock)
            return
        token = secrets.token_hex(16)
        accepted, done = asyncio.Event(), asyncio.Event()
        self._pending[token] = (reader, writer, accepted, done)
        try:
            try:
                await write_frame(reg.writer, {"t": "relay_incoming", "token": token}, reg.lock)
            except ConnectionError:
                await write_frame(writer, {"t": "relay_err", "error": "target control channel lost"}, lock)
                return
            try:
                await asyncio.wait_for(accepted.wait(), ACCEPT_TIMEOUT)
            except asyncio.TimeoutError:
                await write_frame(writer, {"t": "relay_err", "error": "target did not accept"}, lock)
                return
            # the acceptor's connection task does the splice; park here until
            # it finishes so our finally doesn't close the client socket early
            await done.wait()
        finally:
            self._pending.pop(token, None)

    async def _handle_accept(self, msg, reader, writer, lock) -> None:
        entry = self._pending.pop(msg.get("token") or "", None)
        if entry is None:
            await write_frame(writer, {"t": "relay_err", "error": "unknown token"}, lock)
            return
        dial_reader, dial_writer, accepted, done = entry
        dial_lock = asyncio.Lock()
        await write_frame(dial_writer, {"t": "relay_ok"}, dial_lock)
        await write_frame(writer, {"t": "relay_ok"}, lock)
        accepted.set()
        try:
            await asyncio.gather(
                _splice(dial_reader, writer), _splice(reader, dial_writer)
            )
        finally:
            done.set()
            dial_writer.close()

    def register_on(self, rpc_server) -> None:
        """Advertise the relay service in the host RpcServer's method table so
        peers can discover support via a cheap unary probe."""
        async def relay_info(_payload, _ctx):
            return {"host": self.host, "port": self.port}

        rpc_server.add_unary_handler("relay.info", relay_info)


async def _splice(reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
    try:
        while True:
            chunk = await reader.read(_SPLICE_CHUNK)
            if not chunk:
                break
            writer.write(chunk)
            await writer.drain()
    except (ConnectionError, asyncio.IncompleteReadError):
        pass
    finally:
        try:
            writer.write_eof()
        except (OSError, RuntimeError):
            writer.close()


async def relay_dial(
    host: str, port: int, target: PeerID, timeout: float = 10.0
) -> Tuple[asyncio.StreamReader, asyncio.StreamWriter]:
    """Client side: returns (reader, writer) spliced through the relay to the
    hidden server; the normal rpc handshake runs on top."""
    reader, writer = await asyncio.wait_for(asyncio.open_connection(host, port), timeout)
    lock = asyncio.Lock()
    try:
        hello = await asyncio.wait_for(read_frame(reader), timeout)
        if hello.get("t") != "relay_hello":
            raise ConnectionError(f"not a relay (got {hello.get('t')!r})")
        await write_frame(writer, {"t": "relay_dial", "target": target.to_string()}, lock)
        ok = await asyncio.wait_for(read_frame(reader), timeout + ACCEPT_TIMEOUT)
        if ok.get("t") != "relay_ok":
            raise ConnectionError(f"relay dial failed: {ok.get('error', ok)}")
        return reader, writer
    except BaseException:
        writer.close()
        raise


class RelayRegistrar:
    """Hidden-server side: keeps a registered control connection to the relay
    and answers relay_incoming by dialing back and serving the rpc protocol
    on the reverse connection."""

    def __init__(self, relay_host: str, relay_port: int, identity, rpc_server,
                 *, keepalive: float = 30.0, retry_delay: float = 5.0):
        self.relay_host, self.relay_port = relay_host, relay_port
        self.identity = identity
        self.rpc_server = rpc_server
        self.keepalive = keepalive
        self.retry_delay = retry_delay
        self._task: Optional[asyncio.Task] = None
        self._accept_tasks: set = set()
        self.registered = asyncio.Event()

    async def start(self) -> None:
        self._task = asyncio.create_task(self._run())

    async def wait_registered(self, timeout: float = 15.0) -> None:
        await asyncio.wait_for(self.registered.wait(), timeout)

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        for task in list(self._accept_tasks):
            task.cancel()
        if self._accept_tasks:
            await asyncio.gather(*self._accept_tasks, return_exceptions=True)

    async def _run(self) -> None:
        while True:
            try:
                await self._register_and_serve()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                logger.warning(f"Relay control connection lost ({e}); retrying in {self.retry_delay}s")
            self.registered.clear()
            await asyncio.sleep(self.retry_delay)

    async def _register_and_serve(self) -> None:
        reader, writer = await asyncio.open_connection(self.relay_host, self.relay_port)
        lock = asyncio.Lock()
        try:
            hello = await asyncio.wait_for(read_frame(reader), ACCEPT_TIMEOUT)
            nonce = bytes.fromhex(hello["nonce"])
            sig = self.identity.sign(_register_challenge(nonce, self.identity.public_bytes))
            await write_frame(
                writer,
                {"t": "relay_register", "pub": self.identity.public_bytes.hex(), "sig": sig.hex()},
                lock,
            )
            ok = await asyncio.wait_for(read_frame(reader), ACCEPT_TIMEOUT)
            if ok.get("t") != "relay_ok":
                raise ConnectionError(f"relay refused registration: {ok.get('error', ok)}")
            self.registered.set()
            loop = asyncio.get_running_loop()
            last_rx = loop.time()
            while True:
                try:
                    msg = await asyncio.wait_for(read_frame(reader), self.keepalive)
                except asyncio.TimeoutError:
                    # idle: probe the control channel instead of churning it
                    if loop.time() - last_rx > self.keepalive * 4:
                        raise ConnectionError("relay control channel went silent")
                    await write_frame(writer, {"t": "relay_ping"}, lock)
                    continue
                last_rx = loop.time()
                if msg.get("t") == "relay_incoming":
                    task = asyncio.create_task(self._accept(msg["token"]))
                    self._accept_tasks.add(task)
                    task.add_done_callback(self._accept_tasks.discard)
        finally:
            writer.close()

    async def _accept(self, token: str) -> None:
        try:
            reader, writer = await asyncio.open_connection(self.relay_host, self.relay_port)
        except OSError as e:
            logger.warning(f"Relay accept dial failed: {e}")
            return
        lock = asyncio.Lock()
        try:
            await asyncio.wait_for(read_frame(reader), ACCEPT_TIMEOUT)  # relay_hello
            await write_frame(writer, {"t": "relay_accept", "token": token}, lock)
            ok = await asyncio.wait_for(read_frame(reader), ACCEPT_TIMEOUT)
            if ok.get("t") != "relay_ok":
                raise ConnectionError(f"relay refused accept: {ok.get('error', ok)}")
        except (ConnectionError, asyncio.TimeoutError, asyncio.IncompleteReadError, KeyError) as e:
            logger.warning(f"Relay accept handshake failed: {e}")
            writer.close()
            return
        # serve the normal rpc protocol on the reverse connection; the rpc
        # server's connection loop owns the socket from here
        await self.rpc_server._on_connection(reader, writer)
