"""Wire protocol: length-prefixed msgpack frames over asyncio TCP streams.

This is the swarm's inter-host data plane (the role libp2p streams play in the
reference — SURVEY.md §5.8). One TCP connection multiplexes many concurrent
calls; each call has a connection-local id. Message kinds:

  {"t": "hello", "peer_id": hex}                      — sent once by each side
  {"t": "req",  "id", "method", "payload"}            — unary request
  {"t": "resp", "id", "ok", "payload"|"error"}        — unary response / stream abort
  {"t": "sopen", "id", "method"}                      — open bidirectional stream
  {"t": "sitem", "id", "payload"}                     — stream item (either way)
  {"t": "send",  "id"}                                — half-close (either way)
  {"t": "cancel", "id"}                               — cancel in-flight call

Frames: 4-byte big-endian length + msgpack body. Payload tensors ride as
msgpack bin (see rpc/serialization.py).
"""

from __future__ import annotations

import asyncio
import struct
from typing import Any

import msgpack

MAX_FRAME_BYTES = 1 << 30  # 1 GiB hard cap; large tensors stream in chunks far below this
DEFAULT_CHUNK_BYTES = 4 << 20  # split tensors into ~4 MiB stream items


async def read_frame(reader: asyncio.StreamReader) -> Any:
    header = await reader.readexactly(4)
    (length,) = struct.unpack(">I", header)
    if length > MAX_FRAME_BYTES:
        raise ValueError(f"Frame of {length} bytes exceeds the {MAX_FRAME_BYTES} byte cap")
    body = await reader.readexactly(length)
    return msgpack.unpackb(body, raw=False, strict_map_key=False)


def encode_frame(message: Any) -> bytes:
    body = msgpack.packb(message, use_bin_type=True)
    if len(body) > MAX_FRAME_BYTES:
        raise ValueError(f"Frame of {len(body)} bytes exceeds the {MAX_FRAME_BYTES} byte cap")
    return struct.pack(">I", len(body)) + body


async def write_frame(writer: asyncio.StreamWriter, message: Any, lock: asyncio.Lock) -> None:
    frame = encode_frame(message)
    async with lock:  # interleaving-safe: one frame at a time per connection
        writer.write(frame)
        await writer.drain()
