"""Wire protocol: length-prefixed msgpack frames over asyncio TCP streams.

This is the swarm's inter-host data plane (the role libp2p streams play in the
reference — SURVEY.md §5.8). One TCP connection multiplexes many concurrent
calls; each call has a connection-local id. Message kinds:

  {"t": "hello", "peer_id": hex}                      — sent once by each side
  {"t": "req",  "id", "method", "payload"}            — unary request
  {"t": "resp", "id", "ok", "payload"|"error"}        — unary response / stream abort
  {"t": "sopen", "id", "method"}                      — open bidirectional stream
  {"t": "sitem", "id", "payload"}                     — stream item (either way)
  {"t": "send",  "id"}                                — half-close (either way)
  {"t": "cancel", "id"}                               — cancel in-flight call

Frames: 4-byte big-endian length + msgpack body. Payload tensors ride as
msgpack bin (see rpc/serialization.py).

Server-side generation rides the ``inference`` stream: a step item may carry
``"gen_tokens": n`` (generate n tokens on device from the step's output) and,
optionally, ``"gen_sampling"``, a dict validated by
:func:`validate_gen_sampling`:

  {"do_sample": bool, "temperature": f>0, "top_k": int>=0 (0=off),
   "top_p": f in (0,1] (1=off), "repetition_penalty": f>0 (1=off),
   "seed": int in [0, 2^31), "offset": int>=0, "context": [int token ids]?}

The PRNG contract is stateless: draw ``i`` of a stream seeded ``s`` uses
uniform(fold_in(PRNGKey(s), i)); ``offset`` is the first draw index of this
request, so a client can resume or replay the stream mid-generation.
``context`` (previously seen token ids) is only consulted when
repetition_penalty != 1.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Any, Optional

import msgpack

MAX_FRAME_BYTES = 1 << 30  # 1 GiB hard cap; large tensors stream in chunks far below this
DEFAULT_CHUNK_BYTES = 4 << 20  # split tensors into ~4 MiB stream items


async def read_frame(reader: asyncio.StreamReader) -> Any:
    header = await reader.readexactly(4)
    (length,) = struct.unpack(">I", header)
    if length > MAX_FRAME_BYTES:
        raise ValueError(f"Frame of {length} bytes exceeds the {MAX_FRAME_BYTES} byte cap")
    body = await reader.readexactly(length)
    return msgpack.unpackb(body, raw=False, strict_map_key=False)


def encode_frame(message: Any) -> bytes:
    body = msgpack.packb(message, use_bin_type=True)
    if len(body) > MAX_FRAME_BYTES:
        raise ValueError(f"Frame of {len(body)} bytes exceeds the {MAX_FRAME_BYTES} byte cap")
    return struct.pack(">I", len(body)) + body


async def write_frame(writer: asyncio.StreamWriter, message: Any, lock: asyncio.Lock) -> None:
    frame = encode_frame(message)
    async with lock:  # interleaving-safe: one frame at a time per connection
        writer.write(frame)
        await writer.drain()


def validate_gen_sampling(payload: Any) -> Optional[dict]:
    """Normalize and validate a step item's ``gen_sampling`` dict (schema in
    the module docstring). Returns a clean dict with every field present, or
    None for a None payload. Raises ValueError on anything malformed — the
    handler turns that into a protocol error before touching the device."""
    if payload is None:
        return None
    if not isinstance(payload, dict):
        raise ValueError(f"gen_sampling must be a dict, got {type(payload).__name__}")
    out = {
        "do_sample": bool(payload.get("do_sample", False)),
        "temperature": float(payload.get("temperature", 1.0)),
        "top_k": int(payload.get("top_k", 0) or 0),
        "top_p": float(payload.get("top_p", 1.0) if payload.get("top_p") is not None else 1.0),
        "repetition_penalty": float(payload.get("repetition_penalty", 1.0) or 1.0),
        "seed": int(payload.get("seed", 0)),
        "offset": int(payload.get("offset", 0)),
    }
    if not out["temperature"] > 0:
        raise ValueError(f"gen_sampling.temperature must be > 0, got {out['temperature']}")
    if out["top_k"] < 0:
        raise ValueError(f"gen_sampling.top_k must be >= 0, got {out['top_k']}")
    if not 0 < out["top_p"] <= 1:
        raise ValueError(f"gen_sampling.top_p must be in (0, 1], got {out['top_p']}")
    if not out["repetition_penalty"] > 0:
        raise ValueError(
            f"gen_sampling.repetition_penalty must be > 0, got {out['repetition_penalty']}"
        )
    if not 0 <= out["seed"] < 1 << 31:
        raise ValueError(f"gen_sampling.seed must be in [0, 2^31), got {out['seed']}")
    if out["offset"] < 0:
        raise ValueError(f"gen_sampling.offset must be >= 0, got {out['offset']}")
    context = payload.get("context")
    if context is not None:
        if not isinstance(context, (list, tuple)):
            raise ValueError("gen_sampling.context must be a list of token ids")
        out["context"] = [int(t) for t in context]
    return out
