"""Asyncio RPC client with connection multiplexing (the stub side of the wire
protocol — role of hivemind's StubBase in the reference, e.g.
TransformerConnectionHandler.get_stub at src/petals/server/handler.py).

One ``RpcClient`` owns one TCP connection; concurrent unary calls and streams
share it, matched by call id. Connection failures fail all in-flight calls —
retry/ban policy belongs to the routing layer above.
"""

from __future__ import annotations

import asyncio
import itertools
from typing import Any, AsyncIterator, Optional

from petals_tpu import chaos
from petals_tpu.data_structures import PeerID
from petals_tpu.rpc.protocol import read_frame, write_frame
from petals_tpu.rpc.server import RpcError
from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_END = object()


class StreamCall:
    """A bidirectional stream: ``send``/``end`` feed the server, iterate to read."""

    def __init__(self, client: "RpcClient", call_id: int, method: Optional[str] = None):
        self._client = client
        self._call_id = call_id
        self._method = method  # chaos-injection detail for rpc.stream_recv
        self._inbound: asyncio.Queue = asyncio.Queue()
        self._closed = False

    async def send(self, payload: Any) -> None:
        if self._closed:
            raise RpcError("Stream is closed")
        await self._client._send({"t": "sitem", "id": self._call_id, "payload": payload})

    async def end(self) -> None:
        """Half-close: no more requests will be sent."""
        await self._client._send({"t": "send", "id": self._call_id})

    async def recv(self, timeout: Optional[float] = None) -> Any:
        """Next response item; raises StopAsyncIteration at end of stream."""
        if chaos.ENABLED:
            await chaos.inject(chaos.SITE_RPC_STREAM_RECV, detail=self._method)
        item = await asyncio.wait_for(self._inbound.get(), timeout)
        if item is _END:
            self._closed = True
            raise StopAsyncIteration
        if isinstance(item, Exception):
            self._closed = True
            raise item
        return item

    def __aiter__(self) -> AsyncIterator[Any]:
        return self

    async def __anext__(self) -> Any:
        return await self.recv()

    async def cancel(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                await self._client._send({"t": "cancel", "id": self._call_id})
            except (ConnectionError, RpcError):
                pass
        self._client._streams.pop(self._call_id, None)

    def _push(self, item: Any) -> None:
        self._inbound.put_nowait(item)


class RpcClient:
    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
                 peer_id: Optional[PeerID] = None, identity=None):
        import secrets

        self._reader, self._writer = reader, writer
        self._identity = identity
        self._peer_id = identity.peer_id if identity is not None else peer_id
        self._nonce = secrets.token_bytes(16)
        self._write_lock = asyncio.Lock()
        self._call_ids = itertools.count()
        self._pending: dict = {}  # call_id -> Future (unary)
        self._streams: dict = {}  # call_id -> StreamCall
        self._closed = False
        # set ONLY once the server PROVES the id by signing our nonce with the
        # key whose hash is the id — an unauthenticated hello proves nothing
        self.remote_peer_id: Optional[PeerID] = None
        self._server_pub: Optional[bytes] = None
        self._server_nonce: Optional[bytes] = None
        self._server_claimed: Optional[PeerID] = None
        # set once the server's hello is processed (and our auth proof sent):
        # connect() waits on it so our first request never overtakes the proof
        self._handshake_done = asyncio.Event()
        # set once the server's auth frame is processed (valid or not) — TCP
        # ordering puts it right after the hello when the server will prove
        self._auth_done = asyncio.Event()
        self._loop_task = asyncio.create_task(self._read_loop())

    async def _on_server_hello(self, msg) -> None:
        self._server_pub = bytes.fromhex(msg["pub"]) if msg.get("pub") else None
        self._server_nonce = bytes.fromhex(msg["nonce"]) if msg.get("nonce") else None
        self._server_claimed = (
            PeerID.from_string(msg["peer_id"]) if msg.get("peer_id") else None
        )
        if (
            self._identity is not None
            and self._server_pub is not None
            and self._server_nonce is not None
        ):
            from petals_tpu.dht.identity import hello_challenge_message

            sig = self._identity.sign(
                hello_challenge_message(
                    self._identity.public_bytes, self._server_pub, self._server_nonce
                )
            )
            await self._send({"t": "auth", "sig": sig.hex()})
        self._handshake_done.set()

    def _on_server_auth(self, msg) -> None:
        """The server's proof: its signature over OUR public key and nonce."""
        from petals_tpu.dht import identity as ident

        try:
            if self._server_pub is None or self._identity is None:
                return
            try:
                sig = bytes.fromhex(msg.get("sig") or "")
            except ValueError:
                return
            message = ident.hello_challenge_message(
                self._server_pub, self._identity.public_bytes, self._nonce
            )
            if not ident.verify(self._server_pub, sig, message):
                return
            proven = ident.peer_id_of(self._server_pub)
            if self._server_claimed is None or proven == self._server_claimed:
                self.remote_peer_id = proven
        finally:
            self._auth_done.set()

    async def wait_authenticated(self, timeout: float = 10.0) -> Optional[PeerID]:
        """Waits for the server's identity proof (if it advertised a key) and
        returns the PROVEN peer id — None if the server never proves or the
        proof is invalid. Callers pinning a peer id (relay circuits) must
        compare against this, not the unauthenticated hello claim."""
        if self._identity is None or self._server_pub is None:
            return self.remote_peer_id
        try:
            await asyncio.wait_for(self._auth_done.wait(), timeout)
        except asyncio.TimeoutError:
            pass
        return self.remote_peer_id

    @classmethod
    async def connect(
        cls, host: str, port: int, *, peer_id: Optional[PeerID] = None,
        identity=None, timeout: float = 10.0,
    ) -> "RpcClient":
        reader, writer = await asyncio.wait_for(asyncio.open_connection(host, port), timeout)
        return await cls.from_streams(
            reader, writer, peer_id=peer_id, identity=identity, timeout=timeout
        )

    @classmethod
    async def from_streams(
        cls, reader: asyncio.StreamReader, writer: asyncio.StreamWriter, *,
        peer_id: Optional[PeerID] = None, identity=None, timeout: float = 10.0,
    ) -> "RpcClient":
        """Handshake over an already-established byte stream (direct TCP or a
        relay splice — rpc/relay.py): the hello/auth exchange is end-to-end."""
        client = cls(reader, writer, peer_id, identity)
        hello = {"t": "hello", "peer_id": client._peer_id.to_string() if client._peer_id else None}
        if identity is not None:
            hello["pub"] = identity.public_bytes.hex()
            hello["nonce"] = client._nonce.hex()
        await client._send(hello)
        try:
            await asyncio.wait_for(client._handshake_done.wait(), timeout)
        except asyncio.TimeoutError:
            await client.close()
            raise
        if client._closed:
            raise RpcError("Connection closed during handshake")
        return client

    async def _send(self, message: Any) -> None:
        if self._closed:
            raise RpcError("Client connection is closed")
        await write_frame(self._writer, message, self._write_lock)

    async def call(self, method: str, payload: Any = None, timeout: Optional[float] = None) -> Any:
        if chaos.ENABLED:
            await chaos.inject(chaos.SITE_RPC_CALL, detail=method)
        call_id = next(self._call_ids)
        future: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[call_id] = future
        try:
            await self._send({"t": "req", "id": call_id, "method": method, "payload": payload})
            return await asyncio.wait_for(future, timeout)
        except (asyncio.TimeoutError, asyncio.CancelledError):
            # tell the server to stop working on this call (best effort)
            if not self._closed:
                try:
                    await self._send({"t": "cancel", "id": call_id})
                except (ConnectionError, RpcError):
                    pass
            raise
        finally:
            self._pending.pop(call_id, None)

    async def open_stream(self, method: str) -> StreamCall:
        if chaos.ENABLED:
            await chaos.inject(chaos.SITE_RPC_STREAM, detail=method)
        call_id = next(self._call_ids)
        stream = StreamCall(self, call_id, method)
        self._streams[call_id] = stream
        await self._send({"t": "sopen", "id": call_id, "method": method})
        return stream

    async def _read_loop(self) -> None:
        error: Exception = RpcError("Connection closed")
        try:
            while True:
                msg = await read_frame(self._reader)
                kind = msg.get("t")
                if kind == "hello":
                    await self._on_server_hello(msg)
                elif kind == "auth":
                    self._on_server_auth(msg)
                elif kind == "resp":
                    call_id = msg["id"]
                    if msg.get("ok"):
                        future = self._pending.get(call_id)
                        if future is not None and not future.done():
                            future.set_result(msg.get("payload"))
                    else:
                        exc = RpcError(msg.get("error", "remote error"))
                        future = self._pending.get(call_id)
                        if future is not None and not future.done():
                            future.set_exception(exc)
                        stream = self._streams.pop(call_id, None)
                        if stream is not None:
                            stream._push(exc)
                elif kind == "sitem":
                    stream = self._streams.get(msg["id"])
                    if stream is not None:
                        stream._push(msg.get("payload"))
                elif kind == "send":
                    stream = self._streams.pop(msg["id"], None)
                    if stream is not None:
                        stream._push(_END)
                else:
                    logger.warning(f"Unknown frame kind {kind!r} from server")
        except (asyncio.IncompleteReadError, ConnectionResetError, BrokenPipeError) as e:
            error = RpcError(f"Connection lost: {type(e).__name__}")
        except asyncio.CancelledError:
            pass
        except Exception as e:
            logger.exception("Client read loop crashed")
            error = RpcError(f"Client read loop crashed: {e}")
        finally:
            self._closed = True
            # unblock connect(): a connection that died mid-handshake should
            # fail immediately (connect checks _closed), not wait out the timeout
            self._handshake_done.set()
            self._auth_done.set()
            for future in self._pending.values():
                if not future.done():
                    future.set_exception(error)
            for stream in self._streams.values():
                stream._push(error)
            self._streams.clear()

    async def close(self) -> None:
        self._closed = True
        self._loop_task.cancel()
        try:
            await self._loop_task
        except asyncio.CancelledError:
            pass
        self._writer.close()
        try:
            await self._writer.wait_closed()
        except (ConnectionError, BrokenPipeError):
            pass
