"""Asyncio RPC server: unary + bidirectional-streaming methods over the framed
msgpack protocol (the role of hivemind's ServicerBase/ConnectionHandler RPC
surface in the reference — src/petals/server/handler.py:55 serves 7 such
methods; this server hosts them all in one process).
"""

from __future__ import annotations

import asyncio
import dataclasses
import traceback
from typing import Any, AsyncIterator, Awaitable, Callable, Dict, Optional

from petals_tpu.data_structures import PeerID
from petals_tpu.rpc.protocol import read_frame, write_frame
from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_END = object()

# Per-call inbound buffer bound. A well-behaved streaming client (inference
# session) keeps at most a couple of steps in flight; a peer stuffing frames
# faster than the handler consumes would otherwise grow the queue — and server
# memory — without limit (frames can be up to MAX_FRAME_BYTES each).
MAX_INBOUND_QUEUE = 128


class RpcError(Exception):
    """Error raised on the caller when the remote handler failed."""


@dataclasses.dataclass
class RpcContext:
    local_peer_id: Optional[PeerID]
    remote_peer_id: Optional[PeerID]
    remote_addr: tuple


UnaryHandler = Callable[[Any, RpcContext], Awaitable[Any]]
StreamHandler = Callable[[AsyncIterator[Any], RpcContext], AsyncIterator[Any]]


class RpcServer:
    def __init__(
        self,
        peer_id: Optional[PeerID] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        identity=None,  # dht.identity.Identity: enables authenticated hellos
    ):
        self.identity = identity
        self.peer_id = identity.peer_id if identity is not None else peer_id
        self.host, self._requested_port = host, port
        self._unary: Dict[str, UnaryHandler] = {}
        self._stream: Dict[str, StreamHandler] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._conn_tasks: set = set()

    def add_unary_handler(self, method: str, fn: UnaryHandler) -> None:
        self._unary[method] = fn

    def add_stream_handler(self, method: str, fn: StreamHandler) -> None:
        self._stream[method] = fn

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._on_connection, self.host, self._requested_port)
        logger.debug(f"RpcServer listening on {self.listen_addr}")

    @property
    def port(self) -> int:
        assert self._server is not None, "server not started"
        return self._server.sockets[0].getsockname()[1]

    @property
    def listen_addr(self) -> str:
        return f"{self.host}:{self.port}"

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
        # Cancel live connections BEFORE wait_closed(): since py3.12 wait_closed
        # also waits for active connection handlers to finish.
        for task in list(self._conn_tasks):
            task.cancel()
        if self._conn_tasks:
            await asyncio.gather(*self._conn_tasks, return_exceptions=True)
        if self._server is not None:
            await self._server.wait_closed()

    # ------------------------------------------------------------------ connection

    async def _on_connection(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        task = asyncio.current_task()
        self._conn_tasks.add(task)
        write_lock = asyncio.Lock()
        call_tasks: Dict[int, asyncio.Task] = {}
        inbound_queues: Dict[int, asyncio.Queue] = {}
        ctx = RpcContext(
            local_peer_id=self.peer_id,
            remote_peer_id=None,
            remote_addr=writer.get_extra_info("peername") or ("?", 0),
        )
        import secrets

        our_nonce = secrets.token_bytes(16)
        client_pub: Optional[bytes] = None
        client_claimed: Optional[PeerID] = None
        try:
            hello = {"t": "hello", "peer_id": self.peer_id.to_string() if self.peer_id else None}
            if self.identity is not None:
                hello["pub"] = self.identity.public_bytes.hex()
                hello["nonce"] = our_nonce.hex()
            await write_frame(writer, hello, write_lock)
            while True:
                msg = await read_frame(reader)
                kind = msg.get("t")
                if kind == "hello":
                    # claims are recorded but remote_peer_id is set ONLY after
                    # a valid "auth" proof — hello alone cannot impersonate
                    client_pub = bytes.fromhex(msg["pub"]) if msg.get("pub") else None
                    client_claimed = (
                        PeerID.from_string(msg["peer_id"]) if msg.get("peer_id") else None
                    )
                    if (
                        self.identity is not None
                        and client_pub is not None
                        and msg.get("nonce")
                    ):
                        # prove OUR identity to the client: sign its nonce,
                        # with our own key bound into the message
                        from petals_tpu.dht.identity import hello_challenge_message

                        sig = self.identity.sign(
                            hello_challenge_message(
                                self.identity.public_bytes,
                                client_pub,
                                bytes.fromhex(msg["nonce"]),
                            )
                        )
                        await write_frame(writer, {"t": "auth", "sig": sig.hex()}, write_lock)
                elif kind == "auth":
                    from petals_tpu.dht import identity as ident

                    if self.identity is None or client_pub is None:
                        continue
                    try:
                        sig = bytes.fromhex(msg.get("sig") or "")
                    except ValueError:
                        sig = b""
                    message = ident.hello_challenge_message(
                        client_pub, self.identity.public_bytes, our_nonce
                    )
                    proven = ident.peer_id_of(client_pub)
                    if ident.verify(client_pub, sig, message) and (
                        client_claimed is None or proven == client_claimed
                    ):
                        ctx.remote_peer_id = proven
                    else:
                        logger.warning(
                            f"Rejecting peer {ctx.remote_addr}: invalid identity proof"
                        )
                        break  # close the connection
                elif kind == "req":
                    call_tasks[msg["id"]] = asyncio.create_task(
                        self._run_unary(msg, ctx, writer, write_lock, call_tasks)
                    )
                elif kind == "sopen":
                    queue: asyncio.Queue = asyncio.Queue(maxsize=MAX_INBOUND_QUEUE)
                    inbound_queues[msg["id"]] = queue
                    call_tasks[msg["id"]] = asyncio.create_task(
                        self._run_stream(msg, queue, ctx, writer, write_lock, call_tasks, inbound_queues)
                    )
                elif kind in ("sitem", "send"):
                    queue = inbound_queues.get(msg["id"])
                    if queue is not None:
                        item = _END if kind == "send" else msg.get("payload")
                        try:
                            queue.put_nowait(item)
                        except asyncio.QueueFull:
                            # The handler is MAX_INBOUND_QUEUE frames behind this
                            # peer: abusive or wedged either way. Kill the call
                            # instead of buffering its frames unboundedly.
                            logger.warning(
                                f"Inbound queue overflow on call {msg['id']} from "
                                f"{ctx.remote_addr}; cancelling the call"
                            )
                            stuck = call_tasks.get(msg["id"])
                            if stuck is not None:
                                stuck.cancel()
                            inbound_queues.pop(msg["id"], None)
                            # tell the peer: its pending recv should fail fast,
                            # not hang until its own timeout
                            await write_frame(
                                writer,
                                {
                                    "t": "resp",
                                    "id": msg["id"],
                                    "ok": False,
                                    "error": "RpcError: inbound queue overflow, call cancelled",
                                },
                                write_lock,
                            )
                elif kind == "cancel":
                    task_to_cancel = call_tasks.get(msg["id"])
                    if task_to_cancel is not None:
                        task_to_cancel.cancel()
                else:
                    logger.warning(f"Unknown frame kind {kind!r} from {ctx.remote_addr}")
        except (asyncio.IncompleteReadError, ConnectionResetError, BrokenPipeError):
            pass  # remote disconnected
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception(f"Connection loop failed for {ctx.remote_addr}")
        finally:
            for call_task in call_tasks.values():
                call_task.cancel()
            if call_tasks:
                await asyncio.gather(*call_tasks.values(), return_exceptions=True)
            writer.close()
            self._conn_tasks.discard(task)

    async def _run_unary(self, msg, ctx, writer, write_lock, call_tasks):
        call_id = msg["id"]
        try:
            handler = self._unary.get(msg.get("method"))
            if handler is None:
                raise RpcError(f"Unknown unary method {msg.get('method')!r}")
            result = await handler(msg.get("payload"), ctx)
            await write_frame(writer, {"t": "resp", "id": call_id, "ok": True, "payload": result}, write_lock)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            logger.debug(f"Unary {msg.get('method')} failed: {e}\n{traceback.format_exc()}")
            try:
                await write_frame(
                    writer, {"t": "resp", "id": call_id, "ok": False, "error": _format_error(e)}, write_lock
                )
            except (ConnectionError, RuntimeError):
                pass
        finally:
            call_tasks.pop(call_id, None)

    async def _run_stream(self, msg, queue, ctx, writer, write_lock, call_tasks, inbound_queues):
        call_id = msg["id"]

        async def request_iter():
            while True:
                item = await queue.get()
                if item is _END:
                    return
                yield item

        try:
            handler = self._stream.get(msg.get("method"))
            if handler is None:
                raise RpcError(f"Unknown stream method {msg.get('method')!r}")
            async for item in handler(request_iter(), ctx):
                await write_frame(writer, {"t": "sitem", "id": call_id, "payload": item}, write_lock)
            await write_frame(writer, {"t": "send", "id": call_id}, write_lock)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            logger.debug(f"Stream {msg.get('method')} failed: {e}\n{traceback.format_exc()}")
            try:
                await write_frame(
                    writer, {"t": "resp", "id": call_id, "ok": False, "error": _format_error(e)}, write_lock
                )
            except (ConnectionError, RuntimeError):
                pass
        finally:
            call_tasks.pop(call_id, None)
            inbound_queues.pop(call_id, None)


def _format_error(e: Exception) -> str:
    return f"{type(e).__name__}: {e}"
