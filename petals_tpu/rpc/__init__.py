from petals_tpu.rpc.client import RpcClient
from petals_tpu.rpc.serialization import (
    CompressionType,
    deserialize_array,
    serialize_array,
)
from petals_tpu.rpc.server import RpcServer, RpcError

__all__ = [
    "RpcClient",
    "RpcServer",
    "RpcError",
    "CompressionType",
    "serialize_array",
    "deserialize_array",
]
