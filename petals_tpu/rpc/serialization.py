"""Tensor (de)serialization with per-tensor compression for the wire
(counterpart of hivemind's runtime_pb2 Tensor + compression stack, used by the
reference at src/petals/client/remote_forward_backward.py:88-110).

Wire form is a msgpack-safe dict: {shape, dtype, compression, data}. Supported
compressions:
- NONE:     raw little-endian bytes of the original dtype
- FLOAT16:  cast float tensors to fp16 (reference's default for activations)
- BFLOAT16: cast float tensors to bf16 (TPU-native; bit-exact for bf16 compute)
- QINT8:    blockwise 8-bit quantization with per-block absmax scales
            (hivemind's "blockwise 8-bit" analogue; block size 1024)

bfloat16 numpy support comes from ml_dtypes (always present with jax).
"""

from __future__ import annotations

import enum
from typing import Any, Dict

import ml_dtypes
import numpy as np

BF16 = np.dtype(ml_dtypes.bfloat16)
_QBLOCK = 1024


class CompressionType(str, enum.Enum):
    NONE = "none"
    FLOAT16 = "float16"
    BFLOAT16 = "bfloat16"
    QINT8 = "qint8"


def _to_numpy(array) -> np.ndarray:
    if isinstance(array, np.ndarray):
        return array
    # jax.Array (or anything exposing __array__); jax bf16 maps to ml_dtypes.bfloat16
    return np.asarray(array)


def serialize_array(array, compression: CompressionType = CompressionType.NONE) -> Dict[str, Any]:
    arr = _to_numpy(array)
    orig_dtype = arr.dtype
    is_float = np.issubdtype(orig_dtype, np.floating) or orig_dtype == BF16

    if compression == CompressionType.FLOAT16 and is_float:
        data_arr, wire_dtype = arr.astype(np.float16), "float16"
    elif compression == CompressionType.BFLOAT16 and is_float:
        data_arr, wire_dtype = arr.astype(BF16), "bfloat16"
    elif compression == CompressionType.QINT8 and is_float:
        return _serialize_qint8(arr)
    else:
        compression = CompressionType.NONE
        data_arr, wire_dtype = arr, _dtype_name(orig_dtype)

    return {
        "shape": list(arr.shape),
        "dtype": _dtype_name(orig_dtype),
        "wire_dtype": wire_dtype,
        "compression": compression.value,
        "data": np.ascontiguousarray(data_arr).tobytes(),
    }


def deserialize_array(obj: Dict[str, Any]) -> np.ndarray:
    compression = CompressionType(obj.get("compression", "none"))
    shape = tuple(obj["shape"])
    target_dtype = _dtype_from_name(obj["dtype"])
    if compression == CompressionType.QINT8:
        return _deserialize_qint8(obj)
    wire_dtype = _dtype_from_name(obj.get("wire_dtype", obj["dtype"]))
    arr = np.frombuffer(bytearray(obj["data"]), dtype=wire_dtype).reshape(shape)
    if wire_dtype != target_dtype:
        arr = arr.astype(target_dtype)
    return arr


def _serialize_qint8(arr: np.ndarray) -> Dict[str, Any]:
    flat = np.ascontiguousarray(arr, dtype=np.float32).reshape(-1)

    from petals_tpu.native import native_qint8_quantize

    native = native_qint8_quantize(flat, _QBLOCK)  # C++ fast path (1 pass, no temps)
    if native is not None:
        q, scales = native
    else:
        pad = (-len(flat)) % _QBLOCK
        padded = np.concatenate([flat, np.zeros(pad, np.float32)]) if pad else flat
        blocks = padded.reshape(-1, _QBLOCK)
        scales = np.maximum(np.abs(blocks).max(axis=1), 1e-8).astype(np.float32)
        q = np.clip(np.round(blocks / scales[:, None] * 127.0), -127, 127).astype(np.int8)
        q = q.reshape(-1)[: len(flat)]
    return {
        "shape": list(arr.shape),
        "dtype": _dtype_name(arr.dtype),
        "wire_dtype": "int8",
        "compression": CompressionType.QINT8.value,
        "data": q.tobytes(),
        "scales": scales.tobytes(),
    }


def _deserialize_qint8(obj: Dict[str, Any]) -> np.ndarray:
    shape = tuple(obj["shape"])
    target_dtype = _dtype_from_name(obj["dtype"])
    n = int(np.prod(shape)) if shape else 1
    n_blocks = -(-n // _QBLOCK)
    data, scales_bytes = obj["data"], obj["scales"]
    # Wire data is untrusted: the native dequantizer reads scales[b] for every
    # block, so a short buffer would be an out-of-bounds heap read in C++.
    if len(data) < n:
        raise ValueError(f"qint8 data too short: {len(data)} bytes for {n} elements")
    if len(scales_bytes) != n_blocks * 4:
        raise ValueError(
            f"qint8 scales length {len(scales_bytes)} != {n_blocks * 4} "
            f"(need {n_blocks} f32 scales for {n} elements)"
        )
    q = np.frombuffer(bytearray(data), dtype=np.int8)[:n]
    scales = np.frombuffer(bytearray(scales_bytes), dtype=np.float32)

    from petals_tpu.native import native_qint8_dequantize

    flat = native_qint8_dequantize(q, scales, _QBLOCK)
    if flat is None:
        expand = np.repeat(scales, _QBLOCK)[:n]
        flat = (q.astype(np.float32) / 127.0) * expand
    return flat.reshape(shape).astype(target_dtype)


def _dtype_name(dtype) -> str:
    dtype = np.dtype(dtype)
    if dtype == BF16:
        return "bfloat16"
    return dtype.name


def _dtype_from_name(name: str):
    if name == "bfloat16":
        return BF16
    return np.dtype(name)


def serialize_arrays(arrays, compression: CompressionType = CompressionType.NONE) -> list:
    return [serialize_array(a, compression) for a in arrays]


def deserialize_arrays(objs) -> list:
    return [deserialize_array(o) for o in objs]
