"""Connection pool: one multiplexed RpcClient per remote address, created on
demand and discarded on failure (the swarm equivalent of hivemind's cached
p2p stubs)."""

from __future__ import annotations

import asyncio
from typing import Dict, Optional, Tuple

from petals_tpu.data_structures import PeerID
from petals_tpu.rpc.client import RpcClient
from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class ConnectionPool:
    def __init__(
        self,
        own_peer_id: Optional[PeerID] = None,
        connect_timeout: float = 10.0,
        identity=None,  # dht.identity.Identity: proves our peer id in hellos
    ):
        self.identity = identity
        self.own_peer_id = identity.peer_id if identity is not None else own_peer_id
        self.connect_timeout = connect_timeout
        self._clients: Dict[Tuple[str, int], RpcClient] = {}
        self._locks: Dict[Tuple[str, int], asyncio.Lock] = {}

    async def get(self, host: str, port: int) -> RpcClient:
        key = (host, port)
        lock = self._locks.setdefault(key, asyncio.Lock())
        async with lock:
            client = self._clients.get(key)
            if client is not None and not client._closed:
                return client
            client = await RpcClient.connect(
                host, port, peer_id=self.own_peer_id, identity=self.identity,
                timeout=self.connect_timeout,
            )
            self._clients[key] = client
            return client

    def invalidate(self, host: str, port: int) -> None:
        client = self._clients.pop((host, port), None)
        if client is not None:
            # close in the background: invalidate() is called from sync contexts
            asyncio.ensure_future(self._close_quietly(client))

    @staticmethod
    async def _close_quietly(client: RpcClient) -> None:
        try:
            await client.close()
        except Exception:
            pass

    async def close(self) -> None:
        clients, self._clients = list(self._clients.values()), {}
        for client in clients:
            try:
                await client.close()
            except Exception:
                pass
