"""Connection pool: one multiplexed RpcClient per remote address, created on
demand and discarded on failure (the swarm equivalent of hivemind's cached
p2p stubs)."""

from __future__ import annotations

import asyncio
from typing import Dict, Optional, Tuple

from petals_tpu.data_structures import PeerID
from petals_tpu.rpc.client import RpcClient
from petals_tpu.rpc.server import RpcError
from petals_tpu.utils.asyncio_utils import log_exception_callback
from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class ConnectionPool:
    def __init__(
        self,
        own_peer_id: Optional[PeerID] = None,
        connect_timeout: float = 10.0,
        identity=None,  # dht.identity.Identity: proves our peer id in hellos
    ):
        self.identity = identity
        self.own_peer_id = identity.peer_id if identity is not None else own_peer_id
        self.connect_timeout = connect_timeout
        self._clients: Dict[tuple, RpcClient] = {}
        self._locks: Dict[tuple, asyncio.Lock] = {}
        # strong refs to in-flight background closes (the loop holds tasks
        # weakly; an unreferenced close could be GC'd before it runs)
        self._bg_closes: set = set()

    async def get(self, host: str, port: int) -> RpcClient:
        return await self._get((host, port, None))

    async def get_addr(self, addr) -> RpcClient:
        """Connect to a PeerAddr — directly, or through its relay when the
        address is a relay circuit (addr.relayed; rpc/relay.py)."""
        target = addr.peer_id if addr.relayed else None
        return await self._get((addr.host, addr.port, target))

    async def _get(self, key: tuple) -> RpcClient:
        host, port, relay_target = key
        lock = self._locks.setdefault(key, asyncio.Lock())
        async with lock:
            client = self._clients.get(key)
            if client is not None and not client._closed:
                return client
            if relay_target is not None:
                from petals_tpu.rpc.relay import relay_dial

                if self.identity is None:
                    # without our identity the remote sends no auth proof, so a
                    # malicious relay could splice us to any registered server
                    raise RpcError("Relay circuits require an identity (mutual auth)")
                reader, writer = await relay_dial(
                    host, port, relay_target, timeout=self.connect_timeout
                )
                client = await RpcClient.from_streams(
                    reader, writer, peer_id=self.own_peer_id, identity=self.identity,
                    timeout=self.connect_timeout,
                )
                proven = await client.wait_authenticated(self.connect_timeout)
                if proven != relay_target:
                    # the relay spliced us to some OTHER (or unproven) peer
                    await client.close()
                    raise RpcError(f"Relay handed us {proven}, expected {relay_target}")
            else:
                client = await RpcClient.connect(
                    host, port, peer_id=self.own_peer_id, identity=self.identity,
                    timeout=self.connect_timeout,
                )
            self._clients[key] = client
            return client

    def invalidate(self, host: str, port: int) -> None:
        for key in [k for k in self._clients if k[0] == host and k[1] == port]:
            client = self._clients.pop(key, None)
            if client is not None:
                # close in the background: invalidate() is called from sync contexts
                task = asyncio.ensure_future(self._close_quietly(client))
                self._bg_closes.add(task)
                task.add_done_callback(self._bg_closes.discard)
                task.add_done_callback(
                    log_exception_callback(logger, "connection close")
                )

    @staticmethod
    async def _close_quietly(client: RpcClient) -> None:
        try:
            await client.close()
        except Exception:
            pass

    async def close(self) -> None:
        clients, self._clients = list(self._clients.values()), {}
        for client in clients:
            try:
                await client.close()
            except Exception:
                pass
