"""Open-loop schedule replay: thread per session, arrivals never wait.

An open-loop driver is the honest way to load-test a serving system:
closed-loop drivers (next request after the previous reply) slow down
exactly when the system does, hiding queueing collapse. Here each
:class:`~petals_tpu.traffic.generator.SessionPlan` fires at its
scheduled offset regardless of how the earlier sessions are doing — a
slow swarm accumulates concurrent sessions, like real users would.

``session_fn`` runs in the session's own thread and does the actual
client work (open a session, generate, return whatever the caller wants
recorded). Exceptions are captured per-session, never lost: a "lost
session" gate is only meaningful if every failure is accounted for.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, List, Optional, Sequence

from petals_tpu.traffic.generator import SessionPlan
from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = ["SessionResult", "run_schedule"]


@dataclasses.dataclass
class SessionResult:
    index: int
    tenant: int
    ok: bool
    value: Any = None  # whatever session_fn returned
    error: Optional[str] = None
    started_at: float = 0.0  # offset from run start (s)
    elapsed_s: float = 0.0


def run_schedule(
    plans: Sequence[SessionPlan],
    session_fn: Callable[[SessionPlan], Any],
    *,
    time_scale: float = 1.0,
    join_timeout_s: float = 300.0,
) -> List[SessionResult]:
    """Replay ``plans`` open-loop; returns one result per plan, in plan
    order. ``time_scale`` compresses the schedule (0.5 = twice as fast)
    so a 60 s "day" can run in a 30 s CI budget without changing the
    schedule itself (and hence the seeded determinism)."""
    results: List[Optional[SessionResult]] = [None] * len(plans)
    t0 = time.monotonic()

    def _one(plan: SessionPlan) -> None:
        start = time.monotonic()
        result = SessionResult(
            index=plan.index, tenant=plan.tenant, ok=False, started_at=start - t0
        )
        try:
            result.value = session_fn(plan)
            result.ok = True
        except Exception as e:  # captured per-session: the gate counts these
            result.error = repr(e)
            logger.warning(f"traffic session {plan.index} failed: {e!r}")
        result.elapsed_s = time.monotonic() - start
        results[plan.index] = result

    threads: List[threading.Thread] = []
    for plan in plans:
        target_t = t0 + plan.t * time_scale
        delay = target_t - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        thread = threading.Thread(
            target=_one, args=(plan,), name=f"traffic-{plan.index}", daemon=True
        )
        thread.start()
        threads.append(thread)

    deadline = time.monotonic() + join_timeout_s
    for thread in threads:
        thread.join(max(0.0, deadline - time.monotonic()))
    for plan, thread in zip(plans, threads):
        if thread.is_alive() and results[plan.index] is None:
            results[plan.index] = SessionResult(
                index=plan.index, tenant=plan.tenant, ok=False,
                error="timeout: session still running at join deadline",
                started_at=plan.t * time_scale,
                elapsed_s=join_timeout_s,
            )
    # every slot is filled by construction; the assert documents the invariant
    assert all(r is not None for r in results)
    return [r for r in results if r is not None]
