"""Deterministic traffic plane: seeded open-loop load for swarm tests.

:mod:`petals_tpu.traffic.generator` turns a seed into a fixed arrival
schedule — diurnal load waves (nonhomogeneous Poisson via thinning),
heavy-tailed session lengths (truncated Pareto), and an N-tenant prompt
mix with shared per-tenant prefixes (so the prefix cache sees realistic
reuse). The schedule is pure data: the same seed always yields the same
sessions, which is what lets ``benchmarks/bench_swarm_scale.py`` demand
token parity and byte-identical autoscaler journals across runs.

:mod:`petals_tpu.traffic.runner` replays a schedule OPEN-LOOP against
real client sessions (thread per session, arrivals never wait on
completions — a slow swarm gets more concurrent load, like real users).
Compose with ``PETALS_TPU_CHAOS`` to add faults under the wave.
"""

from petals_tpu.traffic.generator import SessionPlan, TrafficConfig, TrafficGenerator
from petals_tpu.traffic.runner import SessionResult, run_schedule

__all__ = [
    "SessionPlan",
    "SessionResult",
    "TrafficConfig",
    "TrafficGenerator",
    "run_schedule",
]
