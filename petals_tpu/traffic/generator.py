"""Seeded traffic generator: seed -> deterministic session schedule.

Models the shape of public-swarm load without owning production traffic:

- **Diurnal wave**: arrivals follow a nonhomogeneous Poisson process with
  rate ``base_rate * (1 + wave_amplitude * sin(2*pi*t/wave_period_s))``,
  sampled by Lewis-Shedler thinning (draw from the peak rate, keep each
  arrival with probability rate(t)/peak) — exact for any bounded rate
  function and trivially deterministic under a seeded RNG.
- **Heavy-tailed sessions**: decode lengths draw from a truncated Pareto
  (most sessions short, a few very long — the distribution that actually
  stresses lane occupancy and the swap tier).
- **N-tenant prompt mix**: each tenant owns a fixed prompt prefix (drawn
  once from the seed) plus a per-session random suffix, so the prefix
  cache sees realistic reuse and the ledger sees distinct tenants.
- **Prompt trees** (optional, ``tree_branching``): real multi-tenant
  prompts nest — a swarm-shared system prompt, a per-tenant tool
  preamble, then branching few-shot variants, then the random user turn.
  With ``tree_branching=(b0, b1, ...)`` each session walks one path
  through a per-tenant tree of content segments (level ``i`` picks among
  ``b_i`` children), so prompts share progressively shorter prefixes the
  deeper they diverge — exactly the workload a radix prefix tree exploits
  and a flat LRU thrashes on. ``tree_hot_bias`` skews path choice toward
  child 0 at every level, creating one hot lineage and a cold bushy rest.

Everything derives from one ``random.Random(seed)`` in a fixed draw
order; the schedule is pure data (no wall clock anywhere). The tree
fields draw NOTHING when disabled, so legacy seeds reproduce exactly.
"""

from __future__ import annotations

import dataclasses
import math
import random
from typing import List, Tuple

__all__ = ["SessionPlan", "TrafficConfig", "TrafficGenerator"]


@dataclasses.dataclass(frozen=True)
class SessionPlan:
    """One scheduled session: arrive at ``t`` (seconds from run start),
    send ``prompt``, decode ``new_tokens`` greedily."""

    index: int
    t: float
    tenant: int
    prompt: Tuple[int, ...]  # token ids
    new_tokens: int
    path: Tuple[int, ...] = ()  # branch chosen at each tree level (tree mode)
    storm: bool = False  # arrived via the prefill_storm overlay


@dataclasses.dataclass(frozen=True)
class TrafficConfig:
    seed: int = 0
    duration_s: float = 60.0
    base_rate: float = 0.5  # mean arrivals/s at wave midline
    wave_amplitude: float = 0.8  # 0 = flat, 1 = rate swings to zero at trough
    wave_period_s: float = 60.0  # one "day" of the diurnal cycle
    tenants: int = 3
    prompt_prefix_len: int = 4  # shared per-tenant prefix (prefix-cache reuse)
    prompt_suffix_len: int = 3  # per-session random tail
    vocab_size: int = 1000
    min_new_tokens: int = 2  # Pareto x_m (scale)
    max_new_tokens: int = 16  # truncation cap (keeps CPU benches bounded)
    pareto_alpha: float = 1.5  # tail index; <2 = heavy tail, infinite variance
    # prompt trees: () keeps flat prompts (and the legacy RNG stream)
    shared_prefix_len: int = 0  # swarm-shared system prompt before the tenant prefix
    tree_branching: Tuple[int, ...] = ()  # children per level of the per-tenant tree
    tree_segment_len: int = 0  # tokens per tree-node segment
    tree_hot_bias: float = 0.0  # P(child 0) at each level; rest uniform
    # prefill_storm overlay (disaggregated-serving stress): a second seeded
    # arrival process of BURSTS of heavy-tailed LONG prompts with short
    # decodes — the workload that floods prefill lanes while light decode
    # traffic keeps flowing. ``storm_rate=0`` disables the overlay and
    # draws NOTHING from the RNG, so legacy seeds reproduce byte-identically.
    storm_rate: float = 0.0  # mean burst arrivals/s inside the storm window
    storm_burst: int = 4  # sessions per burst epoch
    storm_start_frac: float = 0.25  # storm window as fractions of duration_s
    storm_end_frac: float = 0.75
    storm_prompt_len: int = 64  # Pareto x_m for the storm prompt length
    storm_prompt_max: int = 256  # truncation cap
    storm_prompt_alpha: float = 1.2  # tail index (heavier than the decode tail)
    storm_new_tokens: int = 4  # short decode: these sessions are prefill-bound

    def __post_init__(self):
        if not 0.0 <= self.wave_amplitude <= 1.0:
            raise ValueError("wave_amplitude must be in [0, 1]")
        if self.base_rate <= 0 or self.duration_s <= 0:
            raise ValueError("base_rate and duration_s must be positive")
        if self.tenants < 1:
            raise ValueError("need at least one tenant")
        if not 1 <= self.min_new_tokens <= self.max_new_tokens:
            raise ValueError("need 1 <= min_new_tokens <= max_new_tokens")
        if self.tree_branching:
            if any(b < 1 for b in self.tree_branching):
                raise ValueError("tree_branching factors must be >= 1")
            if self.tree_segment_len < 1:
                raise ValueError("tree_branching requires tree_segment_len >= 1")
        if not 0.0 <= self.tree_hot_bias <= 1.0:
            raise ValueError("tree_hot_bias must be in [0, 1]")
        if self.shared_prefix_len < 0:
            raise ValueError("shared_prefix_len must be >= 0")
        if self.storm_rate < 0:
            raise ValueError("storm_rate must be >= 0")
        if self.storm_rate > 0:
            if not 0.0 <= self.storm_start_frac < self.storm_end_frac <= 1.0:
                raise ValueError("need 0 <= storm_start_frac < storm_end_frac <= 1")
            if self.storm_burst < 1:
                raise ValueError("storm_burst must be >= 1")
            if not 1 <= self.storm_prompt_len <= self.storm_prompt_max:
                raise ValueError("need 1 <= storm_prompt_len <= storm_prompt_max")
            if self.storm_new_tokens < 1:
                raise ValueError("storm_new_tokens must be >= 1")


class TrafficGenerator:
    def __init__(self, config: TrafficConfig):
        self.config = config

    def rate_at(self, t: float) -> float:
        cfg = self.config
        return cfg.base_rate * (
            1.0 + cfg.wave_amplitude * math.sin(2.0 * math.pi * t / cfg.wave_period_s)
        )

    def schedule(self) -> List[SessionPlan]:
        """The full deterministic schedule for ``duration_s`` seconds."""
        cfg = self.config
        rng = random.Random(cfg.seed)
        # draw order is load-bearing: shared root, then tenant prefixes, then
        # tree segments (tenant-major, depth-first), then the arrival loop —
        # and the tree draws happen ONLY in tree mode, so flat-config seeds
        # keep producing the schedules they always have
        shared = tuple(
            rng.randrange(1, cfg.vocab_size) for _ in range(cfg.shared_prefix_len)
        )
        prefixes = [
            tuple(rng.randrange(1, cfg.vocab_size) for _ in range(cfg.prompt_prefix_len))
            for _ in range(cfg.tenants)
        ]
        trees = [self._draw_tree(rng) for _ in range(cfg.tenants)]
        peak = cfg.base_rate * (1.0 + cfg.wave_amplitude)
        plans: List[SessionPlan] = []
        t = 0.0
        while True:
            # thinning: homogeneous candidate stream at the peak rate...
            t += rng.expovariate(peak)
            if t >= cfg.duration_s:
                break
            # ...accepted with probability rate(t)/peak (draw unconditionally
            # so the RNG stream — and thus the schedule — is reproducible)
            if rng.random() >= self.rate_at(t) / peak:
                continue
            tenant = rng.randrange(cfg.tenants)
            path: Tuple[int, ...] = ()
            tree_tokens: Tuple[int, ...] = ()
            if cfg.tree_branching:
                path = self._draw_path(rng)
                nodes = trees[tenant]
                for depth in range(1, len(path) + 1):
                    tree_tokens += nodes[path[:depth]]
            suffix = tuple(
                rng.randrange(1, cfg.vocab_size) for _ in range(cfg.prompt_suffix_len)
            )
            # truncated Pareto via inverse CDF: x_m * (1-u)^(-1/alpha)
            u = rng.random()
            length = int(cfg.min_new_tokens * (1.0 - u) ** (-1.0 / cfg.pareto_alpha))
            new_tokens = max(cfg.min_new_tokens, min(cfg.max_new_tokens, length))
            plans.append(
                SessionPlan(
                    index=len(plans),
                    t=t,
                    tenant=tenant,
                    prompt=shared + prefixes[tenant] + tree_tokens + suffix,
                    new_tokens=new_tokens,
                    path=path,
                )
            )
        # prefill_storm overlay draws strictly AFTER every legacy draw (and
        # only when enabled), so the legacy portion of the stream — and thus
        # disabled-storm schedules — never shifts
        storm_plans = self._storm_overlay(rng)
        if not storm_plans:
            return plans
        # stable merge by arrival time (legacy plan wins a tie), reindexed
        merged = sorted(plans + storm_plans, key=lambda p: p.t)
        return [dataclasses.replace(p, index=i) for i, p in enumerate(merged)]

    def _storm_overlay(self, rng: random.Random) -> List[SessionPlan]:
        """Burst arrivals of heavy-tailed long prompts inside the storm
        window: burst epochs are a homogeneous Poisson stream at
        ``storm_rate``; each epoch lands ``storm_burst`` sessions at once
        (the thundering-herd shape that queues prefill lanes)."""
        cfg = self.config
        if cfg.storm_rate <= 0:
            return []
        t0 = cfg.storm_start_frac * cfg.duration_s
        t1 = cfg.storm_end_frac * cfg.duration_s
        plans: List[SessionPlan] = []
        t = t0
        while True:
            t += rng.expovariate(cfg.storm_rate)
            if t >= t1:
                break
            for _ in range(cfg.storm_burst):
                tenant = rng.randrange(cfg.tenants)
                # truncated Pareto prompt length (same inverse-CDF form as
                # the decode-length draw, scaled to prompt tokens)
                u = rng.random()
                length = int(
                    cfg.storm_prompt_len * (1.0 - u) ** (-1.0 / cfg.storm_prompt_alpha)
                )
                plen = max(cfg.storm_prompt_len, min(cfg.storm_prompt_max, length))
                prompt = tuple(
                    rng.randrange(1, cfg.vocab_size) for _ in range(plen)
                )
                plans.append(
                    SessionPlan(
                        index=len(plans),
                        t=t,
                        tenant=tenant,
                        prompt=prompt,
                        new_tokens=cfg.storm_new_tokens,
                        storm=True,
                    )
                )
        return plans

    def _draw_tree(self, rng: random.Random) -> dict:
        """One tenant's content tree: ``{path: segment_tokens}`` for every
        node, drawn depth-first child-major so the layout (and thus every
        prompt) is a pure function of the seed."""
        cfg = self.config
        nodes: dict = {}

        def expand(path: Tuple[int, ...]) -> None:
            level = len(path)
            if level == len(cfg.tree_branching):
                return
            for b in range(cfg.tree_branching[level]):
                child = path + (b,)
                nodes[child] = tuple(
                    rng.randrange(1, cfg.vocab_size)
                    for _ in range(cfg.tree_segment_len)
                )
                expand(child)

        if cfg.tree_branching:
            expand(())
        return nodes

    def _draw_path(self, rng: random.Random) -> Tuple[int, ...]:
        """One root-to-leaf walk. ``tree_hot_bias`` concentrates mass on
        child 0 at every level: bias 0 is uniform, bias 1 always takes the
        hot lineage — the knob that turns one subtree hot and the rest
        into cache-thrashing cold bulk."""
        cfg = self.config
        path = []
        for branching in cfg.tree_branching:
            if branching == 1:
                path.append(0)
                continue
            if rng.random() < cfg.tree_hot_bias:
                path.append(0)
            else:
                path.append(rng.randrange(branching))
        return tuple(path)
