"""Swarm chaos harness: deterministic fault injection at named sites.

Gated by ``PETALS_TPU_CHAOS`` (or programmatic :func:`configure`); see
:mod:`petals_tpu.chaos.plane` for the spec grammar and site list.
"""

from petals_tpu.chaos import plane as _plane_mod
from petals_tpu.chaos.plane import (
    ACTIONS,
    MAX_LOG,
    SITES,
    SITE_ANNOUNCE,
    SITE_DHT_LOOKUP,
    SITE_HANDLER_STEP,
    SITE_HANDOFF_PUSH,
    SITE_INTEGRITY_CORRUPT,
    SITE_MIGRATE_PUSH,
    SITE_RPC_CALL,
    SITE_RPC_STREAM,
    SITE_RPC_STREAM_RECV,
    SITE_SWAP_RESERVE,
    ChaosInjected,
    ChaosPlane,
    ChaosRule,
    configure,
    corrupt_array,
    disable,
    fire,
    get_plane,
    inject,
    parse_spec,
)

def __getattr__(name):
    # `ENABLED` is mutable state on the plane module (configure()/disable()
    # flip it); a from-import here would freeze the armed/disarmed snapshot
    # taken at package import, so delegate the read instead. Call sites do
    # `chaos.ENABLED` on this package and always see the live value.
    if name == "ENABLED":
        return _plane_mod.ENABLED
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "ACTIONS",
    "ENABLED",
    "MAX_LOG",
    "SITES",
    "SITE_ANNOUNCE",
    "SITE_DHT_LOOKUP",
    "SITE_HANDLER_STEP",
    "SITE_HANDOFF_PUSH",
    "SITE_INTEGRITY_CORRUPT",
    "SITE_MIGRATE_PUSH",
    "SITE_RPC_CALL",
    "SITE_RPC_STREAM",
    "SITE_RPC_STREAM_RECV",
    "SITE_SWAP_RESERVE",
    "ChaosInjected",
    "ChaosPlane",
    "ChaosRule",
    "configure",
    "corrupt_array",
    "disable",
    "fire",
    "get_plane",
    "inject",
    "parse_spec",
]
