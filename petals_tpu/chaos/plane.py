"""Deterministic fault-injection plane for swarm chaos testing.

Petals' promise is serving on an unreliable public swarm; hand-picked
failure tests only exercise the failure modes someone thought of. This
plane injects faults at NAMED SITES wired into the production code paths
— RPC calls, mid-stream receives, the handler's step boundary, the
migration push, DHT announces and lookups, the swap-pool budget — under
a seeded RNG, so a chaos run is
reproducible: the same seed and call order yields the same fault
sequence. It drives the ``-m chaos`` test lane and
``benchmarks/bench_churn.py``.

Zero overhead when disabled: every call site guards with
``if chaos.ENABLED:`` (a module attribute read) before touching the
plane, and ``ENABLED`` is False unless ``PETALS_TPU_CHAOS`` is set or a
test calls :func:`configure`.

Env spec (``PETALS_TPU_CHAOS``): semicolon-separated tokens, e.g.::

    PETALS_TPU_CHAOS="seed=42;rpc.call:drop:0.1;handler.step:delay:0.2:0.05"

- ``seed=N`` seeds the RNG (default 0).
- ``site:action[:p[:delay_s[:max_count]]]`` adds a rule: at ``site``,
  with probability ``p`` (default 1.0), apply ``action`` — ``drop`` /
  ``refuse`` raise :class:`ChaosInjected`, ``delay`` sleeps ``delay_s``
  seconds, ``kill`` invokes the registered kill callback (an in-process
  stand-in for a mid-step server death) then raises. ``max_count``
  bounds how many times the rule may fire.

A malformed spec raises at import — a typo'd chaos run silently testing
nothing would be worse than a crash.
"""

from __future__ import annotations

import asyncio
import dataclasses
import random
from typing import Callable, List, Optional, Sequence

from petals_tpu.analysis.sanitizer import make_thread_lock
from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# Named injection sites. Static, code-defined strings: they label the
# petals_chaos_injections_total metric (bounded cardinality) and the
# chaos log, and typos in a rule's site are rejected at parse time.
SITE_RPC_CALL = "rpc.call"  # client unary call (detail: method name)
SITE_RPC_STREAM = "rpc.stream_open"  # client stream open (detail: method)
SITE_RPC_STREAM_RECV = "rpc.stream_recv"  # client mid-stream receive (detail: method)
SITE_HANDLER_STEP = "handler.step"  # server inference-step boundary
SITE_MIGRATE_PUSH = "migrate.push"  # server->server session_migrate push
SITE_HANDOFF_PUSH = "handoff.push"  # prefill->decode KV handoff push (disagg)
SITE_ANNOUNCE = "dht.announce"  # server's periodic DHT announce
SITE_DHT_LOOKUP = "dht.lookup"  # client route discovery (module-info fetch)
SITE_SWAP_RESERVE = "swap.reserve"  # host swap-pool budget reservation
SITE_INTEGRITY_CORRUPT = "integrity.corrupt"  # server activation corruption (detail: peer/session)

SITES = (
    SITE_RPC_CALL,
    SITE_RPC_STREAM,
    SITE_RPC_STREAM_RECV,
    SITE_HANDLER_STEP,
    SITE_MIGRATE_PUSH,
    SITE_HANDOFF_PUSH,
    SITE_ANNOUNCE,
    SITE_DHT_LOOKUP,
    SITE_SWAP_RESERVE,
    SITE_INTEGRITY_CORRUPT,
)

ACTIONS = ("drop", "delay", "refuse", "kill", "corrupt")

MAX_LOG = 1024  # bounded injection log (tests assert against it)


class ChaosInjected(RuntimeError):
    """A fault injected by the chaos plane (drop/refuse/kill)."""


@dataclasses.dataclass
class ChaosRule:
    """One fault rule: at ``site``, with probability ``p``, do ``action``.

    ``match`` (programmatic only) restricts the rule to arrivals whose
    detail string contains it — e.g. only ``ptu.push`` RPC calls.
    ``max_count`` caps total firings; ``count`` tracks them."""

    site: str
    action: str
    p: float = 1.0
    delay_s: float = 0.0
    match: Optional[str] = None
    max_count: Optional[int] = None
    count: int = 0

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(f"unknown chaos site {self.site!r} (known: {SITES})")
        if self.action not in ACTIONS:
            raise ValueError(f"unknown chaos action {self.action!r} (known: {ACTIONS})")
        if not 0.0 <= self.p <= 1.0:
            raise ValueError(f"chaos probability must be in [0, 1], got {self.p}")
        if self.delay_s < 0:
            raise ValueError(f"chaos delay must be >= 0, got {self.delay_s}")


class ChaosPlane:
    """Seeded rule engine. One shared RNG consumes a draw per matching
    arrival, so a fixed seed + fixed call order reproduces the same fault
    sequence (concurrent swarms interleave arrivals nondeterministically;
    tests that need exactness keep the perturbed path single-threaded)."""

    def __init__(
        self,
        seed: int = 0,
        rules: Sequence[ChaosRule] = (),
        kill_callback: Optional[Callable[[str, Optional[str]], None]] = None,
    ):
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self.rules: List[ChaosRule] = list(rules)
        self.kill_callback = kill_callback
        self._lock = make_thread_lock("chaos.plane")
        self.log: List[dict] = []  # fired injections, bounded to MAX_LOG

    def decide(self, site: str, detail: Optional[str] = None) -> Optional[ChaosRule]:
        """One arrival at ``site``: the first matching rule that passes its
        probability draw fires (and is logged + counted); None otherwise."""
        with self._lock:
            for rule in self.rules:
                if rule.site != site:
                    continue
                if rule.match is not None and (
                    detail is None or rule.match not in str(detail)
                ):
                    continue
                if rule.max_count is not None and rule.count >= rule.max_count:
                    continue
                if rule.p < 1.0 and self._rng.random() >= rule.p:
                    continue
                rule.count += 1
                if len(self.log) < MAX_LOG:
                    self.log.append(
                        {"site": site, "action": rule.action, "detail": detail}
                    )
                from petals_tpu.telemetry import instruments as tm

                tm.CHAOS_INJECTIONS.labels(site=site, action=rule.action).inc()
                return rule
        return None

    def fired(self, site: Optional[str] = None) -> List[dict]:
        with self._lock:
            entries = list(self.log)
        if site is not None:
            entries = [e for e in entries if e["site"] == site]
        return entries


# ----------------------------------------------------------------- module API
#
# Call sites read `ENABLED` first (one attribute load — the disabled-path
# cost), then go through inject()/fire(). configure()/disable() swap the
# module-level plane; the env spec arms it at import time.

ENABLED: bool = False
_plane: Optional[ChaosPlane] = None


def configure(
    seed: int = 0,
    rules: Sequence[ChaosRule] = (),
    kill_callback: Optional[Callable[[str, Optional[str]], None]] = None,
) -> ChaosPlane:
    """Arm the chaos plane (tests/benchmarks call this programmatically)."""
    global _plane, ENABLED
    _plane = ChaosPlane(seed=seed, rules=rules, kill_callback=kill_callback)
    ENABLED = True
    logger.warning(
        f"CHAOS PLANE ARMED (seed={seed}, {len(_plane.rules)} rule(s)) — "
        "faults will be injected into production code paths"
    )
    return _plane


def disable() -> None:
    global _plane, ENABLED
    _plane = None
    ENABLED = False


def get_plane() -> Optional[ChaosPlane]:
    return _plane


def fire(site: str, detail: Optional[str] = None) -> Optional[str]:
    """Synchronous decision: the action name that fired at ``site``, or
    None. For sync sites that interpret the action themselves —
    ``swap.reserve`` treats any firing as a budget refusal, and
    ``dht.announce`` treats any firing as a lost announce."""
    plane = _plane
    if plane is None:
        return None
    rule = plane.decide(site, detail)
    return rule.action if rule is not None else None


async def inject(site: str, detail: Optional[str] = None) -> None:
    """Async injection with full action semantics: ``delay`` sleeps,
    ``drop``/``refuse`` raise :class:`ChaosInjected`, ``kill`` invokes the
    plane's kill callback (in-process stand-in for a server death) and
    then raises."""
    plane = _plane
    if plane is None:
        return
    rule = plane.decide(site, detail)
    if rule is None:
        return
    if rule.action == "delay":
        await asyncio.sleep(rule.delay_s)
        return
    if rule.action == "kill" and plane.kill_callback is not None:
        plane.kill_callback(site, detail)
    raise ChaosInjected(f"chaos[{site}]: {rule.action} ({detail or 'no detail'})")


def corrupt_array(arr, site_seed: int, position: int = 0):
    """Seeded activation corruption for ``integrity.corrupt``: perturb the
    LAST token row of ``arr [batch, seq, hidden]`` by sign-flipping a
    deterministic subset of components — the in-process stand-in for a
    faulty/malicious replica returning plausible-but-wrong activations
    (magnitudes stay realistic, so nothing downstream NaNs or clips; only
    the fingerprint plane can tell). Deterministic in ``(plane seed,
    site_seed, position)`` so a chaos run reproduces bit-for-bit."""
    import numpy as np

    plane = _plane
    base = plane.seed if plane is not None else 0
    rng = random.Random((base << 20) ^ (int(site_seed) & 0xFFFFF) ^ int(position))
    out = np.array(arr, copy=True)
    row = out[0, -1, :]
    n_flip = max(1, row.shape[0] // 8)
    idx = rng.sample(range(row.shape[0]), n_flip)
    row[idx] = -row[idx]
    out[0, -1, :] = row
    return out


def parse_spec(spec: str) -> tuple:
    """Parse a ``PETALS_TPU_CHAOS`` spec into ``(seed, rules)``."""
    seed = 0
    rules: List[ChaosRule] = []
    for token in spec.split(";"):
        token = token.strip()
        if not token:
            continue
        if token.startswith("seed="):
            seed = int(token[len("seed="):])
            continue
        parts = token.split(":")
        if len(parts) < 2 or len(parts) > 5:
            raise ValueError(
                f"bad chaos rule {token!r}: want site:action[:p[:delay_s[:max_count]]]"
            )
        site, action = parts[0], parts[1]
        p = float(parts[2]) if len(parts) > 2 and parts[2] != "" else 1.0
        delay_s = float(parts[3]) if len(parts) > 3 and parts[3] != "" else 0.0
        max_count = int(parts[4]) if len(parts) > 4 and parts[4] != "" else None
        rules.append(
            ChaosRule(site=site, action=action, p=p, delay_s=delay_s, max_count=max_count)
        )
    return seed, rules


def _arm_from_env() -> None:
    import os

    spec = os.environ.get("PETALS_TPU_CHAOS")
    if not spec:
        return
    seed, rules = parse_spec(spec)  # malformed spec raises: fail loudly
    configure(seed=seed, rules=rules)


_arm_from_env()

__all__ = [
    "ACTIONS",
    "ENABLED",
    "MAX_LOG",
    "SITES",
    "SITE_ANNOUNCE",
    "SITE_DHT_LOOKUP",
    "SITE_HANDLER_STEP",
    "SITE_HANDOFF_PUSH",
    "SITE_INTEGRITY_CORRUPT",
    "SITE_MIGRATE_PUSH",
    "SITE_RPC_CALL",
    "SITE_RPC_STREAM",
    "SITE_RPC_STREAM_RECV",
    "SITE_SWAP_RESERVE",
    "ChaosInjected",
    "ChaosPlane",
    "ChaosRule",
    "configure",
    "corrupt_array",
    "disable",
    "fire",
    "get_plane",
    "inject",
    "parse_spec",
]
