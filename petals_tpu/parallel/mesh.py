"""Device-mesh helpers: the ICI-native replacement for the reference's
intra-server `tensor_parallel` package (SURVEY.md §2.2 — torch TP over NCCL
becomes jax.sharding over a Mesh; XLA inserts the collectives).

Serving meshes are 1-D ("tp",) over the chips of one server's slice. Training
dry-runs use richer meshes (dp/tp/sp) — see __graft_entry__.dryrun_multichip.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(
    axis_sizes: Sequence[int],
    axis_names: Sequence[str],
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n = int(np.prod(axis_sizes))
    if n > len(devices):
        raise ValueError(f"Mesh of {axis_sizes} needs {n} devices, have {len(devices)}")
    grid = np.asarray(devices[:n]).reshape(tuple(axis_sizes))
    return Mesh(grid, tuple(axis_names))


def tp_mesh(
    num_devices: Optional[int] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """1-D tensor-parallel mesh over this host's chips (the intra-server
    mesh). ``devices`` overrides the pool (e.g. jax.local_devices() when a
    surviving multi-host leader re-forms locally — jax.devices() would still
    list the dead members' chips)."""
    devices = list(devices if devices is not None else jax.devices())
    num_devices = num_devices or len(devices)
    return make_mesh((num_devices,), ("tp",), devices=devices)


def serving_mesh(
    num_tp: int = 1,
    num_sp: int = 1,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """2-D intra-server mesh: heads/FFN sharded over "tp", long-context
    activations sharded over "sp" (ring attention on the stateless
    forward/backward path)."""
    return make_mesh((num_tp, num_sp), ("tp", "sp"), devices=devices)
