"""SPMD pipeline parallelism: a real microbatch schedule for the "pp" mesh axis.

The reference's pipeline parallelism is inter-server (client-routed spans,
SURVEY.md §2.2) and has no intra-step schedule; round 1 of this build sharded
the stacked layer axis over "pp" inside one jit, which places weights but
leaves every stage idle while the `lax.scan` carry walks through it. This
module implements the real thing, TPU-style: a GPipe/1F1B-family microbatch
schedule expressed in pure SPMD so XLA compiles stage compute and the
stage-to-stage hop into overlapping device programs:

- Stage s holds layers [s*L/S, (s+1)*L/S) — the stacked layer axis is
  reshaped to [S, L/S, ...] and sharded over "pp" on the stage axis.
- Each schedule step runs ``vmap(stage_fn)`` over the stage axis: with the
  stage axis sharded, GSPMD turns the vmap into "every stage computes its
  resident microbatch simultaneously" — the overlap 1F1B exists for.
- Activations advance one stage per step via ``jnp.roll`` on the pp-sharded
  stage axis, which XLA lowers to a single ICI ``CollectivePermute``.
- After M microbatches + (S-1) bubble steps, outputs are collected from the
  last stage. Differentiating through the schedule replays it in reverse
  (the cotangent CollectivePermutes run backward) — pipelined backward for
  free, with activations rematerialized by XLA where cheaper.

Bubble fraction is the textbook (S-1)/(M+S-1); pick M >= S for efficiency.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def microbatch_split(x: jnp.ndarray, num_microbatches: int) -> jnp.ndarray:
    """[batch, ...] -> [M, batch/M, ...] (M must divide batch)."""
    batch = x.shape[0]
    if batch % num_microbatches:
        raise ValueError(f"batch {batch} does not divide into {num_microbatches} microbatches")
    return x.reshape(num_microbatches, batch // num_microbatches, *x.shape[1:])


def pipeline_apply(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    params: Any,
    x: jnp.ndarray,
    *,
    mesh: Mesh,
    axis: str = "pp",
    microbatch_spec: P | None = None,
    param_specs: Any | None = None,
) -> jnp.ndarray:
    """Run microbatches through a pipeline of stages sharded over ``axis``.

    Args:
      stage_fn: ``(stage_params, h) -> h`` applying one stage's layer slice
        (typically a ``lax.scan`` over the [L/S, ...] leaves it receives).
      params: pytree whose leaves lead with the stacked layer axis [L, ...];
        the axis size S must divide L. Leaves are reshaped to [S, L/S, ...]
        and constrained to shard the stage axis over ``axis``.
      x: microbatched input [M, ...single-microbatch shape...].
      mesh: the device mesh (entered or passed; used for constraints).
      axis: mesh axis name for pipeline stages.
      microbatch_spec: PartitionSpec for one microbatch's value (e.g.
        ``P("dp", "sp", None)``); used to keep activations sharded while they
        move through the schedule.
      param_specs: optional pytree of PartitionSpecs matching the STACKED
        leaves (first entry = the layer axis, e.g. ``P("pp", "tp", None)``);
        non-layer entries are preserved so tensor-parallel weight shardings
        survive the stage reshape. Default: stage axis only, rest replicated.

    Returns: y [M, ...] — stage_fn applied over all L layers, microbatched.
    """
    num_stages = mesh.shape[axis]
    num_micro = x.shape[0]
    mb_spec = tuple(microbatch_spec) if microbatch_spec is not None else (None,) * (x.ndim - 1)

    def stack_stages(p: jnp.ndarray, spec: P | None) -> jnp.ndarray:
        n_layers = p.shape[0]
        if n_layers % num_stages:
            raise ValueError(f"layer stack {n_layers} does not divide {num_stages} stages")
        staged = p.reshape(num_stages, n_layers // num_stages, *p.shape[1:])
        rest = tuple(spec)[1:] if spec is not None else (None,) * (p.ndim - 1)
        rest = rest + (None,) * (p.ndim - 1 - len(rest))
        return jax.lax.with_sharding_constraint(
            staged, NamedSharding(mesh, P(axis, None, *rest))
        )

    leaves, treedef = jax.tree_util.tree_flatten(params)
    if param_specs is None:
        spec_leaves = [None] * len(leaves)
    else:
        spec_leaves = jax.tree_util.tree_leaves(
            param_specs, is_leaf=lambda s: s is None or isinstance(s, P)
        )
        if len(spec_leaves) != len(leaves):
            raise ValueError("param_specs structure does not match params")
    params_staged = jax.tree_util.tree_unflatten(
        treedef, [stack_stages(p, s) for p, s in zip(leaves, spec_leaves)]
    )
    if num_stages == 1:
        return jax.vmap(lambda mb: stage_fn(jax.tree_util.tree_map(lambda p: p[0], params_staged), mb))(x)

    buf_sharding = NamedSharding(mesh, P(axis, *mb_spec))
    total_steps = num_micro + num_stages - 1

    buf0 = jax.lax.with_sharding_constraint(
        jnp.zeros((num_stages, *x.shape[1:]), x.dtype), buf_sharding
    )
    out0 = jnp.zeros((total_steps, *x.shape[1:]), x.dtype)

    def step(carry, t):
        buf, out = carry
        # feed the next microbatch into stage 0 (clamped re-reads past the end
        # are never collected, and their cotangents are zero)
        x_t = jax.lax.dynamic_index_in_dim(
            x, jnp.clip(t, 0, num_micro - 1), 0, keepdims=False
        )
        buf = jax.lax.dynamic_update_index_in_dim(buf, x_t.astype(buf.dtype), 0, 0)
        buf = jax.lax.with_sharding_constraint(buf, buf_sharding)
        y = jax.vmap(stage_fn)(params_staged, buf)
        y = jax.lax.with_sharding_constraint(y, buf_sharding)
        # the last stage's result is microbatch t-(S-1); collect every step and
        # slice off the warm-up garbage at the end
        out = jax.lax.dynamic_update_index_in_dim(out, y[-1], t, 0)
        # advance the pipeline: stage s+1's next input is stage s's output
        # (roll on the pp-sharded stage axis == ICI collective-permute)
        buf = jnp.roll(y, 1, axis=0)
        return (buf, out), None

    (_, out), _ = jax.lax.scan(step, (buf0, out0), jnp.arange(total_steps))
    return out[num_stages - 1 :]
