"""Multi-host serving: one SPAN-server spanning several hosts' chips.

The reference cannot express this at all — its tensor parallelism is bounded
by one machine's GPUs (`tensor_parallel` over local CUDA devices, reference
convert_block.py:118-135). On a v5e-64 (16 hosts x 4 chips) a 405B block
sharded past 4 chips needs tensor parallelism ACROSS hosts, which in JAX is
multi-controller SPMD: every participating process runs the same jitted
computation over a global mesh, with XLA collectives riding ICI/DCN.

Architecture (TPU-first; there is no torch/NCCL analogue to port):

- ``init_multihost`` wraps ``jax.distributed.initialize`` — afterwards
  ``jax.devices()`` spans all hosts and a Mesh built from it shards params and
  KV caches across every chip of every host.
- Only the LEADER (process 0) runs the swarm surface (DHT, RPC handler,
  scheduler, memory-cache budgeting). Workers (``cli/run_worker.py``) build
  the identical backend from the identical checkpoint and sit in
  ``LockstepWorker.run``: multi-controller JAX requires every process to enter
  every jitted computation together, so each leader-side compute call
  broadcasts a compact descriptor (``multihost_utils.broadcast_one_to_all``)
  and the workers invoke the same backend method on their shards.
- KV buffers are mirrored by HANDLE: the leader's MemoryCache reserves
  handles/budget as usual but broadcasts ALLOC/FREE (``LockstepMemoryCache``),
  and each process materializes its own shards of the same logical buffer.
  Only handles and replicated activations cross the control plane — KV shards
  never move between hosts outside XLA collectives.
- Array creation (zeros, device_put of identical host values) is process-local
  in multi-controller JAX; the actual cross-host traffic is the in-program
  collectives (psum/all_gather over the tp axis) plus the tiny control
  broadcasts.

v2 (this round): per-request LoRA adapters cross the control plane as indices
into the sorted adapter list (leader and workers host identical sets); session
KV export/import runs as an in-program all_gather every process enters
(OP_EXPORT_KV) and a broadcast prefix every process shards (OP_IMPORT_KV) —
re-enabling migration, drain-parking and route upgrades for multi-host spans;
auto-throughput probes the REAL lockstep backend (server._measure_multihost_throughput)
instead of a throwaway; and a dead worker degrades the group FAST
(_degrade_on_failure) instead of hanging every subsequent collective — the
leader stops serving with clear errors, clients fail over, and the group is
re-formed by restarting its processes (XLA bakes the mesh into every compiled
program and shards params across member processes, so a worker hot-swap is a
rebuild by construction; elasticity lives at the swarm layer, where the unit
of failure is the span server — same as the reference's whole-server process).

The prefix cache (server/prefix_cache.py) rides the same import/export ops,
so shared-prompt prefills skip compute on multi-host spans too.

v3 (round 5): continuous batching composes with lockstep. The lane pool is
one more mirrored allocation (OP_ALLOC's 5-slot shape covers it — the batch
slot carries n_lanes); the batched decode step broadcasts hidden + the
per-lane position vector (OP_BATCHED_DECODE); non-batchable work checks a
lane out into a synthetic negative-handle mirror (OP_LANE_EXTRACT), runs the
ordinary lockstep session ops against it, and checks it back in
(OP_LANE_INSERT) — so chunked prefill, prefix-cache seeding/storing, and KV
import/export all work on pooled multi-host sessions.

Sequence parallelism crosses hosts too (round 5): the serving mesh can be
(tp, sp) over the global device set — the q-sharded cached prefill and the
stateless path's ring attention then run their sp collectives between
processes, because every process enters the same jitted program anyway.

Live rebalancing works too (v4, round 5): a span move is OP_RELOAD_SPAN —
leader and workers rebuild from the checkpoint simultaneously (the sharded
param device_puts pair like at startup, under the broadcast lock), after the
leader quiesces sessions (park for migration + queue barrier). No process
restarts; the reference restarts its whole server to move blocks.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)

OP_SHUTDOWN = 0
OP_ALLOC = 1
OP_FREE = 2
OP_INFERENCE_STEP = 3
OP_FORWARD = 4
OP_BACKWARD = 5
OP_EXPORT_KV = 6  # v2: per-shard all_gather of a session's KV (migration/drain)
OP_IMPORT_KV = 7  # v2: seed a KV mirror from an exported prefix
# v3: continuous batching composes with lockstep — the lane pool is one more
# mirrored allocation, and its three device ops broadcast like any other.
# Extracted lanes live on the workers as SYNTHETIC mirrors (negative handles
# minted by the leader's DecodeBatcher), so exclusive ops (chunked prefill,
# kv import/seed) target them with the ordinary OP_INFERENCE_STEP/IMPORT_KV.
OP_BATCHED_DECODE = 8
OP_LANE_EXTRACT = 9
OP_LANE_INSERT = 10
# v4 (round 5): LIVE REBALANCING for lockstep groups. A span move is a
# lockstep op like any other: the leader broadcasts the new first block and
# every process rebuilds its backend from the checkpoint SIMULTANEOUSLY (the
# sharded param device_puts are collectives that must pair, exactly like at
# startup). The leader runs the whole reload while holding the broadcast
# lock, so no ALLOC/FREE/compute collective can interleave with the rebuild.
OP_RELOAD_SPAN = 11

_HEADER_LEN = 14
_FLAG_PROMPTS = 1
_FLAG_HYPO = 2

# One lockstep op (header + operand broadcasts + the jitted compute) must hit
# the group atomically: ALLOC/FREE run on the asyncio event-loop thread while
# compute ops run on the PriorityTaskQueue thread — interleaved broadcasts
# would pair a worker's operand wait with the wrong leader collective and hang
# the group. (Workers are single-threaded; only the leader needs the lock.)
_BCAST_LOCK = threading.RLock()

# v2 worker-death detection: one lockstep group per process, so group health
# is module state. A worker that dies mid-collective makes the runtime's
# barrier/collective raise on the leader (coordination-service heartbeat or
# collective timeout); once that happens the group's compiled programs and
# sharded arrays are unrecoverable without a rebuild, so every subsequent op
# must fail FAST with a clear error instead of hanging a fresh collective.
_GROUP_STATE = {"degraded": None}


class MultihostDegraded(RuntimeError):
    """The lockstep group lost a member; the span server must stop serving."""


def group_degraded() -> Optional[BaseException]:
    """The exception that degraded this process's lockstep group, if any."""
    return _GROUP_STATE["degraded"]


def _check_group() -> None:
    if _GROUP_STATE["degraded"] is not None:
        raise MultihostDegraded(
            f"multihost group degraded: {_GROUP_STATE['degraded']!r} — "
            f"restart the leader and workers to re-form the group"
        ) from _GROUP_STATE["degraded"]


@contextlib.contextmanager
def _degrade_on_failure():
    """Mark the group degraded when a lockstep op dies in a collective."""
    _check_group()
    try:
        yield
    except MultihostDegraded:
        raise
    except Exception as e:
        _GROUP_STATE["degraded"] = e
        logger.error(
            f"multihost lockstep op failed ({e!r}): marking the group degraded"
        )
        raise MultihostDegraded(
            f"multihost group degraded: {e!r} — restart the leader and "
            f"workers to re-form the group"
        ) from e


def init_multihost(coordinator_address: str, num_processes: int, process_id: int) -> None:
    """Join the multi-controller group. Must run before ANYTHING initializes
    the XLA backend (even jax.devices()) — hence the module flag instead of
    querying jax state."""
    import jax

    if getattr(init_multihost, "_done", False):
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    init_multihost._done = True
    logger.info(
        f"multihost: process {jax.process_index()}/{jax.process_count()}, "
        f"{len(jax.local_devices())} local / {len(jax.devices())} global devices"
    )


def multihost_mesh(tp: Optional[int] = None, sp: int = 1):
    """Serving mesh over the GLOBAL device set (all hosts' chips): 1-D tp, or
    2-D (tp, sp) when sequence parallelism is requested — the sp collectives
    (ring attention / q-sharded cached prefill) then cross the process
    boundary like any other lockstep compute, because every process enters
    the same jitted program (ops broadcast via LockstepBackend)."""
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    sp = sp or 1
    if tp is None:
        tp, rem = divmod(len(devices), sp)
        if tp == 0:
            raise ValueError(
                f"multihost mesh sp={sp} needs at least {sp} devices, "
                f"{len(devices)} available across {jax.process_count()} processes"
            )
        if rem:
            logger.warning(
                f"multihost mesh: {len(devices)} devices do not divide sp={sp}; "
                f"serving on tp={tp} x sp={sp} = {tp * sp} devices, {rem} idle"
            )
    need = tp * sp
    if tp < 1 or len(devices) < need:
        raise ValueError(
            f"multihost mesh tp={tp} x sp={sp} needs {need} devices (tp >= 1), "
            f"{len(devices)} available across {jax.process_count()} processes"
        )
    if sp > 1:
        return Mesh(np.array(devices[:need]).reshape(tp, sp), ("tp", "sp"))
    return Mesh(np.array(devices[:tp]).reshape(tp), ("tp",))


def _bcast_header(values=None):
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    header = np.zeros((_HEADER_LEN,), np.int64)
    if values is not None:
        header[: len(values)] = values
    return np.asarray(
        multihost_utils.broadcast_one_to_all(jnp.asarray(header))
    ).tolist()


def _bcast_array(arr, shape, dtype):
    """Broadcast one operand (leader sends; workers pass zeros of the
    announced shape — broadcast_one_to_all needs identical avals everywhere)."""
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    if arr is None:
        arr = np.zeros(shape, dtype)
    return np.asarray(
        multihost_utils.broadcast_one_to_all(jnp.asarray(arr, dtype).reshape(shape))
    )


def _adapter_digest(adapters) -> int:
    """31-bit digest of the sorted adapter-name list: leader and workers must
    agree on the index->name mapping, not just the count. 31 bits because the
    header broadcast rides jnp's default int32 (no x64) — wider values would
    truncate silently."""
    import hashlib

    blob = ",".join(sorted(adapters)).encode()
    return int.from_bytes(hashlib.sha256(blob).digest()[:4], "big") >> 1


def _stage_kv_mirror(backend, k_prefix, v_prefix, position, batch_size, max_length, n_blocks):
    """Full sharded KV buffers seeded with an imported prefix. Runs in
    lockstep on every process (device_put with a cross-process sharding is a
    multi-controller operation: each process materializes its shards of the
    same logical value)."""
    import jax
    import jax.numpy as jnp

    kd, vd = backend.cache_descriptors(batch_size, max_length, 0, n_blocks)

    def stage(prefix, descr):
        full = np.zeros(descr.shape, jnp.dtype(descr.dtype))
        full[:, :, :position] = prefix.astype(full.dtype)
        if descr.sharding is not None:
            return jax.device_put(full, descr.sharding)
        return jnp.asarray(full)

    return stage(k_prefix, kd), stage(v_prefix, vd)


class _LockstepMixin:
    """Shared op encoding for leader and worker."""

    def _replicate_fn(self, mesh):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        # Outputs can come back sharded across PROCESSES (XLA's choice);
        # np.asarray on a non-addressable array raises. This jitted constraint
        # all_gathers INSIDE the program — a collective every process enters
        # (a host-side gather only the leader runs would deadlock the group).
        return jax.jit(
            lambda o: jax.lax.with_sharding_constraint(o, NamedSharding(mesh, P()))
        )


class LockstepBackend(_LockstepMixin):
    """Leader-side wrapper with the TransformerBackend surface the handler and
    server use. Attribute access falls through to the wrapped backend; the
    compute methods broadcast before computing. ``handles`` identifies the
    session's KV mirror on the workers (pass the k-handle)."""

    # class attribute (NOT via __getattr__, which only fires for misses):
    # handler gates sub-span wrapping and KV export/import on this
    is_lockstep = True

    def __init__(self, backend, *, span: Tuple[int, int] = None, retired_state=None):
        self._backend = backend
        self._span = span or (0, backend.n_blocks)
        self._replicate = self._replicate_fn(backend.mesh)
        # shared across sub-views: a live span move (reload_span) RETIRES the
        # old wrapper — sessions that captured it at open must fail their next
        # op per-request (client failover) instead of broadcasting against
        # worker mirrors the reload cleared, which would KeyError the worker
        # loop and degrade the whole group
        self._retired_state = retired_state if retired_state is not None else {"retired": False}

    def _check_live(self) -> None:
        if self._retired_state["retired"]:
            raise RuntimeError(
                "This span was moved by a live rebalance; the session's server-"
                "side state is gone — re-open through routing (client failover)"
            )

    def __getattr__(self, name):
        return getattr(self._backend, name)

    def sub_view(self, backend_slice, start: int, end: int) -> "LockstepBackend":
        """Lockstep view over a partial chain (handler._sub_backend)."""
        base = self._span[0]
        return LockstepBackend(
            backend_slice, span=(base + start, base + end),
            retired_state=self._retired_state,
        )

    def _adapter_code(self, active_adapter) -> int:
        """Adapters cross the control plane as 1-based indices into the SORTED
        adapter-name list — leader and workers host identical adapter sets
        (same flags, same checkpoints), so the mapping agrees by construction
        and one int64 slot identifies the pytree the worker must apply. A
        digest of the name list rides along (header slot 11) so a drifted
        worker set fails loud instead of applying the wrong adapter."""
        if not active_adapter:
            return 0
        names = sorted(self._backend.adapters)
        try:
            return names.index(active_adapter) + 1
        except ValueError:
            raise KeyError(f"Adapter {active_adapter!r} is not loaded on this server")

    # ------------------------------------------------------------- compute ops

    def inference_step(self, hidden, kv, position, *, prompts=None, hypo_ids=None,
                       active_adapter=None, handles=None, n_total=None):
        self._check_live()
        adapter_code = self._adapter_code(active_adapter)
        batch, seq, _ = hidden.shape
        flags = (_FLAG_PROMPTS if prompts is not None else 0) | (
            _FLAG_HYPO if hypo_ids is not None else 0
        )
        pre_seq = 0 if prompts is None else prompts.shape[2]
        mirror = -1 if handles is None else int(handles[0])
        b0, b1 = self._span
        with _BCAST_LOCK, _degrade_on_failure():
            # ``n_total`` rides the otherwise-unused n_valid header slot so every
            # follower picks the same LongRoPE scaling branch as the leader.
            _bcast_header([
                OP_INFERENCE_STEP, mirror, batch, seq, int(position),
                -1 if n_total is None else int(n_total), flags,
                pre_seq, adapter_code, b0, b1,
                _adapter_digest(self._backend.adapters) if adapter_code else 0,
            ])
            hidden = _bcast_array(hidden, (batch, seq, self._backend.hidden_size), np.float32)
            if prompts is not None:
                prompts = _bcast_array(
                    prompts,
                    (b1 - b0, batch, pre_seq, self._backend.hidden_size),
                    np.float32,
                )
            if hypo_ids is not None:
                hypo_ids = _bcast_array(hypo_ids, (batch,), np.int64)
            out, new_kv = self._backend.inference_step(
                hidden, kv, position, prompts=prompts, hypo_ids=hypo_ids,
                active_adapter=active_adapter, n_total=n_total,
            )
            return self._replicate(out), new_kv

    def forward(self, hidden, *, prompts=None, active_adapter=None):
        self._check_live()
        adapter_code = self._adapter_code(active_adapter)
        batch, seq, _ = hidden.shape
        flags = _FLAG_PROMPTS if prompts is not None else 0
        pre_seq = 0 if prompts is None else prompts.shape[2]
        b0, b1 = self._span
        with _BCAST_LOCK, _degrade_on_failure():
            _bcast_header([
                OP_FORWARD, -1, batch, seq, 0, -1, flags, pre_seq, adapter_code, b0, b1,
                _adapter_digest(self._backend.adapters) if adapter_code else 0,
            ])
            hidden = _bcast_array(hidden, (batch, seq, self._backend.hidden_size), np.float32)
            if prompts is not None:
                prompts = _bcast_array(
                    prompts, (b1 - b0, batch, pre_seq, self._backend.hidden_size), np.float32
                )
            return self._replicate(
                self._backend.forward(hidden, prompts=prompts, active_adapter=active_adapter)
            )

    def backward(self, hidden, grad_out, *, prompts=None, active_adapter=None):
        self._check_live()
        adapter_code = self._adapter_code(active_adapter)
        batch, seq, _ = hidden.shape
        flags = _FLAG_PROMPTS if prompts is not None else 0
        pre_seq = 0 if prompts is None else prompts.shape[2]
        b0, b1 = self._span
        with _BCAST_LOCK, _degrade_on_failure():
            _bcast_header([
                OP_BACKWARD, -1, batch, seq, 0, -1, flags, pre_seq, adapter_code, b0, b1,
                _adapter_digest(self._backend.adapters) if adapter_code else 0,
            ])
            # operand order mirrors the worker's generic decode: hidden, then
            # prompts (if flagged), then the op-specific grad_out
            hidden = _bcast_array(hidden, (batch, seq, self._backend.hidden_size), np.float32)
            if prompts is not None:
                prompts = _bcast_array(
                    prompts, (b1 - b0, batch, pre_seq, self._backend.hidden_size), np.float32
                )
            grad_out = _bcast_array(grad_out, (batch, seq, self._backend.hidden_size), np.float32)
            grad_in, grad_prompts = self._backend.backward(
                hidden, grad_out, prompts=prompts, active_adapter=active_adapter
            )
            grad_in = self._replicate(grad_in)
            if grad_prompts is not None:
                grad_prompts = self._replicate(grad_prompts)
            return grad_in, grad_prompts

    # ------------------------------------------------- continuous batching (v3)

    def batched_decode_step(self, hidden, pool_kv, positions, handles=None):
        """One coalesced decode step over the whole mirrored lane pool
        (server/batching.py flush loop). ``handles`` carries the pool's
        mirror handle; hidden/positions broadcast, every process steps its
        shards of the pool."""
        self._check_live()
        n_lanes = int(hidden.shape[0])
        with _BCAST_LOCK, _degrade_on_failure():
            _bcast_header([OP_BATCHED_DECODE, int(handles[0]), n_lanes])
            hidden = _bcast_array(
                hidden, (n_lanes, 1, self._backend.hidden_size), np.float32
            )
            positions = _bcast_array(
                np.asarray(positions, np.int64), (n_lanes,), np.int64
            )
            out, new_kv = self._backend.batched_decode_step(hidden, pool_kv, positions)
            return self._replicate(out), new_kv

    def lane_extract(self, k_pool, v_pool, lane: int, *, pool_handle: int, temp_handle: int):
        """Check a lane OUT of the pool on every process; workers register the
        session-shaped copy under the synthetic ``temp_handle`` mirror so
        subsequent exclusive ops (inference steps, imports, exports) can
        address it like any session KV."""
        self._check_live()
        with _BCAST_LOCK, _degrade_on_failure():
            _bcast_header([OP_LANE_EXTRACT, int(pool_handle), int(lane), int(temp_handle)])
            return self._backend._lane_extract_fn(k_pool, v_pool, np.int32(lane))

    def lane_insert(self, k_pool, v_pool, kv_lane, lane: int, *, pool_handle: int, temp_handle: int):
        """Check a lane back IN on every process; workers consume (pop) their
        ``temp_handle`` mirror. Returns the leader's new pool buffers."""
        self._check_live()
        k2, v2 = kv_lane
        with _BCAST_LOCK, _degrade_on_failure():
            _bcast_header([OP_LANE_INSERT, int(pool_handle), int(lane), int(temp_handle)])
            return self._backend._lane_insert_fn(k_pool, v_pool, k2, v2, np.int32(lane))

    def release_temp(self, temp_handle: int) -> None:
        """Drop a synthetic mirror that will not be inserted back (read-only
        extracts, e.g. lane snapshots). Rides OP_FREE — workers pop the id."""
        if _GROUP_STATE["degraded"] is not None:
            return
        with _BCAST_LOCK, _degrade_on_failure():
            _bcast_header([OP_FREE, int(temp_handle), 1])

    # ------------------------------------------------------- KV export/import (v2)

    def export_kv(self, handles, get_buffers, b0: int, b1: int, position: int):
        """Host copy of blocks [b0, b1) of a session's KV mirror, sliced to
        ``position`` — the migration/drain/park path under lockstep. Every
        process enters an in-program all_gather (the replicate constraint) on
        its shards; only the leader reads the result. The gather is bounded to
        the live prefix rounded up to 128 tokens (bucketed so the replicate
        program compiles once per bucket, not once per position).

        ``get_buffers`` is called UNDER the broadcast lock so no step can be
        mid-donation; a buffer already donated but not yet swapped by the
        handler's update_cache is retried. Local errors (freed handles, a
        closing session) stay per-request errors — only a failure INSIDE the
        collective degrades the group."""
        import time

        self._check_live()
        for attempt in range(40):
            with _BCAST_LOCK:
                _check_group()
                # local fetch: failures here must NOT mark the group degraded
                k_buf, v_buf = get_buffers()
                if not (k_buf.is_deleted() or v_buf.is_deleted()):
                    max_len = k_buf.shape[2]
                    pad_pos = min(-(-max(position, 1) // 128) * 128, max_len)
                    with _degrade_on_failure():
                        _bcast_header([OP_EXPORT_KV, int(handles[0]), b0, b1, pad_pos])
                        k = self._replicate(k_buf[b0:b1, :, :pad_pos])
                        v = self._replicate(v_buf[b0:b1, :, :pad_pos])
                        return (
                            np.asarray(k)[:, :, :position],
                            np.asarray(v)[:, :, :position],
                        )
            time.sleep(0.05)
        raise RuntimeError("KV buffers kept being donated mid-export")

    def import_kv(self, handles, k_prefix, v_prefix, position: int,
                  batch_size: int, max_length: int, n_blocks: int):
        """Seed a session's KV mirror from an exported prefix: the prefix is
        broadcast once and every process materializes its own shards of the
        full buffer. Returns the leader's new (k, v) global arrays."""
        self._check_live()
        shape = tuple(k_prefix.shape)
        with _BCAST_LOCK, _degrade_on_failure():
            _bcast_header([
                OP_IMPORT_KV, int(handles[0]), int(position),
                n_blocks, batch_size, max_length,
            ])
            k_prefix = _bcast_array(k_prefix, shape, np.float32)
            v_prefix = _bcast_array(v_prefix, shape, np.float32)
            return _stage_kv_mirror(
                self._backend, k_prefix, v_prefix, position,
                batch_size, max_length, n_blocks,
            )

    def reload_span(self, new_first_block: int, build_backend) -> "LockstepBackend":
        """LIVE SPAN MOVE (v4): broadcast the new first block and rebuild
        leader + workers in lockstep. ``build_backend()`` is the leader's
        synchronous rebuild (load + convert + shard); it runs UNDER the
        broadcast lock so its sharded-param collectives pair with the
        workers' identical rebuild and nothing else can interleave. Callers
        must have quiesced session compute first (drain + queue barrier) —
        an op referencing the old span's mirrors after the swap would find
        nothing. Returns the new leader-side lockstep wrapper."""
        with _BCAST_LOCK, _degrade_on_failure():
            self._retired_state["retired"] = True  # fence BEFORE the swap:
            # a straggler session op must fail per-request, never broadcast
            # against the mirrors the reload is about to clear
            _bcast_header([OP_RELOAD_SPAN, int(new_first_block)])
            backend = build_backend()
        return LockstepBackend(backend)

    def shutdown_workers(self) -> None:
        if _GROUP_STATE["degraded"] is not None:
            return  # the group is gone; a release broadcast would only hang
        with _BCAST_LOCK:
            _bcast_header([OP_SHUTDOWN])


class LockstepMemoryCache:
    """Leader-side MemoryCache wrapper: identical budget/queueing semantics
    (delegation), but reservation and free broadcast ALLOC/FREE so every
    worker mirrors the buffers for the same handles."""

    def __init__(self, memory_cache):
        self._cache = memory_cache
        orig_reserve, orig_free = memory_cache._reserve, memory_cache._free

        def reserve(descriptors, alloc_size):
            _check_group()  # before booking anything the broadcast can't mirror
            handles = orig_reserve(descriptors, alloc_size)
            # [op, h0, n, batch, max_len, hkv, hd, n_descr]
            d = descriptors[0]
            try:
                with _BCAST_LOCK, _degrade_on_failure():
                    _bcast_header([OP_ALLOC, handles[0], *d.shape, len(descriptors)])
                    # materialize NOW, in lockstep with the workers: creating
                    # an array whose sharding spans processes is itself a
                    # multi-controller computation — a lazy get_buffers on the
                    # leader would deadlock against workers waiting in broadcast
                    memory_cache.get_buffers(*handles)
            except BaseException:
                orig_free(handles)  # never strand booked budget on failure
                raise
            return handles

        def free(handles):
            # the leader-side free must ALWAYS run — on a degraded group the
            # mirrors died with the workers, but draining sessions still have
            # to return their budget so the surviving leader's accounting and
            # teardown stay clean. A broadcast failure here still marks the
            # group degraded but never propagates out of cleanup.
            try:
                if handles and _GROUP_STATE["degraded"] is None:
                    with _BCAST_LOCK, _degrade_on_failure():
                        _bcast_header([OP_FREE, handles[0], len(handles)])
            except MultihostDegraded as e:
                logger.warning(f"FREE broadcast failed on a degraded group: {e}")
            finally:
                orig_free(handles)

        memory_cache._reserve = reserve
        memory_cache._free = free

    def __getattr__(self, name):
        return getattr(self._cache, name)


class LockstepWorker:
    """Non-leader process: mirrors allocations and executes the leader's
    compute ops in lockstep until OP_SHUTDOWN.

    ``rebuild_fn(new_first_block) -> TransformerBackend`` enables live span
    moves (OP_RELOAD_SPAN): the worker rebuilds its backend from the
    checkpoint in lockstep with the leader. Without it a reload op degrades
    the group (restart-to-move, the pre-v4 behavior)."""

    def __init__(self, backend, rebuild_fn=None):
        self.backend = backend
        self.rebuild_fn = rebuild_fn
        self._kv: Dict[int, Tuple] = {}
        self._subs: Dict[Tuple[int, int], object] = {}
        self._replicate = _LockstepMixin()._replicate_fn(backend.mesh)

    def _sub(self, b0: int, b1: int):
        if (b0, b1) == (0, self.backend.n_blocks):
            return self.backend
        key = (b0, b1)
        if key not in self._subs:
            from petals_tpu.server.backend import TransformerBackend
            from petals_tpu.server.memory_cache import MemoryCache

            import jax

            sub = TransformerBackend(
                self.backend.family,
                self.backend.cfg,
                self.backend._slice_params(b0, b1),
                first_block=self.backend.first_block + b0,
                n_blocks=b1 - b0,
                memory_cache=MemoryCache(None),
                compute_dtype=self.backend.compute_dtype,
                cache_dtype=self.backend.cache_dtype,
                max_chunk_size_bytes=self.backend.max_chunk_size_bytes,
                use_flash=self.backend.use_flash,
                mesh=self.backend.mesh,
            )
            # mirror the leader handler's sub-backend adapter slicing
            sub.adapters = {
                name: (jax.tree_util.tree_map(lambda x: x[b0:b1], stacked), scaling)
                for name, (stacked, scaling) in self.backend.adapters.items()
            }
            self._subs[key] = sub
        return self._subs[key]

    def _adapter_name(self, code: int, digest: int):
        if code == 0:
            return None
        names = sorted(self.backend.adapters)
        # the digest catches sets that differ in NAMES, not just count —
        # without it a drifted worker would silently apply the wrong adapter
        if code > len(names) or digest != _adapter_digest(names):
            raise RuntimeError(
                f"Leader requested adapter #{code} of a set with digest "
                f"{digest} but this worker hosts {names} — leader and workers "
                f"must be started with identical --adapters flags"
            )
        return names[code - 1]

    def run(self) -> None:
        import jax

        logger.info(f"multihost worker {jax.process_index()}: serving lockstep ops")
        while True:
            header = _bcast_header()
            op = header[0]
            if op == OP_SHUTDOWN:
                logger.info("multihost worker: shutdown")
                return
            if op == OP_ALLOC:
                # [op, h0, n, batch, max_len, hkv, hd, n_descr]
                _, h0, n, batch, max_len = header[:5]
                # materialize immediately (lockstep with the leader's reserve:
                # cross-process-sharded zeros are a collective computation)
                kd, vd = self.backend.cache_descriptors(batch, max_len, 0, n)
                self._kv[h0] = (kd.make_zeros(), vd.make_zeros())
                continue
            if op == OP_FREE:
                _, h0, _count = header[:3]
                self._kv.pop(h0, None)
                continue
            if op == OP_EXPORT_KV:
                # [op, mirror, b0, b1, pad_pos]: enter the all_gather (bounded
                # to the bucketed live prefix); the leader reads the result
                _, mirror, b0, b1, pad_pos = header[:5]
                k_buf, v_buf = self._kv[mirror]
                self._replicate(k_buf[b0:b1, :, :pad_pos])
                self._replicate(v_buf[b0:b1, :, :pad_pos])
                continue
            if op == OP_IMPORT_KV:
                # [op, mirror, position, n, batch, max_len]
                _, mirror, position, n, batch, max_len = header[:6]
                hkv, hd = self.backend.num_kv_heads, self.backend.head_dim
                shape = (n, batch, position, hkv, hd)
                k_prefix = _bcast_array(None, shape, np.float32)
                v_prefix = _bcast_array(None, shape, np.float32)
                self._kv[mirror] = _stage_kv_mirror(
                    self.backend, k_prefix, v_prefix, position, batch, max_len, n
                )
                continue
            if op == OP_RELOAD_SPAN:
                # [op, new_first_block]: rebuild for the new span IN LOCKSTEP
                # with the leader (the sharded param device_puts pair up).
                # Old session mirrors die with the old span.
                _, new_first = header[:2]
                if self.rebuild_fn is None:
                    raise RuntimeError(
                        "leader requested a live span move but this worker "
                        "has no rebuild_fn — restart the group to move spans"
                    )
                logger.info(f"multihost worker: live span move to first_block={new_first}")
                self._kv.clear()
                self._subs.clear()
                # release the OLD span's params BEFORE loading the new ones:
                # keeping both resident would double peak device memory and
                # OOM moves on exactly the hosts sized to their span
                self.backend = None
                self._replicate = None
                self.backend = self.rebuild_fn(int(new_first))
                self._replicate = _LockstepMixin()._replicate_fn(self.backend.mesh)
                continue
            if op == OP_BATCHED_DECODE:
                # [op, pool_h, n_lanes]: step every lane of the pool mirror
                _, pool_h, n_lanes = header[:3]
                hidden = _bcast_array(
                    None, (n_lanes, 1, self.backend.hidden_size), np.float32
                )
                positions = _bcast_array(None, (n_lanes,), np.int64)
                out, new_kv = self.backend.batched_decode_step(
                    hidden, self._kv[pool_h], positions
                )
                self._kv[pool_h] = new_kv
                self._replicate(out)
                continue
            if op == OP_LANE_EXTRACT:
                # [op, pool_h, lane, temp]: session-shaped copy under ``temp``
                _, pool_h, lane, temp = header[:4]
                k_pool, v_pool = self._kv[pool_h]
                self._kv[temp] = self.backend._lane_extract_fn(
                    k_pool, v_pool, np.int32(lane)
                )
                continue
            if op == OP_LANE_INSERT:
                # [op, pool_h, lane, temp]: consume the temp mirror back in
                _, pool_h, lane, temp = header[:4]
                k_pool, v_pool = self._kv[pool_h]
                k2, v2 = self._kv.pop(temp)
                self._kv[pool_h] = self.backend._lane_insert_fn(
                    k_pool, v_pool, k2, v2, np.int32(lane)
                )
                continue

            # compute ops: [op, mirror, batch, seq, position, n_valid, flags,
            #               pre_seq, adapter_code, b0, b1, adapter_digest]
            (_, mirror, batch, seq, position, _n_valid, flags, pre_seq,
             adapter_code, b0, b1, adapter_digest) = header[:12]
            hidden = _bcast_array(
                None, (batch, seq, self.backend.hidden_size), np.float32
            )
            prompts = hypo_ids = None
            if flags & _FLAG_PROMPTS:
                prompts = _bcast_array(
                    None, (b1 - b0, batch, pre_seq, self.backend.hidden_size), np.float32
                )
            backend = self._sub(b0, b1)
            adapter = self._adapter_name(adapter_code, adapter_digest)
            if op == OP_INFERENCE_STEP:
                if flags & _FLAG_HYPO:
                    hypo_ids = _bcast_array(None, (batch,), np.int64)
                kv = self._kv[mirror]
                out, new_kv = backend.inference_step(
                    hidden, kv, position, prompts=prompts, hypo_ids=hypo_ids,
                    active_adapter=adapter,
                    n_total=None if _n_valid < 0 else int(_n_valid),
                )
                self._kv[mirror] = new_kv
                self._replicate(out)
            elif op == OP_FORWARD:
                self._replicate(
                    backend.forward(hidden, prompts=prompts, active_adapter=adapter)
                )
            elif op == OP_BACKWARD:
                grad_out = _bcast_array(
                    None, (batch, seq, self.backend.hidden_size), np.float32
                )
                g_in, g_p = backend.backward(
                    hidden, grad_out, prompts=prompts, active_adapter=adapter
                )
                self._replicate(g_in)
                if g_p is not None:
                    self._replicate(g_p)
            else:
                raise RuntimeError(f"multihost worker: unknown op {op}")


