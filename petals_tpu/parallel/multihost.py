"""Multi-host serving: one SPAN-server spanning several hosts' chips.

The reference cannot express this at all — its tensor parallelism is bounded
by one machine's GPUs (`tensor_parallel` over local CUDA devices, reference
convert_block.py:118-135). On a v5e-64 (16 hosts x 4 chips) a 405B block
sharded past 4 chips needs tensor parallelism ACROSS hosts, which in JAX is
multi-controller SPMD: every participating process runs the same jitted
computation over a global mesh, with XLA collectives riding ICI/DCN.

Architecture (TPU-first; there is no torch/NCCL analogue to port):

- ``init_multihost`` wraps ``jax.distributed.initialize`` — afterwards
  ``jax.devices()`` spans all hosts and a Mesh built from it shards params and
  KV caches across every chip of every host.
- Only the LEADER (process 0) runs the swarm surface (DHT, RPC handler,
  scheduler, memory-cache budgeting). Workers (``cli/run_worker.py``) build
  the identical backend from the identical checkpoint and sit in
  ``LockstepWorker.run``: multi-controller JAX requires every process to enter
  every jitted computation together, so each leader-side compute call
  broadcasts a compact descriptor (``multihost_utils.broadcast_one_to_all``)
  and the workers invoke the same backend method on their shards.
- KV buffers are mirrored by HANDLE: the leader's MemoryCache reserves
  handles/budget as usual but broadcasts ALLOC/FREE (``LockstepMemoryCache``),
  and each process materializes its own shards of the same logical buffer.
  Only handles and replicated activations cross the control plane — KV shards
  never move between hosts outside XLA collectives.
- Array creation (zeros, device_put of identical host values) is process-local
  in multi-controller JAX; the actual cross-host traffic is the in-program
  collectives (psum/all_gather over the tp axis) plus the tiny control
  broadcasts.

Known v1 limits (enforced with clean errors at server start): session KV
export/import (migration, drain-parking) and live rebalancing are disabled —
both move whole KV buffers through the host, which is a per-shard gather this
control plane does not do yet. Throughput must be given explicitly (the
auto-probe builds throwaway backends workers don't mirror).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)

OP_SHUTDOWN = 0
OP_ALLOC = 1
OP_FREE = 2
OP_INFERENCE_STEP = 3
OP_FORWARD = 4
OP_BACKWARD = 5

_HEADER_LEN = 14
_FLAG_PROMPTS = 1
_FLAG_HYPO = 2

# One lockstep op (header + operand broadcasts + the jitted compute) must hit
# the group atomically: ALLOC/FREE run on the asyncio event-loop thread while
# compute ops run on the PriorityTaskQueue thread — interleaved broadcasts
# would pair a worker's operand wait with the wrong leader collective and hang
# the group. (Workers are single-threaded; only the leader needs the lock.)
_BCAST_LOCK = threading.RLock()


def init_multihost(coordinator_address: str, num_processes: int, process_id: int) -> None:
    """Join the multi-controller group. Must run before ANYTHING initializes
    the XLA backend (even jax.devices()) — hence the module flag instead of
    querying jax state."""
    import jax

    if getattr(init_multihost, "_done", False):
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    init_multihost._done = True
    logger.info(
        f"multihost: process {jax.process_index()}/{jax.process_count()}, "
        f"{len(jax.local_devices())} local / {len(jax.devices())} global devices"
    )


def multihost_mesh(tp: Optional[int] = None):
    """tp serving mesh over the GLOBAL device set (all hosts' chips)."""
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    tp = tp or len(devices)
    if len(devices) < tp:
        raise ValueError(
            f"multihost mesh tp={tp} needs {tp} devices, {len(devices)} "
            f"available across {jax.process_count()} processes"
        )
    return Mesh(np.array(devices[:tp]).reshape(tp), ("tp",))


def _bcast_header(values=None):
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    header = np.zeros((_HEADER_LEN,), np.int64)
    if values is not None:
        header[: len(values)] = values
    return np.asarray(
        multihost_utils.broadcast_one_to_all(jnp.asarray(header))
    ).tolist()


def _bcast_array(arr, shape, dtype):
    """Broadcast one operand (leader sends; workers pass zeros of the
    announced shape — broadcast_one_to_all needs identical avals everywhere)."""
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    if arr is None:
        arr = np.zeros(shape, dtype)
    return np.asarray(
        multihost_utils.broadcast_one_to_all(jnp.asarray(arr, dtype).reshape(shape))
    )


class _LockstepMixin:
    """Shared op encoding for leader and worker."""

    def _replicate_fn(self, mesh):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        # Outputs can come back sharded across PROCESSES (XLA's choice);
        # np.asarray on a non-addressable array raises. This jitted constraint
        # all_gathers INSIDE the program — a collective every process enters
        # (a host-side gather only the leader runs would deadlock the group).
        return jax.jit(
            lambda o: jax.lax.with_sharding_constraint(o, NamedSharding(mesh, P()))
        )


class LockstepBackend(_LockstepMixin):
    """Leader-side wrapper with the TransformerBackend surface the handler and
    server use. Attribute access falls through to the wrapped backend; the
    compute methods broadcast before computing. ``handles`` identifies the
    session's KV mirror on the workers (pass the k-handle)."""

    # class attribute (NOT via __getattr__, which only fires for misses):
    # handler gates sub-span wrapping and KV export/import on this
    is_lockstep = True

    def __init__(self, backend, *, span: Tuple[int, int] = None):
        self._backend = backend
        self._span = span or (0, backend.n_blocks)
        self._replicate = self._replicate_fn(backend.mesh)

    def __getattr__(self, name):
        return getattr(self._backend, name)

    def sub_view(self, backend_slice, start: int, end: int) -> "LockstepBackend":
        """Lockstep view over a partial chain (handler._sub_backend)."""
        base = self._span[0]
        return LockstepBackend(backend_slice, span=(base + start, base + end))

    # ------------------------------------------------------------- compute ops

    def inference_step(self, hidden, kv, position, *, prompts=None, hypo_ids=None,
                       active_adapter=None, handles=None):
        if active_adapter:
            raise NotImplementedError("LoRA adapters are not supported with multi-host serving yet")
        batch, seq, _ = hidden.shape
        flags = (_FLAG_PROMPTS if prompts is not None else 0) | (
            _FLAG_HYPO if hypo_ids is not None else 0
        )
        pre_seq = 0 if prompts is None else prompts.shape[2]
        mirror = -1 if handles is None else int(handles[0])
        b0, b1 = self._span
        with _BCAST_LOCK:
            _bcast_header([
                OP_INFERENCE_STEP, mirror, batch, seq, int(position), -1, flags,
                pre_seq, 0, b0, b1,
            ])
            hidden = _bcast_array(hidden, (batch, seq, self._backend.hidden_size), np.float32)
            if prompts is not None:
                prompts = _bcast_array(
                    prompts,
                    (b1 - b0, batch, pre_seq, self._backend.hidden_size),
                    np.float32,
                )
            if hypo_ids is not None:
                hypo_ids = _bcast_array(hypo_ids, (batch,), np.int64)
            out, new_kv = self._backend.inference_step(
                hidden, kv, position, prompts=prompts, hypo_ids=hypo_ids
            )
            return self._replicate(out), new_kv

    def forward(self, hidden, *, prompts=None, active_adapter=None):
        if active_adapter:
            raise NotImplementedError("LoRA adapters are not supported with multi-host serving yet")
        batch, seq, _ = hidden.shape
        flags = _FLAG_PROMPTS if prompts is not None else 0
        pre_seq = 0 if prompts is None else prompts.shape[2]
        b0, b1 = self._span
        with _BCAST_LOCK:
            _bcast_header([OP_FORWARD, -1, batch, seq, 0, -1, flags, pre_seq, 0, b0, b1])
            hidden = _bcast_array(hidden, (batch, seq, self._backend.hidden_size), np.float32)
            if prompts is not None:
                prompts = _bcast_array(
                    prompts, (b1 - b0, batch, pre_seq, self._backend.hidden_size), np.float32
                )
            return self._replicate(self._backend.forward(hidden, prompts=prompts))

    def backward(self, hidden, grad_out, *, prompts=None, active_adapter=None):
        if active_adapter:
            raise NotImplementedError("LoRA adapters are not supported with multi-host serving yet")
        batch, seq, _ = hidden.shape
        flags = _FLAG_PROMPTS if prompts is not None else 0
        pre_seq = 0 if prompts is None else prompts.shape[2]
        b0, b1 = self._span
        with _BCAST_LOCK:
            _bcast_header([OP_BACKWARD, -1, batch, seq, 0, -1, flags, pre_seq, 0, b0, b1])
            # operand order mirrors the worker's generic decode: hidden, then
            # prompts (if flagged), then the op-specific grad_out
            hidden = _bcast_array(hidden, (batch, seq, self._backend.hidden_size), np.float32)
            if prompts is not None:
                prompts = _bcast_array(
                    prompts, (b1 - b0, batch, pre_seq, self._backend.hidden_size), np.float32
                )
            grad_out = _bcast_array(grad_out, (batch, seq, self._backend.hidden_size), np.float32)
            grad_in, grad_prompts = self._backend.backward(hidden, grad_out, prompts=prompts)
            grad_in = self._replicate(grad_in)
            if grad_prompts is not None:
                grad_prompts = self._replicate(grad_prompts)
            return grad_in, grad_prompts

    def shutdown_workers(self) -> None:
        with _BCAST_LOCK:
            _bcast_header([OP_SHUTDOWN])


class LockstepMemoryCache:
    """Leader-side MemoryCache wrapper: identical budget/queueing semantics
    (delegation), but reservation and free broadcast ALLOC/FREE so every
    worker mirrors the buffers for the same handles."""

    def __init__(self, memory_cache):
        self._cache = memory_cache
        orig_reserve, orig_free = memory_cache._reserve, memory_cache._free

        def reserve(descriptors, alloc_size):
            handles = orig_reserve(descriptors, alloc_size)
            # [op, h0, n, batch, max_len, hkv, hd, n_descr]
            d = descriptors[0]
            with _BCAST_LOCK:
                _bcast_header([OP_ALLOC, handles[0], *d.shape, len(descriptors)])
                # materialize NOW, in lockstep with the workers: creating an
                # array whose sharding spans processes is itself a
                # multi-controller computation — a lazy get_buffers on the
                # leader would deadlock against workers waiting in broadcast
                memory_cache.get_buffers(*handles)
            return handles

        def free(handles):
            if handles:
                with _BCAST_LOCK:
                    _bcast_header([OP_FREE, handles[0], len(handles)])
            orig_free(handles)

        memory_cache._reserve = reserve
        memory_cache._free = free

    def __getattr__(self, name):
        return getattr(self._cache, name)


class LockstepWorker:
    """Non-leader process: mirrors allocations and executes the leader's
    compute ops in lockstep until OP_SHUTDOWN."""

    def __init__(self, backend):
        self.backend = backend
        self._kv: Dict[int, Tuple] = {}
        self._subs: Dict[Tuple[int, int], object] = {}
        self._replicate = _LockstepMixin()._replicate_fn(backend.mesh)

    def _sub(self, b0: int, b1: int):
        if (b0, b1) == (0, self.backend.n_blocks):
            return self.backend
        key = (b0, b1)
        if key not in self._subs:
            from petals_tpu.server.backend import TransformerBackend
            from petals_tpu.server.memory_cache import MemoryCache

            self._subs[key] = TransformerBackend(
                self.backend.family,
                self.backend.cfg,
                self.backend._slice_params(b0, b1),
                first_block=self.backend.first_block + b0,
                n_blocks=b1 - b0,
                memory_cache=MemoryCache(None),
                compute_dtype=self.backend.compute_dtype,
                cache_dtype=self.backend.cache_dtype,
                max_chunk_size_bytes=self.backend.max_chunk_size_bytes,
                use_flash=self.backend.use_flash,
                mesh=self.backend.mesh,
            )
        return self._subs[key]

    def run(self) -> None:
        import jax

        logger.info(f"multihost worker {jax.process_index()}: serving lockstep ops")
        while True:
            header = _bcast_header()
            op = header[0]
            if op == OP_SHUTDOWN:
                logger.info("multihost worker: shutdown")
                return
            if op == OP_ALLOC:
                # [op, h0, n, batch, max_len, hkv, hd, n_descr]
                _, h0, n, batch, max_len = header[:5]
                # materialize immediately (lockstep with the leader's reserve:
                # cross-process-sharded zeros are a collective computation)
                kd, vd = self.backend.cache_descriptors(batch, max_len, 0, n)
                self._kv[h0] = (kd.make_zeros(), vd.make_zeros())
                continue
            if op == OP_FREE:
                _, h0, _count = header[:3]
                self._kv.pop(h0, None)
                continue

            # compute ops: [op, mirror, batch, seq, position, n_valid, flags,
            #               pre_seq, spare, b0, b1]
            (_, mirror, batch, seq, position, _n_valid, flags, pre_seq,
             _spare, b0, b1) = header[:11]
            hidden = _bcast_array(
                None, (batch, seq, self.backend.hidden_size), np.float32
            )
            prompts = hypo_ids = None
            if flags & _FLAG_PROMPTS:
                prompts = _bcast_array(
                    None, (b1 - b0, batch, pre_seq, self.backend.hidden_size), np.float32
                )
            backend = self._sub(b0, b1)
            if op == OP_INFERENCE_STEP:
                if flags & _FLAG_HYPO:
                    hypo_ids = _bcast_array(None, (batch,), np.int64)
                kv = self._kv[mirror]
                out, new_kv = backend.inference_step(
                    hidden, kv, position, prompts=prompts, hypo_ids=hypo_ids
                )
                self._kv[mirror] = new_kv
                self._replicate(out)
            elif op == OP_FORWARD:
                self._replicate(backend.forward(hidden, prompts=prompts))
            elif op == OP_BACKWARD:
                grad_out = _bcast_array(
                    None, (batch, seq, self.backend.hidden_size), np.float32
                )
                g_in, g_p = backend.backward(hidden, grad_out, prompts=prompts)
                self._replicate(g_in)
                if g_p is not None:
                    self._replicate(g_p)
            else:
                raise RuntimeError(f"multihost worker: unknown op {op}")


