from petals_tpu.parallel.mesh import make_mesh
from petals_tpu.parallel.tp import kv_cache_pspec, span_param_pspecs

__all__ = ["make_mesh", "span_param_pspecs", "kv_cache_pspec"]
