"""Tensor-parallel shardings for stacked span parameters
(counterpart of the reference's per-block TP configs,
src/petals/utils/convert_block.py:118-135 + backend.py:88-99, re-expressed as
jax.sharding PartitionSpecs — Megatron-style: attention/MLP input projections
split on the output (head) axis, output projections split on the input axis,
norms replicated; XLA then inserts the psums over ICI).

All leaf shapes have a leading layer axis (the span stack), so weight specs
are (None, <in>, <out>).
"""

from __future__ import annotations

from typing import Dict

from jax.sharding import NamedSharding, PartitionSpec as P

COL = "tp"  # axis name used for head/ffn splits


def span_param_pspecs(family_name: str, cfg) -> Dict[str, P]:
    """PartitionSpecs for one family's stacked block params."""
    if family_name == "llama":
        specs = {
            "ln1": P(),
            "wq": P(None, None, COL),
            "wk": P(None, None, COL),
            "wv": P(None, None, COL),
            "wo": P(None, COL, None),
            "ln2": P(),
            "wg": P(None, None, COL),
            "wu": P(None, None, COL),
            "wd": P(None, COL, None),
        }
        if getattr(cfg, "attention_bias", False):
            specs.update(bq=P(None, COL), bk=P(None, COL), bv=P(None, COL), bo=P())
        if getattr(cfg, "mlp_bias", False):
            specs.update(bg=P(None, COL), bu=P(None, COL), bd=P())
        return specs
    if family_name == "bloom":
        return {
            "ln1_w": P(),
            "ln1_b": P(),
            "wq": P(None, None, COL),
            "bq": P(None, COL),
            "wk": P(None, None, COL),
            "bk": P(None, COL),
            "wv": P(None, None, COL),
            "bv": P(None, COL),
            "wo": P(None, COL, None),
            "bo": P(),
            "ln2_w": P(),
            "ln2_b": P(),
            "w_up": P(None, None, COL),
            "b_up": P(None, COL),
            "w_down": P(None, COL, None),
            "b_down": P(),
        }
    if family_name == "falcon":
        specs = {
            "wq": P(None, None, COL),
            "wk": P(None, None, COL),
            "wv": P(None, None, COL),
            "wo": P(None, COL, None),
            "w_up": P(None, None, COL),
            "w_down": P(None, COL, None),
        }
        if cfg.new_decoder_architecture and cfg.num_ln_in_parallel_attn == 2:
            specs.update(ln_attn_w=P(), ln_attn_b=P(), ln_mlp_w=P(), ln_mlp_b=P())
        else:
            specs.update(ln1_w=P(), ln1_b=P())
            if not cfg.parallel_attn and not cfg.new_decoder_architecture:
                specs.update(ln2_w=P(), ln2_b=P())
        if cfg.bias:
            specs.update(
                bq=P(None, COL), bk=P(None, COL), bv=P(None, COL),
                bo=P(), b_up=P(None, COL), b_down=P(),
            )
        return specs
    if family_name == "mixtral":
        return {
            "ln1": P(),
            "wq": P(None, None, COL),
            "wk": P(None, None, COL),
            "wv": P(None, None, COL),
            "wo": P(None, COL, None),
            "ln2": P(),
            "gate": P(),
            # experts: shard the expert axis — expert parallelism over the mesh
            # (goes beyond the reference, which keeps experts unsharded)
            "w1": P(None, COL, None, None),
            "w2": P(None, COL, None, None),
            "w3": P(None, COL, None, None),
        }
    raise KeyError(f"No TP spec for family {family_name!r}")


def kv_cache_pspec() -> P:
    """KV stacks [n_blocks, batch, max_len, kv_heads, head_dim]: shard heads."""
    return P(None, None, None, COL, None)


def quant_leaf_pspecs(q, spec: P):
    """(data_spec, scales_spec) for a QuantizedLinear whose *dense* weight spec
    is ``spec`` (leading stack/expert axes + trailing [in, out]).

    The quantized layouts follow the dense axes directly (the reference
    quantizes after its TP wrap, convert_block.py:25-73 — same composition,
    expressed as shardings):
    - int8: data int8 [..., in, out] shards like the dense weight; scales f32
      [..., out] drop the input axis.
    - nf4/nf4a/int4: data uint8 [..., in/2, out] and scales bf16 [..., in/64, out]
      both follow the dense spec — packed rows and absmax blocks track the
      input axis, so an input-axis (row) split lands whole blocks per shard.
    """
    s = tuple(spec)
    if q.kind == "int8":
        return P(*s), P(*s[:-2], s[-1])
    return P(*s), P(*s)


def validate_tp_divisibility(params, mesh, specs, *, num_kv_heads: int = None) -> None:
    """Fail fast with a clear message instead of an opaque GSPMD error at
    session-open time."""
    from petals_tpu.ops.quant import NF4_BLOCK, QuantizedLinear

    tp_size = mesh.shape.get(COL, 1)
    if tp_size == 1:
        return
    if num_kv_heads is not None and num_kv_heads % tp_size != 0:
        raise ValueError(
            f"num_key_value_heads={num_kv_heads} is not divisible by the tensor-"
            f"parallel axis size {tp_size}; use a smaller tp mesh for this model"
        )
    for name, leaf in params.items():
        spec = specs[name]
        is_quant = isinstance(leaf, QuantizedLinear)
        shape = leaf.shape  # QuantizedLinear.shape is the logical [..., in, out]
        for dim, axis in enumerate(tuple(spec)):
            if axis != COL:
                continue
            if shape[dim] % tp_size != 0:
                raise ValueError(
                    f"Parameter {name!r} dim {dim} (size {shape[dim]}) is not "
                    f"divisible by the tensor-parallel axis size {tp_size}"
                )
            if is_quant and leaf.kind in ("nf4", "nf4a", "int4") and dim == len(shape) - 2:
                # input-axis split: every shard must hold whole absmax blocks
                blocks = leaf.data.shape[-2] * 2 // NF4_BLOCK
                if blocks % tp_size != 0:
                    raise ValueError(
                        f"{leaf.kind} parameter {name!r} has {blocks} absmax blocks, "
                        f"not divisible by the tensor-parallel axis size {tp_size}"
                    )


def shard_span_params(params, mesh, family_name: str, cfg):
    """device_put the stacked params with TP shardings over ``mesh``."""
    import jax

    from petals_tpu.ops.quant import OutlierQuantLinear, QuantizedLinear

    if any(
        isinstance(v, OutlierQuantLinear)
        for v in jax.tree_util.tree_leaves(
            params, is_leaf=lambda x: isinstance(x, OutlierQuantLinear)
        )
    ):
        raise NotImplementedError(
            "outlier-augmented quantization ('+o' kinds) does not compose "
            "with tensor-parallel meshes yet — the outlier side arrays have "
            "no PartitionSpecs; use the base kind (nf4a/int4) under TP"
        )
    specs = span_param_pspecs(family_name, cfg)
    validate_tp_divisibility(
        params, mesh, specs,
        num_kv_heads=getattr(cfg, "num_key_value_heads", cfg.num_attention_heads),
    )
    out = {}
    for name, leaf in params.items():
        if isinstance(leaf, QuantizedLinear):
            data_spec, scales_spec = quant_leaf_pspecs(leaf, specs[name])
            out[name] = QuantizedLinear(
                leaf.kind,
                jax.device_put(leaf.data, NamedSharding(mesh, data_spec)),
                jax.device_put(leaf.scales, NamedSharding(mesh, scales_spec)),
                leaf.in_features,
                leaf.out_features,
            )
        else:
            out[name] = jax.device_put(leaf, NamedSharding(mesh, specs[name]))
    return out
