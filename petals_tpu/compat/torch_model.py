"""PyTorch adapter: train through the swarm from a torch pipeline.

The reference's client IS a torch ``transformers`` model (BASELINE north star:
"the RemoteSequential client stays PyTorch"); this build's native client is
JAX. This module gives torch users the same training surface against the same
swarm, without duplicating any model math:

- ``TorchRemoteSequential``: a ``torch.nn.Module`` whose forward/backward run
  the fault-tolerant swarm pipeline (client/sequential_autograd.py) through a
  ``torch.autograd.Function`` — torch gradients flow straight through remote
  servers (which recompute activations, reference block_functions.py:84-141).
- ``TorchDistributedModelForCausalLM``: embeddings + LM head evaluated by the
  native (JAX) client hooks, exposed to torch autograd via ``jax.vjp``; soft
  prompts are a plain ``torch.nn.Parameter`` trained by any torch optimizer.
  The loss formula matches client/training.compute_loss_and_grads exactly, so
  torch-side gradients are numerically identical to the native path.

Known v1 limits: ``generate()`` delegates to the native sampler and does not
apply the torch-held soft prompts; deep (per-block) prompts stay native-only.
"""

from __future__ import annotations

from types import SimpleNamespace
from typing import Optional, Sequence

import numpy as np

import torch  # CPU torch; tensors bridge via numpy (zero-copy on CPU)

from petals_tpu.client.model import DistributedModelForCausalLM
from petals_tpu.client.remote_sequential import RemoteSequential
from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class _RemoteBlocksFn(torch.autograd.Function):
    """Differentiable swarm chain: forward keeps per-span activations, backward
    replays them through rpc_backward on (possibly different) servers."""

    @staticmethod
    def forward(ctx, hidden: torch.Tensor, remote: RemoteSequential):
        np_hidden = np.ascontiguousarray(hidden.detach().cpu().numpy(), dtype=np.float32)
        out, histories, spans = remote.forward_with_state(np_hidden)
        ctx.remote, ctx.histories, ctx.spans = remote, histories, spans
        return torch.from_numpy(np.ascontiguousarray(out)).to(hidden.dtype)

    @staticmethod
    def backward(ctx, grad_out: torch.Tensor):
        grad_np = np.ascontiguousarray(grad_out.detach().cpu().numpy(), dtype=np.float32)
        grad_in, _ = ctx.remote.backward(grad_np, ctx.histories, ctx.spans)
        return torch.from_numpy(np.ascontiguousarray(grad_in)).to(grad_out.dtype), None


class _JaxFn(torch.autograd.Function):
    """Torch autograd over a frozen jax function of one array (the client
    embed/head hooks): forward runs jax.vjp, backward applies it."""

    @staticmethod
    def forward(ctx, x: torch.Tensor, jax_fn):
        import jax
        import jax.numpy as jnp

        out, vjp = jax.vjp(jax_fn, jnp.asarray(x.detach().cpu().numpy()))
        ctx.vjp, ctx.in_dtype = vjp, x.dtype
        # copy: np.asarray over a jax array is a read-only XLA-buffer view, and
        # torch.from_numpy would alias it (in-place torch ops -> UB in jax)
        return torch.from_numpy(np.array(out, copy=True))

    @staticmethod
    def backward(ctx, grad_out: torch.Tensor):
        import jax.numpy as jnp

        (grad_in,) = ctx.vjp(jnp.asarray(grad_out.detach().cpu().numpy()))
        return torch.from_numpy(np.array(grad_in, np.float32, copy=True)).to(ctx.in_dtype), None


class TorchRemoteSequential(torch.nn.Module):
    """The chain of remote blocks as a differentiable torch module."""

    def __init__(self, remote: RemoteSequential):
        super().__init__()
        self.remote = remote

    def forward(self, hidden: torch.Tensor) -> torch.Tensor:
        if not torch.is_grad_enabled():
            # eval path: no per-span activation histories retained
            np_hidden = np.ascontiguousarray(hidden.detach().cpu().numpy(), dtype=np.float32)
            return torch.from_numpy(np.ascontiguousarray(self.remote.forward(np_hidden))).to(hidden.dtype)
        return _RemoteBlocksFn.apply(hidden, self.remote)

    def close(self) -> None:
        self.remote.close()


class TorchDistributedModelForCausalLM(torch.nn.Module):
    """HF-style causal LM for torch pipelines: local embed/head (native JAX
    hooks under torch autograd), remote blocks, torch-held soft prompts."""

    def __init__(self, native: DistributedModelForCausalLM, *, pre_seq_len: int = 0):
        super().__init__()
        self.native = native
        self.cfg = native.cfg
        self.blocks = TorchRemoteSequential(native.remote)
        self.pre_seq_len = pre_seq_len
        if pre_seq_len > 0:
            # same init scale as the native ptune prompts (client/ptune.py:
            # 1/sqrt(hidden_size))
            self.prompt_embeddings = torch.nn.Parameter(
                torch.randn(pre_seq_len, self.cfg.hidden_size)
                / float(np.sqrt(self.cfg.hidden_size))
            )
        else:
            self.prompt_embeddings = None

    @classmethod
    def from_pretrained(
        cls,
        model_name_or_path: str,
        *,
        initial_peers: Sequence[str],
        pre_seq_len: int = 0,
        **kwargs,
    ) -> "TorchDistributedModelForCausalLM":
        if "ptune" in kwargs:
            # two prompt states (random JAX prompts in generate, torch prompts
            # in training) would silently diverge — prompts live torch-side here
            raise ValueError("use pre_seq_len= (torch-held prompts), not ptune=")
        native = DistributedModelForCausalLM.from_pretrained(
            model_name_or_path, initial_peers=initial_peers, **kwargs
        )
        return cls(native, pre_seq_len=pre_seq_len)

    # ------------------------------------------------------------------ forward

    def embed_tokens(self, input_ids: torch.Tensor) -> torch.Tensor:
        """Frozen token embeddings via the native hook (no grad to weights —
        matching the reference's frozen-client-embedding training setup)."""
        hidden = self.native.embed(np.asarray(input_ids.cpu().numpy()), with_prompts=False)
        return torch.from_numpy(np.array(hidden, np.float32, copy=True))

    def forward(
        self,
        input_ids: torch.Tensor,  # [batch, seq] int64
        labels: Optional[torch.Tensor] = None,  # [batch, seq], -100 = ignored
    ) -> SimpleNamespace:
        batch, seq = input_ids.shape
        hidden = self.embed_tokens(input_ids)
        if self.prompt_embeddings is not None:
            prompts = self.prompt_embeddings.unsqueeze(0).expand(batch, -1, -1)
            hidden = torch.cat([prompts.to(hidden.dtype), hidden], dim=1)

        hidden = self.blocks(hidden)

        head_fn = lambda h: self.native._head_jit(self.native.client_params, h)  # noqa: E731
        if torch.is_grad_enabled():
            logits_full = _JaxFn.apply(hidden, head_fn)  # [batch, pre+seq, vocab] f32
        else:  # eval path: plain jitted head, no vjp residuals
            logits_full = torch.from_numpy(
                np.array(head_fn(hidden.detach().cpu().numpy()), copy=True)
            )

        loss = None
        if labels is not None:
            padded = labels
            if self.pre_seq_len:
                pad = torch.full(
                    (batch, self.pre_seq_len), -100, dtype=labels.dtype, device=labels.device
                )
                padded = torch.cat([pad, labels], dim=1)
            # identical formula to client/training.compute_loss_and_grads:
            # shift over the FULL (prompt + tokens) length, mean over real
            # targets — with the native path's max(count, 1) guard, so an
            # all-ignored batch yields 0, not 0/0 = NaN
            targets = padded[:, 1:].reshape(-1)
            ce_sum = torch.nn.functional.cross_entropy(
                logits_full[:, :-1].reshape(-1, logits_full.shape[-1]),
                targets, ignore_index=-100, reduction="sum",
            )
            loss = ce_sum / (targets != -100).sum().clamp(min=1)
        logits = logits_full[:, self.pre_seq_len :]
        return SimpleNamespace(loss=loss, logits=logits)

    # ------------------------------------------------------------------ misc

    @torch.no_grad()
    def generate(self, input_ids: torch.Tensor, **kwargs) -> torch.Tensor:
        """Delegates to the native sampler (token-identical to HF); the
        torch-held soft prompts are NOT applied (v1 limitation)."""
        out = self.native.generate(np.asarray(input_ids.cpu().numpy()), **kwargs)
        return torch.from_numpy(np.array(out, copy=True))

    def close(self) -> None:
        self.native.close()
