"""PyTorch interop layer (see compat/torch_model.py)."""
