"""Server-side speculative decoding: the draft model.

The span verifies k draft tokens per lane in ONE paged-attention step
(backend.py ``paged_spec_verify_step``); this module supplies the k drafts.
A ``DraftModel`` is a SMALL full model (any registered family — embeddings,
every block, head — typically NF4A-quantized) loaded alongside the span via
``--draft_model``. It is deliberately stateless across ticks:

- No persistent draft KV cache. Each propose() call re-prefills a bounded
  token WINDOW (the last ``window`` tokens of each lane's context) into a
  fresh dense buffer and then decodes k tokens greedily. That makes drafts
  a pure function of (window tokens) — no draft-side rollback, reorder, or
  page bookkeeping when the verify step rejects a suffix, no extra state to
  migrate, and one compiled program regardless of which lanes speculate.
- Static BUCKETED shapes: speculating lanes are compacted and padded to the
  next power-of-two lane count (clamped to the pool size), so a single
  speculating lane pays for a [1, window] prefill, not the whole pool's
  [n_lanes, window] — on a half-idle pool the window prefill is the draft's
  dominant cost and it scales linearly with the padded batch. One
  ``tracked_jit`` program ("draft_propose", steady=True) per
  (bucket, window, k); :meth:`warmup` compiles every bucket up front (the
  batcher calls it on the first spec tick) so zero post-warmup recompiles —
  a gate_spec_decode acceptance bar — holds across any mix of lane counts.
- Greedy argmax proposals. Draft quality only moves the ACCEPTANCE RATE,
  never correctness: the verify step samples the target's own tokens from
  the lane's seed+offset PRNG stream and accepts drafts by exact match, so
  the emitted stream is bit-identical to plain decode whatever the draft
  says (backend.py ``_paged_spec_verify_fn`` docstring).

Window positions are chunk-local (the window re-prefills at position 0), so
a draft conditioned on a truncated context sees shifted rotary phases versus
the target. That costs acceptance on long sessions and nothing else; a
cooperative draft whose window covers the whole context (the bench setup)
sees exact positions.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from petals_tpu.telemetry.observatory import tracked_jit
from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)

DEFAULT_WINDOW = 64
# acceptance-rate EMA floor below which a lane falls back to plain decode
# (the batcher's auto-disable heuristic; see server/batching.py)
MIN_ACCEPT_ENV = "PETALS_TPU_SPEC_MIN_ACCEPT"


def min_accept_floor(default: float = 0.1) -> float:
    try:
        return float(os.environ.get(MIN_ACCEPT_ENV, default))
    except ValueError:
        return default


class DraftModel:
    """A small full model proposing k greedy tokens per lane per tick.

    ``block_params`` is a LIST of per-block parameter trees (NOT stacked):
    the propose program unrolls the block loop in Python, which sidesteps the
    quant-constant scan machinery the big span needs — draft models are small
    enough that per-block unrolling compiles in bounded time and lets NF4A
    blocks ride through ``mm``'s isinstance dispatch unchanged.
    """

    def __init__(
        self,
        family,
        cfg,
        block_params: Sequence[dict],
        client_params: dict,
        *,
        spec_k: int,
        window: int = DEFAULT_WINDOW,
        compute_dtype=jnp.float32,
    ):
        if spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {spec_k}")
        if window < 1:
            raise ValueError(f"draft window must be >= 1, got {window}")
        if family.client_embed is None or family.client_head is None:
            raise ValueError(f"{family.name} has no client embed/head mapping")
        self.family = family
        self.cfg = cfg
        self.block_params = list(block_params)
        self.client_params = client_params
        self.spec_k = int(spec_k)
        self.window = int(window)
        self.compute_dtype = compute_dtype
        self.num_kv_heads = getattr(cfg, "num_key_value_heads", cfg.num_attention_heads)
        self.head_dim = cfg.head_dim
        self._propose_fn = self._build_propose_fn()

    # ------------------------------------------------------------------ load

    @classmethod
    def from_pretrained(
        cls,
        model_name_or_path: str,
        *,
        spec_k: int,
        window: int = DEFAULT_WINDOW,
        quant_type: str = "nf4a",
        compute_dtype=jnp.float32,
        revision: str = "main",
        cache_dir=None,
    ) -> "DraftModel":
        """Load every block + the client leaves of a (small) checkpoint,
        quantizing blocks per ``quant_type`` (NF4A default — the 4-bit
        serving default, utils/convert_block.py)."""
        from petals_tpu.client.from_pretrained import load_client_params
        from petals_tpu.server.from_pretrained import get_block_config, load_block_params
        from petals_tpu.utils.convert_block import QuantType, convert_block_params

        family, cfg = get_block_config(
            model_name_or_path, revision=revision, cache_dir=cache_dir
        )
        n_blocks = cfg.num_hidden_layers
        block_params = [
            convert_block_params(
                load_block_params(
                    model_name_or_path, i, dtype=compute_dtype,
                    family=family, cfg=cfg, revision=revision, cache_dir=cache_dir,
                ),
                family.name,
                QuantType(quant_type),
            )
            for i in range(n_blocks)
        ]
        client_params = load_client_params(
            model_name_or_path, dtype=jnp.float32,
            family=family, cfg=cfg, revision=revision, cache_dir=cache_dir,
        )
        logger.info(
            f"Draft model {model_name_or_path}: {n_blocks} blocks "
            f"({quant_type}), window={window}, k={spec_k}"
        )
        return cls(
            family, cfg, block_params, client_params,
            spec_k=spec_k, window=window, compute_dtype=compute_dtype,
        )

    # --------------------------------------------------------------- program

    def _build_propose_fn(self):
        family, cfg = self.family, self.cfg
        k, W = self.spec_k, self.window
        hkv, d = self.num_kv_heads, self.head_dim
        n_blocks = len(self.block_params)
        dtype = self.compute_dtype
        client_embed, client_head = family.client_embed, family.client_head

        @tracked_jit(name="draft_propose", steady=True)
        def propose(block_params, client_params, tokens, lengths):
            # tokens: [n, W] int32 left-aligned; lengths: [n] int32 (0 =
            # lane sits this tick out; its row computes ignored garbage)
            n = tokens.shape[0]
            buf_len = W + k  # window prefill + k-1 decode writes, with slack
            caches = [
                (jnp.zeros((n, buf_len, hkv, d), dtype),
                 jnp.zeros((n, buf_len, hkv, d), dtype))
                for _ in range(n_blocks)
            ]

            def run(hidden, position):
                h = hidden.astype(dtype)
                for i, p_block in enumerate(block_params):
                    h, caches[i] = family.block_apply(
                        p_block, h, caches[i], position, cfg,
                        use_flash=False, tp_mesh=None,
                    )
                return h

            # window prefill at position 0: rows past each lane's length are
            # garbage, but causal masking keeps them out of the rows we read
            hidden = run(client_embed(client_params, tokens, cfg), 0)
            logits = client_head(client_params, hidden, cfg)  # [n, W, vocab]
            last = jnp.clip(lengths - 1, 0, W - 1)
            row = jnp.take_along_axis(logits, last[:, None, None], axis=1)[:, 0]
            tok = jnp.argmax(row, axis=-1).astype(jnp.int32)  # draft 1
            drafts = [tok]
            pos = jnp.maximum(lengths, 1)  # write the next token AT the length
            for _ in range(k - 1):
                h = run(client_embed(client_params, tok[:, None], cfg), pos)
                logits = client_head(client_params, h, cfg)[:, -1]
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                drafts.append(tok)
                pos = pos + 1
            return jnp.stack(drafts, axis=1)  # [n, k]

        return propose

    # ------------------------------------------------------------------ host

    @staticmethod
    def _buckets(max_lanes: int) -> List[int]:
        """Padded batch sizes the propose program compiles for: powers of two
        up to (and always including) ``max_lanes`` — O(log) executables."""
        out, b = [], 1
        while b < max_lanes:
            out.append(b)
            b <<= 1
        out.append(max(int(max_lanes), 1))
        return out

    def warmup(self, max_lanes: int) -> None:
        """Compile every bucket shape once, so steady state never compiles.

        The batcher calls this from the compute thread on the first spec
        tick: warmup calls land inside the observatory's per-program warmup
        budget, and afterwards any mix of speculating-lane counts hits a
        cached executable (the zero post-warmup recompile invariant)."""
        W = self.window
        for b in self._buckets(max_lanes):
            self._propose_fn(
                tuple(self.block_params), self.client_params,
                np.zeros((b, W), np.int32), np.zeros((b,), np.int32),
            )

    def propose(
        self, contexts: Sequence[Optional[Sequence[int]]]
    ) -> np.ndarray:
        """Greedy k-token proposals for a batch of lanes.

        ``contexts[i]`` is lane i's token history (prompt context, when the
        client supplied one, plus every generated token INCLUDING the last
        committed one) or None for lanes not speculating this tick. Returns
        int32 [len(contexts), k]; rows for None/empty contexts are garbage
        the caller must ignore.

        Active lanes are compacted to the front and padded to the smallest
        bucket (power of two, clamped to len(contexts)) before dispatch, so
        the compiled window-prefill cost tracks how many lanes actually
        speculate this tick rather than the pool size.
        """
        n = len(contexts)
        W = self.window
        active = [i for i, ctx in enumerate(contexts) if ctx]
        out = np.zeros((n, self.spec_k), np.int32)
        if not active:
            return out
        B = next(b for b in self._buckets(n) if b >= len(active))
        tokens = np.zeros((B, W), np.int32)
        lengths = np.zeros((B,), np.int32)
        for row, i in enumerate(active):
            tail = list(contexts[i])[-W:]
            tokens[row, : len(tail)] = tail
            lengths[row] = len(tail)
        drafts = self._propose_fn(
            tuple(self.block_params), self.client_params, tokens, lengths
        )
        drafts = np.asarray(drafts, np.int32)
        for row, i in enumerate(active):
            out[i] = drafts[row]
        return out


__all__ = ["DraftModel", "DEFAULT_WINDOW", "MIN_ACCEPT_ENV", "min_accept_floor"]
