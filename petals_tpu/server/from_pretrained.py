"""Load exactly one transformer block's weights from an HF checkpoint
(counterpart of reference src/petals/server/from_pretrained.py:35-224).

Reads local checkpoint directories (safetensors preferred, torch .bin
fallback) and selects only the tensors belonging to the requested block — the
same "load one block, not the model" capability. Non-directory names resolve
through the streaming Hub fetcher (utils/hub.py): config + shard index first,
then ONLY the shards containing the requested prefixes, with retry + flock'd
LRU disk cache (reference from_pretrained.py:81-128,162-213)."""

from __future__ import annotations

import json
import os
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from petals_tpu.models.registry import ModelFamily, get_family
from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)

from petals_tpu.constants import BIN_INDEX, BIN_SINGLE, SAFE_INDEX, SAFE_SINGLE  # noqa: F401 (re-exported)


def resolve_model_path(
    model_name_or_path: str,
    *,
    prefixes: Optional[tuple] = None,
    cache_dir=None,
    max_disk_space: Optional[int] = None,
    revision: str = "main",
) -> str:
    """Local directory, or a repo id resolved through the streaming Hub cache.

    With ``prefixes`` the weight shards containing those tensor prefixes are
    fetched too; without it only config.json is ensured (enough for
    AutoConfig / get_block_config)."""
    if os.path.isdir(model_name_or_path):
        return model_name_or_path
    from petals_tpu.utils import hub

    if prefixes is not None:
        return str(
            hub.ensure_weight_files(
                model_name_or_path, prefixes,
                cache_dir=cache_dir, max_disk_space=max_disk_space, revision=revision,
            )
        )
    return str(
        hub.ensure_config(
            model_name_or_path, cache_dir=cache_dir, max_disk_space=max_disk_space,
            revision=revision,
        )
    )


def load_hf_config(model_name_or_path: str, *, revision: str = "main", cache_dir=None):
    from transformers import AutoConfig

    return AutoConfig.from_pretrained(
        resolve_model_path(model_name_or_path, revision=revision, cache_dir=cache_dir)
    )


def get_block_config(
    model_name_or_path: str, *, revision: str = "main", cache_dir=None
) -> Tuple[ModelFamily, object]:
    hf_config = load_hf_config(model_name_or_path, revision=revision, cache_dir=cache_dir)
    family = get_family(hf_config.model_type)
    return family, family.config_from_hf(hf_config)


def _index_weight_files(path: str) -> Dict[str, str]:
    """Return {tensor_name: filename} for the checkpoint at ``path``."""
    index_file = os.path.join(path, SAFE_INDEX)
    if os.path.exists(index_file):
        with open(index_file) as f:
            return json.load(f)["weight_map"]
    index_file = os.path.join(path, BIN_INDEX)
    if os.path.exists(index_file):
        with open(index_file) as f:
            return json.load(f)["weight_map"]
    for single in (SAFE_SINGLE, BIN_SINGLE):
        fpath = os.path.join(path, single)
        if os.path.exists(fpath):
            return {"*": single}
    raise FileNotFoundError(f"No weight files found in {path}")


def _load_tensors_with_prefixes(
    path: str, prefixes: tuple, *, keep_full_names: bool = False
) -> Dict[str, np.ndarray]:
    """Read only tensors whose name starts with one of ``prefixes`` (names
    returned relative to the matching prefix, or absolute with
    ``keep_full_names`` — use that when prefixes could collide). All candidate
    prefixes are checked in a single pass so each weight file is opened at most
    once (safetensors lazily; .bin state dicts deserialized exactly once —
    reference from_pretrained.py:81-128 semantics)."""
    weight_map = _index_weight_files(path)

    def match(name: str) -> Optional[str]:
        for prefix in prefixes:
            if name.startswith(prefix):
                return name if keep_full_names else name[len(prefix):]
        return None

    if "*" in weight_map:
        files = {weight_map["*"]}
    else:
        files = {fname for name, fname in weight_map.items() if match(name) is not None}

    out: Dict[str, np.ndarray] = {}
    for fname in sorted(files):
        fpath = os.path.join(path, fname)
        if fname.endswith(".safetensors"):
            from safetensors import safe_open

            with safe_open(fpath, framework="pt") as f:
                for name in f.keys():
                    rel = match(name)
                    if rel is not None:
                        out[rel] = _torch_to_numpy(f.get_tensor(name))
        else:
            import torch

            state = torch.load(fpath, map_location="cpu", weights_only=True)
            for name, tensor in state.items():
                rel = match(name)
                if rel is not None:
                    out[rel] = _torch_to_numpy(tensor)
    return out


def _torch_to_numpy(tensor) -> np.ndarray:
    """torch -> numpy, keeping bf16 bit-exact via ml_dtypes (numpy itself has
    no bfloat16; a float32 round-trip would be lossless but 2x the memory)."""
    import torch

    if tensor.dtype == torch.bfloat16:
        import ml_dtypes

        return tensor.view(torch.uint16).numpy().view(ml_dtypes.bfloat16)
    return tensor.numpy()


def load_block_params(
    model_name_or_path: str,
    block_index: int,
    *,
    dtype=jnp.bfloat16,
    device: Optional[jax.Device] = None,
    family: Optional[ModelFamily] = None,
    cfg=None,
    revision: str = "main",
    cache_dir=None,
) -> dict:
    """Load block ``block_index`` and return our parameter pytree on device."""
    if family is None or cfg is None:
        # same revision/cache as the weights, or the architecture could differ
        family, cfg = get_block_config(
            model_name_or_path, revision=revision, cache_dir=cache_dir
        )

    prefixes = tuple(tpl.format(i=block_index) for tpl in family.hf_block_prefixes)
    # for repo ids this streams in exactly the shards holding this block
    path = resolve_model_path(
        model_name_or_path, prefixes=prefixes, revision=revision, cache_dir=cache_dir
    )
    tensors = _load_tensors_with_prefixes(path, prefixes)
    if not tensors:
        raise KeyError(
            f"Block {block_index} not found in {path} under prefixes "
            f"{[p.format(i=block_index) for p in family.hf_block_prefixes]}"
        )

    import inspect

    if "block_index" in inspect.signature(family.hf_to_block_params).parameters:
        # per-layer-heterogeneous architectures (gemma2's alternating
        # windows) need to know WHICH block they are mapping
        params = family.hf_to_block_params(tensors, cfg, block_index=block_index)
    else:
        params = family.hf_to_block_params(tensors, cfg)
    cast = lambda x: jnp.asarray(x, dtype) if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else jnp.asarray(x)
    params = {
        name: (jnp.asarray(leaf) if name in family.cast_exempt
               else jax.tree_util.tree_map(cast, leaf))
        for name, leaf in params.items()
    }
    if device is not None:
        params = jax.device_put(params, device)
    return params
