"""TransformerBackend: the server's compute engine for a span of blocks
(counterpart of reference src/petals/server/backend.py:24-235).

TPU-first redesign:

- The reference wraps each block in a torch module and merges per-block task
  pools so a chain runs in one Runtime call (backend.py:201-235). Here a span's
  parameters are STACKED along a leading layer axis and the whole chain is one
  jitted ``lax.scan`` — one XLA program per step, no per-block dispatch, MXU
  stays hot. (This is also why no CUDA-graph analogue is needed.)
- KV caches are stacked too: [n_blocks, batch, max_len, kv_heads, head_dim]
  buffers live in HBM via MemoryCache handles; decode steps donate them to XLA
  so updates happen in place.
- Variable shapes are bucketed (decode=1 exact; prefill padded to powers of
  two) with the true token count passed as a dynamic scalar — each bucket
  compiles once, then every step is a cached executable
  (reference's recompile-free decode requirement, SURVEY.md §7 hard part 1).
- Beam-search cache reorder (reference backend.py:154-158) is a batch gather
  on the stacked cache, fused into the same step.
- Chunked prefill (reference backend.py:126-152): long inputs are split into
  chunks whose attention-weight footprint fits max_chunk_size_bytes.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import inspect
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from petals_tpu.models.registry import ModelFamily
from petals_tpu.ops import fingerprint as fp_ops
from petals_tpu.ops.sampling import sample_tokens, sampling_vectors
from petals_tpu.server.memory_cache import MemoryCache, TensorDescriptor
from petals_tpu.telemetry.observatory import tracked_jit
from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)

PREFILL_BUCKETS = (8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096)


def bucket_length(n: int) -> int:
    for b in PREFILL_BUCKETS:
        if n <= b:
            return b
    return -(-n // PREFILL_BUCKETS[-1]) * PREFILL_BUCKETS[-1]


@dataclasses.dataclass
class SpanDtypes:
    compute: jnp.dtype = jnp.bfloat16
    cache: jnp.dtype = jnp.bfloat16


class TransformerBackend:
    """Serves blocks [first_block, first_block + n_blocks) of one model."""

    def __init__(
        self,
        family: ModelFamily,
        cfg,
        stacked_params,  # pytree with leading n_blocks axis on every leaf
        *,
        first_block: int,
        n_blocks: int,
        memory_cache: MemoryCache,
        compute_dtype=jnp.bfloat16,
        cache_dtype=None,
        max_chunk_size_bytes: int = 256 * 1024 * 1024,
        use_flash: Optional[bool] = None,
        mesh=None,  # jax.sharding.Mesh with a "tp" axis: intra-server tensor parallelism
        kv_quant_type: str = "none",  # paged-pool encoding: none | int8 | nf4a
    ):
        self.family = family
        self.cfg = cfg
        self.params = stacked_params
        self.first_block = first_block
        self.n_blocks = n_blocks
        self.memory_cache = memory_cache
        self.compute_dtype = compute_dtype
        self.cache_dtype = cache_dtype or compute_dtype
        self.max_chunk_size_bytes = max_chunk_size_bytes
        from petals_tpu.ops.paged_attention import KV_QUANT_KINDS

        if kv_quant_type not in KV_QUANT_KINDS:
            raise ValueError(
                f"kv_quant_type must be one of {KV_QUANT_KINDS}, got {kv_quant_type!r}"
            )
        if kv_quant_type != "none" and mesh is not None:
            raise ValueError("kv_quant_type requires a mesh-less server (paged pool only)")
        if kv_quant_type == "nf4a" and cfg.head_dim % 2:
            raise ValueError(f"nf4a KV packing needs an even head_dim, got {cfg.head_dim}")
        self.kv_quant_type = kv_quant_type
        if use_flash is None:
            use_flash = jax.default_backend() == "tpu"
        self.mesh = mesh
        if mesh is not None:
            from petals_tpu.parallel.tp import shard_span_params

            self.params = shard_span_params(self.params, mesh, family.name, cfg)
            # flash stays ON: attend() runs the Pallas kernel per TP head-shard
            # via shard_map (ops/attention.py _attend_sharded) — GSPMD has no
            # partitioning rule for Mosaic custom calls, shard_map sidesteps it
        self.use_flash = use_flash

        self.num_kv_heads = getattr(cfg, "num_key_value_heads", cfg.num_attention_heads)
        self.head_dim = cfg.head_dim
        self.hidden_size = cfg.hidden_size

        if mesh is None and jax.default_backend() == "tpu":
            from petals_tpu.ops.quant import QuantizedLinear, maybe_autotune_nf4_decode

            has_nf4 = any(
                isinstance(leaf, QuantizedLinear) and leaf.kind == "nf4"
                for leaf in jax.tree_util.tree_leaves(
                    self.params, is_leaf=lambda x: isinstance(x, QuantizedLinear)
                )
            )
            if has_nf4:
                # pick the faster decode path ON THIS DEVICE before the first
                # trace bakes one in (quant.py maybe_autotune_nf4_decode)
                maybe_autotune_nf4_decode(cfg.hidden_size)
        # adapter name -> (stacked {leaf: (A, B)}, scaling); see utils/peft.py
        self.adapters: Dict[str, tuple] = {}
        self._dummy_operands: Dict[tuple, jax.Array] = {}
        # integrity observatory: the last batched step's fused activation
        # fingerprints (ops/fingerprint.py), stashed here by the step
        # wrappers — the public step-method return contracts stay unchanged
        # — and popped by the batcher on its single compute thread
        self._last_step_fp = None  # [n_lanes, FP_DIM] device array or None
        self._last_chunk_fp = None  # [FP_DIM] (mixed step's prefill chunk)

    # ------------------------------------------------------------- cache descriptors

    def cache_descriptors(self, batch_size: int, max_length: int, start: int, end: int):
        """(k, v) descriptors for blocks [start, end) of this span; under TP the
        kv-head axis is sharded over the mesh (reference backend.py:88-99's
        per-shard descriptors, expressed as one NamedSharding)."""
        n = end - start
        shape = (n, batch_size, max_length, self.num_kv_heads, self.head_dim)
        sharding = None
        if self.mesh is not None:
            from jax.sharding import NamedSharding

            from petals_tpu.parallel.tp import kv_cache_pspec

            sharding = NamedSharding(self.mesh, kv_cache_pspec())
        return (
            TensorDescriptor(shape, self.cache_dtype, sharding),
            TensorDescriptor(shape, self.cache_dtype, sharding),
        )

    def paged_cache_descriptors(self, n_pages: int, page_size: int, start: int, end: int):
        """Descriptors for the PAGED pool of blocks [start, end). Unquantized:
        (k, v), each [n, n_pages, page_size, hkv, d] in cache_dtype. Quantized
        (kv_quant_type != none): (k_codes, v_codes, k_scales, v_scales) — the
        codes in the storage dtype (int8, or uint8 with two split-half-packed
        dims per byte for nf4a) and f32 absmax scales per (page row, kv head).
        The paged path is gated to mesh-less single-host servers
        (server/batching.py), so no sharding rides these."""
        n = end - start
        shape = (n, n_pages, page_size, self.num_kv_heads, self.head_dim)
        if self.kv_quant_type == "none":
            return (
                TensorDescriptor(shape, self.cache_dtype),
                TensorDescriptor(shape, self.cache_dtype),
            )
        if self.kv_quant_type == "int8":
            codes_shape, codes_dtype = shape, jnp.int8
        else:  # nf4a
            codes_shape, codes_dtype = (*shape[:-1], self.head_dim // 2), jnp.uint8
        scales_shape = (n, n_pages, page_size, self.num_kv_heads)
        return (
            TensorDescriptor(codes_shape, codes_dtype),
            TensorDescriptor(codes_shape, codes_dtype),
            TensorDescriptor(scales_shape, jnp.float32),
            TensorDescriptor(scales_shape, jnp.float32),
        )

    def cache_bytes_per_token(self) -> int:
        """LOGICAL (dense fp) bytes per token across the span — sizes the
        dense lane cache and stays the fp baseline for capacity ratios."""
        return (
            2
            * self.n_blocks
            * self.num_kv_heads
            * self.head_dim
            * jnp.dtype(self.cache_dtype).itemsize
        )

    def kv_bytes_per_token(self) -> int:
        """WIRE bytes per token across the span: what the paged pool, host
        swap, and migration actually store/ship per token. Equals
        cache_bytes_per_token when kv_quant_type == none."""
        from petals_tpu.ops.paged_attention import kv_wire_bytes_per_token

        return 2 * self.n_blocks * kv_wire_bytes_per_token(
            self.num_kv_heads, self.head_dim, self.kv_quant_type,
            jnp.dtype(self.cache_dtype).itemsize,
        )

    # ------------------------------------------------------------- jitted programs

    def _quant_ctx(self):
        """Under a TP mesh, trace quantized matmuls via the XLA dequant path
        (Mosaic kernels cannot be GSPMD-partitioned). No-op otherwise."""
        if self.mesh is not None:
            from petals_tpu.ops.quant import force_xla_quant_matmul

            return force_xla_quant_matmul()
        return contextlib.nullcontext()

    def _slice_params(self, start: int, end: int):
        if start == 0 and end == self.n_blocks:
            return self.params
        return jax.tree_util.tree_map(lambda x: x[start:end], self.params)

    def params_for(self, active_adapter: Optional[str]):
        """Span params with the requested LoRA adapter applied (reference
        peft.py:132-170's per-request adapter selection, as a pytree arg)."""
        if not active_adapter:
            return self.params
        if active_adapter not in self.adapters:
            raise KeyError(f"Adapter {active_adapter!r} is not loaded on this server")
        from petals_tpu.utils.peft import apply_adapter

        stacked_adapter, scaling = self.adapters[active_adapter]
        return apply_adapter(self.params, stacked_adapter, scaling)

    @functools.cached_property
    def _use_quant_consts(self):
        """Quantized leaves must NOT ride the scan xs: XLA materializes each
        iteration's slice of the packed uint8 bytes at a fraction of kernel
        DMA rate, which dominated quantized decode. Instead they stay whole
        as scan CONSTS and the body hands block_apply a StackedQuantLinear
        view (stacked bytes + the loop counter); the Pallas kernel then
        DMAs its tiles straight out of the stacked array. Off under TP —
        that path traces the XLA dequant matmul, which fuses its slices."""
        from petals_tpu.ops.quant import QuantizedLinear

        return self.mesh is None and any(
            isinstance(leaf, QuantizedLinear)
            for leaf in jax.tree_util.tree_leaves(
                self.params, is_leaf=lambda x: isinstance(x, QuantizedLinear)
            )
        )

    @staticmethod
    def _split_quant(params):
        """Partition span params into (dense-for-scan-xs, quant-for-consts,
        outlier-leaf names). Only span-stacked 2-D weights ([n_blocks, in//2,
        out]) take the consts path; mixtral's stacked EXPERT leaves are 4-D
        and their block code slices experts itself — leave them in the scan
        xs. Outlier-augmented leaves split: the packed inner rides the consts
        path (kernel DMAs from the stacked bytes), the tiny idx/w_out side
        arrays ride the scan xs and are re-attached in the body."""
        from petals_tpu.ops.quant import OutlierQuantLinear, QuantizedLinear

        is_q = lambda x: isinstance(x, QuantizedLinear) and x.data.ndim == 3
        dense, quant, outlier_names = {}, {}, set()
        for k, v in params.items():
            if isinstance(v, OutlierQuantLinear) and v.inner.data.ndim == 3:
                quant[k] = v.inner
                outlier_names.add(k)
                dense[k + "__oidx"] = v.idx  # [n_blocks, k]
                dense[k + "__ow"] = v.w_out  # [n_blocks, k, out]
            elif is_q(v):
                quant[k] = v
            else:
                dense[k] = v
        return dense, quant, outlier_names

    @staticmethod
    def _reattach_quant(p_block: dict, quant_params: dict, outlier_names, block_idx):
        """Rebuild this block's quantized leaves inside a scan body: each
        consts-path weight becomes a StackedQuantLinear view at ``block_idx``,
        with outlier side arrays (threaded through the scan xs by
        _split_quant) re-attached. Shared by the session and lane-pool step
        programs so the re-attach protocol cannot drift between them."""
        from petals_tpu.ops.quant import OutlierQuantLinear, StackedQuantLinear

        p_block = dict(p_block)
        for name, q in quant_params.items():
            sq = StackedQuantLinear(
                q.kind, q.data, q.scales, block_idx, q.in_features, q.out_features
            )
            if name in outlier_names:
                sq = OutlierQuantLinear(
                    sq, p_block.pop(name + "__oidx"), p_block.pop(name + "__ow")
                )
            p_block[name] = sq
        return p_block

    @functools.cached_property
    def _inference_step_fn(self):
        family, cfg, use_flash = self.family, self.cfg, self.use_flash
        tp_mesh = self.mesh
        # sequence parallelism for KV-cached PREFILL (round-3, VERDICT weak
        # #5): chunks with seq > 1 divisible by sp shard queries over the "sp"
        # axis (attention against the replicated cache via ops/attention._attend_sharded);
        # decode steps (seq == 1) stay tp-only
        sp_size = self.mesh.shape.get("sp", 1) if self.mesh is not None else 1
        supports_sp = family.supports_ring_attention and sp_size > 1
        split_quant = self._split_quant
        use_quant_consts = self._use_quant_consts
        reattach = self._reattach_quant
        # longrope (phi3) selects rotary factors from the FINAL sequence
        # length; only families whose block accepts it get the extra operand
        takes_n_total = "n_total" in inspect.signature(family.block_apply).parameters

        @tracked_jit(
            name="inference_step",
            static_argnames=("with_prompts", "with_hypo", "padded"),
            donate_argnums=(1, 2),
        )
        def step(params, k_stack, v_stack, hidden, position, n_valid, n_total,
                 prompts, hypo_ids,
                 *, with_prompts: bool, with_hypo: bool, padded: bool):
            hidden = hidden.astype(k_stack.dtype)
            use_sp = supports_sp and hidden.shape[1] > 1 and hidden.shape[1] % sp_size == 0
            if use_sp:
                from jax.sharding import NamedSharding, PartitionSpec as P

                hidden = jax.lax.with_sharding_constraint(
                    hidden, NamedSharding(tp_mesh, P(None, "sp", None))
                )
            if with_hypo:
                # beam search: reorder per-sequence cache lanes in place
                k_stack = jnp.take(k_stack, hypo_ids, axis=1)
                v_stack = jnp.take(v_stack, hypo_ids, axis=1)

            if with_prompts:
                # deep prompts cover absolute positions [0, pre_seq): add the
                # overlap with this chunk [position, position + seq)
                pre_seq = prompts.shape[2]
                seq = hidden.shape[1]
                pos_in_chunk = position + jnp.arange(seq, dtype=jnp.int32)
                prompt_mask = (pos_in_chunk < pre_seq)[None, :, None]

            if use_quant_consts:
                dense_params, quant_params, outlier_names = split_quant(params)
                n = k_stack.shape[0]
                scan_xs_params = dense_params
                block_indices = jnp.arange(n, dtype=jnp.int32)
            else:
                scan_xs_params = params
                block_indices = jnp.zeros((k_stack.shape[0],), jnp.int32)  # unused

            def body(h, xs):
                p_block, k_block, v_block, prompt, block_idx = xs
                if use_quant_consts:
                    p_block = reattach(p_block, quant_params, outlier_names, block_idx)
                if with_prompts:
                    seq = h.shape[1]
                    pre = prompt.shape[1]
                    # gather the prompt rows aligned with this chunk's positions
                    idx = jnp.clip(position + jnp.arange(seq, dtype=jnp.int32), 0, pre - 1)
                    aligned = jnp.take(prompt, idx, axis=1)
                    h = h + jnp.where(prompt_mask, aligned, 0).astype(h.dtype)
                extra = (
                    {"ring_mesh": tp_mesh if use_sp else None}
                    if family.supports_ring_attention
                    else {}
                )
                if takes_n_total:
                    extra["n_total"] = n_total
                out, (k_new, v_new) = family.block_apply(
                    p_block, h, (k_block, v_block), position, cfg,
                    use_flash=use_flash, n_valid=n_valid if padded else None,
                    tp_mesh=tp_mesh, **extra,
                )
                return out, (k_new, v_new)

            hidden, (k_stack, v_stack) = jax.lax.scan(
                body, hidden, (scan_xs_params, k_stack, v_stack, prompts, block_indices)
            )
            return hidden, k_stack, v_stack

        return step

    @functools.cached_property
    def _batched_decode_fn(self):
        """One decode step for MANY independent sessions at once — the
        continuous-batching hot path (beats the reference, whose task pools
        explicitly never batch across requests: reference task_pool.py:35-36).

        The whole lane pool rides every step with a per-lane position vector:
        lanes without a request this step carry the out-of-range sentinel
        (pool length), so their KV writes drop (scatter mode="drop") and
        their outputs are ignored. One shape -> ONE compiled program, no
        recompilation as sessions join and leave mid-flight; decode is
        weight-bandwidth-bound, so the extra lanes are nearly free.

        Under a TP mesh (incl. multi-host lockstep) the batched step shards
        like the single-session step: params carry their PartitionSpecs, the
        pool's kv-head axis is sharded, and block_apply inserts the psum —
        decode steps are seq==1, so no sp handling is needed here."""
        family, cfg = self.family, self.cfg
        tp_mesh = self.mesh
        split_quant = self._split_quant
        use_quant_consts = self._use_quant_consts
        reattach = self._reattach_quant
        fp_proj = fp_ops.projection(cfg.hidden_size)  # baked constant

        cache_dtype = jnp.dtype(self.cache_dtype)

        @tracked_jit(
            name="batched_decode", steady=True,
            static_argnames=("with_fp",), donate_argnums=(1, 2),
        )
        def step(params, k_pool, v_pool, hidden, positions, *, with_fp: bool):
            # hidden: [n_lanes, 1, hidden]; positions: [n_lanes] int32
            hidden = hidden.astype(cache_dtype)
            if use_quant_consts:
                dense_params, quant_params, outlier_names = split_quant(params)
                xs_params = dense_params
                block_indices = jnp.arange(k_pool.shape[0], dtype=jnp.int32)
            else:
                xs_params = params
                block_indices = jnp.zeros((k_pool.shape[0],), jnp.int32)  # unused

            def body(h, xs):
                p_block, k_block, v_block, block_idx = xs
                if use_quant_consts:
                    p_block = reattach(p_block, quant_params, outlier_names, block_idx)
                out, (k_new, v_new) = family.block_apply(
                    p_block, h, (k_block, v_block), positions, cfg,
                    use_flash=False, tp_mesh=tp_mesh,
                )
                return out, (k_new, v_new)

            hidden, (k_pool, v_pool) = jax.lax.scan(
                body, hidden, (xs_params, k_pool, v_pool, block_indices)
            )
            if with_fp:
                # fused integrity fingerprint: one [n_lanes, hidden] x
                # [hidden, FP_DIM] matmul on the post-span hidden state —
                # the digest the client re-derives from its reply
                fp = fp_ops.fingerprint_rows(hidden[:, -1, :], fp_proj)
                return hidden, k_pool, v_pool, fp
            return hidden, k_pool, v_pool

        return step

    def batched_decode_step(self, hidden, pool_kv, positions, handles=None):
        """One coalesced decode step over the whole lane pool.

        Args:
          hidden: [n_lanes, 1, hidden] (idle lanes: any finite filler).
          pool_kv: (k, v) pool buffers [n_blocks, n_lanes, max_len, hkv, d].
          positions: int32 [n_lanes]; idle lanes hold max_len (the sentinel).
          handles: ignored here; the lockstep wrapper uses the pool's mirror
            handle to address the workers' copy (parallel/multihost.py).
        """
        k_pool, v_pool = pool_kv
        if not isinstance(hidden, jax.Array):
            hidden = np.ascontiguousarray(hidden)
        with_fp = fp_ops.enabled()
        with self._quant_ctx():  # mesh: XLA dequant path (Mosaic can't GSPMD)
            res = self._batched_decode_fn(
                self.params, k_pool, v_pool, hidden,
                np.asarray(positions, np.int32), with_fp=with_fp,
            )
        if with_fp:
            out, k_pool, v_pool, self._last_step_fp = res
        else:
            out, k_pool, v_pool = res
            self._last_step_fp = None
        return out, (k_pool, v_pool)

    def _paged_kernel_path(self, k_pool, tables, *, mixed: bool = False) -> str:
        """Resolve (host-side, O(1) — no table scan) which attention path the
        paged step traces, running the once-per-process autotune for this
        shape class first. The returned string rides as a STATIC argument of
        the jitted step: its only job is to force a retrace when the resolved
        decision changes (env override flip, fresh autotune result) — in
        steady state it is one constant and costs zero extra compiles."""
        from petals_tpu.ops import paged_flash_attention as pfa

        cfg = self.cfg
        # k_pool.shape answers the LOGICAL geometry for quantized pools too
        page_size, hkv, d = k_pool.shape[2], k_pool.shape[3], k_pool.shape[4]
        window = getattr(cfg, "sliding_window", None)
        window = window if isinstance(window, int) and window > 0 else None
        key = pfa.shape_class(
            tables.shape[0], tables.shape[1], page_size, hkv, d, window,
            self.kv_quant_type,
        )
        if not getattr(self, "_paged_autotuned", False):
            heads = getattr(cfg, "num_attention_heads", hkv)
            pfa.maybe_autotune_paged_attention(
                n_lanes=key[0], max_pages=key[1], page_size=page_size,
                hkv=hkv, d=d, group=max(1, heads // hkv), window=window,
                kv_quant=self.kv_quant_type,
            )
            self._paged_autotuned = True
        path = pfa.resolve_paged_kernel_path("decode", key)
        if mixed:
            path = f"dec:{path},pf:{pfa.resolve_paged_kernel_path('prefill', key)}"
        return path

    @functools.cached_property
    def _paged_decode_fn(self):
        """Paged twin of ``_batched_decode_fn``: the pool is page-granular
        ([n_blocks, n_pages, page_size, hkv, d]) and the (pool, block-table)
        pair rides through the model family's block code as a ``PagedKV``
        stand-in for the dense buffer — ``update_kv_cache`` scatters the new
        token rows straight into the pages and ``attend`` dispatches to the
        fused ragged kernel or its XLA-composed fallback
        (ops/paged_flash_attention.py). ONE attention code path: dense is
        just the identity block table, with no host-side contiguity special
        case. ``kernel_path`` is a static pass-through whose only job is to
        retrace the step when the resolved kernel decision changes."""
        family, cfg = self.family, self.cfg
        split_quant = self._split_quant
        use_quant_consts = self._use_quant_consts
        reattach = self._reattach_quant
        fp_proj = fp_ops.projection(cfg.hidden_size)  # baked constant

        from petals_tpu.ops.paged_attention import PagedKV

        cache_dtype = jnp.dtype(self.cache_dtype)

        @tracked_jit(
            name="paged_decode", steady=True,
            static_argnames=("kernel_path", "with_fp"), donate_argnums=(1, 2),
        )
        def step(params, k_pool, v_pool, hidden, positions, tables,
                 *, kernel_path: str, with_fp: bool):
            # hidden: [n_lanes, 1, hidden]; positions: [n_lanes] int32;
            # tables: [n_lanes, max_pages] int32 (-1 = unallocated slot)
            del kernel_path  # static retrace trigger; attend() re-resolves
            hidden = hidden.astype(cache_dtype)
            if use_quant_consts:
                dense_params, quant_params, outlier_names = split_quant(params)
                xs_params = dense_params
                block_indices = jnp.arange(k_pool.shape[0], dtype=jnp.int32)
            else:
                xs_params = params
                block_indices = jnp.zeros((k_pool.shape[0],), jnp.int32)  # unused

            def body(h, xs):
                p_block, k_blk, v_blk, block_idx = xs
                if use_quant_consts:
                    p_block = reattach(p_block, quant_params, outlier_names, block_idx)
                kv = (PagedKV(k_blk, tables), PagedKV(v_blk, tables))
                out, (k_kv, v_kv) = family.block_apply(
                    p_block, h, kv, positions, cfg,
                    use_flash=False, tp_mesh=None,
                )
                return out, (k_kv.pool, v_kv.pool)

            hidden, (k_pool, v_pool) = jax.lax.scan(
                body, hidden, (xs_params, k_pool, v_pool, block_indices)
            )
            if with_fp:
                # same projection as the dense program: path-invariance —
                # identical tokens through dense vs paged yield identical
                # digests (the PR 2/3 bit-exactness contract, observable)
                fp = fp_ops.fingerprint_rows(hidden[:, -1, :], fp_proj)
                return hidden, k_pool, v_pool, fp
            return hidden, k_pool, v_pool

        return step

    def paged_decode_step(self, hidden, pool_kv, positions, tables,
                          handles=None):
        """One coalesced decode step over the whole lane pool, PAGED layout.

        Args:
          hidden: [n_lanes, 1, hidden] (idle lanes: any finite filler).
          pool_kv: (k, v) page pools [n_blocks, n_pages, page_size, hkv, d].
          positions: int32 [n_lanes]; idle sentinel = max_pages * page_size.
          tables: int32 [n_lanes, max_pages] block tables (-1 unallocated).
        """
        k_pool, v_pool = pool_kv
        tables = np.asarray(tables, np.int32)
        kernel_path = self._paged_kernel_path(k_pool, tables)
        if not isinstance(hidden, jax.Array):
            hidden = np.ascontiguousarray(hidden)
        with_fp = fp_ops.enabled()
        with self._quant_ctx():
            res = self._paged_decode_fn(
                self.params, k_pool, v_pool, hidden,
                np.asarray(positions, np.int32), tables,
                kernel_path=kernel_path, with_fp=with_fp,
            )
        if with_fp:
            out, k_pool, v_pool, self._last_step_fp = res
        else:
            out, k_pool, v_pool = res
            self._last_step_fp = None
        return out, (k_pool, v_pool)

    @functools.cached_property
    def _paged_gen_decode_fn(self):
        """Paged twin of ``_batched_gen_decode_fn``: the pooled server-gen
        step (client leaves in the loop) over the page-granular pool. Same
        PagedKV single attention path as ``_paged_decode_fn``."""
        family, cfg = self.family, self.cfg
        split_quant = self._split_quant
        use_quant_consts = self._use_quant_consts
        reattach = self._reattach_quant
        client_embed, client_head = family.client_embed, family.client_head
        fp_proj = fp_ops.projection(cfg.hidden_size)  # baked constant

        from petals_tpu.ops.paged_attention import PagedKV

        cache_dtype = jnp.dtype(self.cache_dtype)

        @tracked_jit(
            name="paged_gen_decode", steady=True,
            static_argnames=("kernel_path", "with_fp"), donate_argnums=(2, 3),
        )
        def step(params, client_params, k_pool, v_pool, hidden, tokens,
                 use_token, positions, do_sample, temperature, top_k, top_p,
                 rep_penalty, seeds, draw_idx, seen_mask, tables,
                 *, kernel_path: str, with_fp: bool):
            del kernel_path  # static retrace trigger; attend() re-resolves
            emb = client_embed(client_params, tokens[:, None], cfg)
            hidden = jnp.where(
                use_token[:, None, None],
                emb.astype(cache_dtype),
                hidden.astype(cache_dtype),
            )
            if use_quant_consts:
                dense_params, quant_params, outlier_names = split_quant(params)
                xs_params = dense_params
                block_indices = jnp.arange(k_pool.shape[0], dtype=jnp.int32)
            else:
                xs_params = params
                block_indices = jnp.zeros((k_pool.shape[0],), jnp.int32)  # unused

            def body(h, xs):
                p_block, k_blk, v_blk, block_idx = xs
                if use_quant_consts:
                    p_block = reattach(p_block, quant_params, outlier_names, block_idx)
                kv = (PagedKV(k_blk, tables), PagedKV(v_blk, tables))
                out, (k_kv, v_kv) = family.block_apply(
                    p_block, h, kv, positions, cfg,
                    use_flash=False, tp_mesh=None,
                )
                return out, (k_kv.pool, v_kv.pool)

            hidden, (k_pool, v_pool) = jax.lax.scan(
                body, hidden, (xs_params, k_pool, v_pool, block_indices)
            )
            logits = client_head(client_params, hidden, cfg)[:, -1, :]
            next_tok = sample_tokens(
                logits, do_sample=do_sample, temperature=temperature,
                top_k=top_k, top_p=top_p, repetition_penalty=rep_penalty,
                seen_mask=seen_mask, seeds=seeds, draw_idx=draw_idx,
            )
            if with_fp:
                fp = fp_ops.fingerprint_rows(hidden[:, -1, :], fp_proj)
                return hidden, next_tok, k_pool, v_pool, fp
            return hidden, next_tok, k_pool, v_pool

        return step

    def paged_gen_decode_step(self, client_params, hidden, tokens, use_token,
                              pool_kv, positions, tables, *, sampling_vecs,
                              handles=None):
        """Paged twin of ``batched_gen_decode_step`` (same argument contract
        plus the block tables)."""
        k_pool, v_pool = pool_kv
        tables = np.asarray(tables, np.int32)
        kernel_path = self._paged_kernel_path(k_pool, tables)
        if not isinstance(hidden, jax.Array):
            hidden = np.ascontiguousarray(hidden)
        v = sampling_vecs
        with_fp = fp_ops.enabled()
        with self._quant_ctx():
            res = self._paged_gen_decode_fn(
                self.params, client_params, k_pool, v_pool, hidden,
                np.asarray(tokens, np.int32), np.asarray(use_token, bool),
                np.asarray(positions, np.int32), v["do_sample"],
                v["temperature"], v["top_k"], v["top_p"],
                v["repetition_penalty"], v["seeds"], v["draw_idx"],
                v["seen_mask"], tables, kernel_path=kernel_path,
                with_fp=with_fp,
            )
        if with_fp:
            out, toks, k_pool, v_pool, self._last_step_fp = res
        else:
            out, toks, k_pool, v_pool = res
            self._last_step_fp = None
        return out, toks, (k_pool, v_pool)

    @functools.cached_property
    def _paged_spec_verify_fn(self):
        """Speculative-decode verify step: every speculating lane feeds its
        last committed token plus k draft tokens ([n_lanes, k+1] rows) through
        the span in ONE program — verification IS chunked prefill into the
        lane's pages (scatter_lane_chunk_rows writes all k+1 candidate KV rows
        per lane; attend masks per-row causally with vector q_offset).

        Acceptance is deterministic-stream: row j's logits are sampled with
        the lane's OWN seed+offset contract (draw_idx + j) to produce the
        target's token ĝ_{j+1} — exactly the token plain decode would have
        produced at that draw, conditioned on the fed prefix. A draft token
        d_j is accepted iff it equals ĝ_j AND every earlier draft matched
        (cumprod of the match vector); the lane emits m = min(a + 1, k + 1)
        tokens ĝ_1..ĝ_m, so the emitted stream is BIT-IDENTICAL to plain
        decode by construction, for greedy and sampling lanes alike — the
        distribution-preservation bar the parity tests pin down.

        Rollback is position truncation: rows past ĝ_m stay in the pages but
        are masked by kv_length (= position + 1 on every later step) and
        overwritten as the lane advances through them — no page frees, no
        refcount edits, which is what keeps the ledger conservation invariant
        trivially intact. The repetition-penalty seen-mask accumulates the
        FED token before sampling each row (idempotent for row 0's already-
        seen committed token), matching plain decode's per-token host update.
        Non-speculating lanes ride along with the idle sentinel position:
        their writes drop and their outputs are ignored."""
        family, cfg = self.family, self.cfg
        split_quant = self._split_quant
        use_quant_consts = self._use_quant_consts
        reattach = self._reattach_quant
        client_embed, client_head = family.client_embed, family.client_head
        fp_proj = fp_ops.projection(cfg.hidden_size)  # baked constant

        from petals_tpu.ops.paged_attention import PagedKV

        cache_dtype = jnp.dtype(self.cache_dtype)

        @tracked_jit(
            name="paged_spec_verify", steady=True,
            static_argnames=("kernel_path", "with_fp"), donate_argnums=(1, 2),
        )
        def step(params, k_pool, v_pool, client_params, tokens, positions,
                 do_sample, temperature, top_k, top_p, rep_penalty, seeds,
                 draw_idx, seen_mask, tables, *, kernel_path: str,
                 with_fp: bool):
            # tokens: [n_lanes, S] int32 (S = spec_k + 1): column 0 is the
            # lane's last committed token, columns 1..S-1 the draft proposals;
            # positions: [n_lanes] int32, idle sentinel for non-spec lanes
            del kernel_path  # static retrace trigger; attend() re-resolves
            S = tokens.shape[1]
            hidden = client_embed(client_params, tokens, cfg).astype(cache_dtype)
            if use_quant_consts:
                dense_params, quant_params, outlier_names = split_quant(params)
                xs_params = dense_params
                block_indices = jnp.arange(k_pool.shape[0], dtype=jnp.int32)
            else:
                xs_params = params
                block_indices = jnp.zeros((k_pool.shape[0],), jnp.int32)  # unused

            def body(h, xs):
                p_block, k_blk, v_blk, block_idx = xs
                if use_quant_consts:
                    p_block = reattach(p_block, quant_params, outlier_names, block_idx)
                kv = (PagedKV(k_blk, tables), PagedKV(v_blk, tables))
                out, (k_kv, v_kv) = family.block_apply(
                    p_block, h, kv, positions, cfg,
                    use_flash=False, tp_mesh=None,
                )
                return out, (k_kv.pool, v_kv.pool)

            hidden, (k_pool, v_pool) = jax.lax.scan(
                body, hidden, (xs_params, k_pool, v_pool, block_indices)
            )
            logits = client_head(client_params, hidden, cfg)  # [n, S, vocab]
            vocab_ids = jnp.arange(logits.shape[-1], dtype=jnp.int32)[None, :]
            emitted = []
            seen = seen_mask
            for j in range(S):  # S is static and small (spec_k + 1)
                # plain decode adds each fed token to the penalty set before
                # the next draw; row 0's committed token is already in the
                # host mask, so the OR is idempotent there
                seen = seen | (vocab_ids == tokens[:, j][:, None])
                g_j = sample_tokens(
                    logits[:, j], do_sample=do_sample, temperature=temperature,
                    top_k=top_k, top_p=top_p, repetition_penalty=rep_penalty,
                    seen_mask=seen, seeds=seeds, draw_idx=draw_idx + j,
                )
                emitted.append(g_j)
            g_hat = jnp.stack(emitted, axis=1)  # [n, S]
            # leading-match count: draft d_j (tokens column j) verifies
            # against ĝ_j (emitted row j-1); a mismatch invalidates every
            # later row's conditioning, hence the cumprod prefix
            match = (tokens[:, 1:] == g_hat[:, :-1]).astype(jnp.int32)
            n_accept = jnp.sum(jnp.cumprod(match, axis=1), axis=1)  # [n]
            n_emit = jnp.minimum(n_accept + 1, S).astype(jnp.int32)
            if with_fp:
                # per-lane digest of the LAST EMITTED row's hidden state —
                # the spec twin of decode's hidden[:, -1, :] digest
                last = jnp.take_along_axis(
                    hidden, jnp.clip(n_emit - 1, 0, S - 1)[:, None, None], axis=1
                )[:, 0, :]
                fp = fp_ops.fingerprint_rows(last, fp_proj)
                return g_hat, n_emit, k_pool, v_pool, fp
            return g_hat, n_emit, k_pool, v_pool

        return step

    def paged_spec_verify_step(self, client_params, tokens, pool_kv,
                               positions, tables, *, sampling_vecs,
                               handles=None):
        """One batched draft–verify step over the lane pool (PAGED layout).

        Args:
          client_params: the span-holder's client leaves (embed/norm/head).
          tokens: int32 [n_lanes, spec_k + 1] — column 0 the last committed
            token per lane, columns 1.. the draft proposals (non-spec lanes:
            anything; their sentinel position drops every write).
          pool_kv: (k, v) page pools [n_blocks, n_pages, page_size, hkv, d].
          positions: int32 [n_lanes]; idle sentinel = max_pages * page_size.
          tables: int32 [n_lanes, max_pages] block tables (-1 unallocated).
          sampling_vecs: per-lane sampling parameter dict (sampling_vectors).

        Returns (g_hat [n_lanes, spec_k+1] int32, n_emit [n_lanes] int32,
        pool_kv): lane i must commit exactly g_hat[i, :n_emit[i]].
        """
        k_pool, v_pool = pool_kv
        tables = np.asarray(tables, np.int32)
        kernel_path = self._paged_kernel_path(k_pool, tables)
        v = sampling_vecs
        with_fp = fp_ops.enabled()
        with self._quant_ctx():
            res = self._paged_spec_verify_fn(
                self.params, k_pool, v_pool, client_params,
                np.asarray(tokens, np.int32), np.asarray(positions, np.int32),
                v["do_sample"], v["temperature"], v["top_k"], v["top_p"],
                v["repetition_penalty"], v["seeds"], v["draw_idx"],
                v["seen_mask"], tables, kernel_path=kernel_path,
                with_fp=with_fp,
            )
        if with_fp:
            g_hat, n_emit, k_pool, v_pool, self._last_step_fp = res
        else:
            g_hat, n_emit, k_pool, v_pool = res
            self._last_step_fp = None
        return g_hat, n_emit, (k_pool, v_pool)

    @functools.cached_property
    def _paged_mixed_step_fn(self):
        """Mixed prefill+decode step — the unified continuous-batching
        program ("Ragged Paged Attention" folding, PAPERS.md): every decode
        lane advances one token AND one lane runs a bucketed prefill chunk,
        in a single jitted scan over the page pool. The decode half is
        ``_paged_decode_fn``'s body verbatim; the prefill half wraps the
        chunk lane's table row as a single-lane PagedKV and runs the SAME
        block compute as the exclusive path (``_inference_step_fn`` at
        batch=1: scalar position, bucket-padded chunk with n_valid
        scatter-drop, n_total for longrope) — update_kv_cache scatters only
        the chunk's freshly written KV rows straight into the pages and
        attend dispatches to the fused prefill kernel or its XLA fallback.
        No lane extract/insert round-trip, so concurrent decode never stalls
        behind a prefill; lanes' pages are disjoint (the prefill lane's
        decode position is the idle sentinel, so its decode-side write
        drops), so decode-before-prefill ordering is immaterial."""
        family, cfg = self.family, self.cfg
        split_quant = self._split_quant
        use_quant_consts = self._use_quant_consts
        reattach = self._reattach_quant
        takes_n_total = "n_total" in inspect.signature(family.block_apply).parameters
        fp_proj = fp_ops.projection(cfg.hidden_size)  # baked constant

        from petals_tpu.ops.paged_attention import PagedKV

        cache_dtype = jnp.dtype(self.cache_dtype)

        @tracked_jit(
            name="paged_mixed_step", steady=True,
            static_argnames=("kernel_path", "with_fp"), donate_argnums=(1, 2),
        )
        def step(params, k_pool, v_pool, hidden, positions, tables,
                 chunk_hidden, chunk_lane, chunk_pos, chunk_n_valid,
                 chunk_n_total, *, kernel_path: str, with_fp: bool):
            # hidden: [n_lanes, 1, hidden]; positions: [n_lanes] int32 (idle
            # sentinel = max_len); chunk_hidden: [1, B, hidden] (B = static
            # bucket); chunk_lane/chunk_pos/chunk_n_valid/chunk_n_total:
            # int32 scalars describing the ONE prefill chunk riding this step
            del kernel_path  # static retrace trigger; attend() re-resolves
            B = chunk_hidden.shape[1]
            hidden = hidden.astype(cache_dtype)
            chunk_hidden = chunk_hidden.astype(cache_dtype)
            table_row = jnp.take(tables, chunk_lane, axis=0)  # [max_pages]
            if use_quant_consts:
                dense_params, quant_params, outlier_names = split_quant(params)
                xs_params = dense_params
                block_indices = jnp.arange(k_pool.shape[0], dtype=jnp.int32)
            else:
                xs_params = params
                block_indices = jnp.zeros((k_pool.shape[0],), jnp.int32)  # unused

            def body(carry, xs):
                h_dec, h_pf = carry
                p_block, k_blk, v_blk, block_idx = xs
                if use_quant_consts:
                    p_block = reattach(p_block, quant_params, outlier_names, block_idx)
                # --- decode half (== _paged_decode_fn body)
                kv = (PagedKV(k_blk, tables), PagedKV(v_blk, tables))
                out_dec, (k_kv, v_kv) = family.block_apply(
                    p_block, h_dec, kv, positions, cfg,
                    use_flash=False, tp_mesh=None,
                )
                k_blk, v_blk = k_kv.pool, v_kv.pool
                # --- prefill half: the chunk lane's table row as a
                # single-lane PagedKV; writes land in the pages directly
                kv_pf = (PagedKV(k_blk, table_row[None]), PagedKV(v_blk, table_row[None]))
                extra = {"n_total": chunk_n_total} if takes_n_total else {}
                out_pf, (k_kv, v_kv) = family.block_apply(
                    p_block, h_pf, kv_pf, chunk_pos, cfg,
                    use_flash=False, n_valid=chunk_n_valid, tp_mesh=None, **extra,
                )
                return (out_dec, out_pf), (k_kv.pool, v_kv.pool)

            (hidden, chunk_out), (k_pool, v_pool) = jax.lax.scan(
                body, (hidden, chunk_hidden),
                (xs_params, k_pool, v_pool, block_indices),
            )
            if with_fp:
                fp = fp_ops.fingerprint_rows(hidden[:, -1, :], fp_proj)
                # the chunk's digest is of its LAST VALID row — the last
                # token the client receives for this prefill chunk, which
                # is what the client-side twin re-derives
                last_row = jnp.take(
                    chunk_out[0], jnp.clip(chunk_n_valid - 1, 0, B - 1), axis=0
                )
                chunk_fp = fp_ops.fingerprint_rows(last_row[None, :], fp_proj)[0]
                return hidden, chunk_out, k_pool, v_pool, fp, chunk_fp
            return hidden, chunk_out, k_pool, v_pool

        return step

    def paged_mixed_step(self, hidden, pool_kv, positions, tables,
                         chunk_hidden, chunk_lane, chunk_pos, *,
                         n_total=None, handles=None):
        """One coalesced mixed step: every decode lane (1 token each) plus
        ONE prefill chunk for ``chunk_lane``, in a single jitted program.

        Args:
          hidden: [n_lanes, 1, hidden] (idle lanes: any finite filler).
          pool_kv: (k, v) page pools [n_blocks, n_pages, page_size, hkv, d].
          positions: int32 [n_lanes]; idle sentinel = max_pages * page_size.
            The chunk lane must carry the sentinel here — its tokens ride the
            prefill half, not the decode half.
          tables: int32 [n_lanes, max_pages] block tables (-1 unallocated).
          chunk_hidden: [1, seq, hidden], unpadded; bucket padding (and the
            matching n_valid) happens here so callers stay shape-oblivious.
          chunk_lane / chunk_pos: which lane, and the chunk's first absolute
            token position.
          n_total: final sequence length when known up front (longrope factor
            selection — same contract as inference_step); defaults to
            chunk_pos + seq.

        Returns (decode_out [n_lanes, 1, h], chunk_out [1, seq, h], pool_kv).
        """
        k_pool, v_pool = pool_kv
        tables = np.asarray(tables, np.int32)
        kernel_path = self._paged_kernel_path(k_pool, tables, mixed=True)
        if not isinstance(hidden, jax.Array):
            hidden = np.ascontiguousarray(hidden)
        seq = chunk_hidden.shape[1]
        bucket = bucket_length(seq)
        if not isinstance(chunk_hidden, jax.Array):
            chunk_hidden = np.ascontiguousarray(chunk_hidden)
            if bucket != seq:
                chunk_hidden = np.pad(
                    chunk_hidden, ((0, 0), (0, bucket - seq), (0, 0))
                )
        elif bucket != seq:
            chunk_hidden = jnp.pad(
                chunk_hidden, ((0, 0), (0, bucket - seq), (0, 0))
            )
        if n_total is None:
            n_total = int(chunk_pos) + seq
        with_fp = fp_ops.enabled()
        with self._quant_ctx():
            res = self._paged_mixed_step_fn(
                self.params, k_pool, v_pool, hidden,
                np.asarray(positions, np.int32), tables, chunk_hidden,
                np.int32(chunk_lane), np.int32(chunk_pos), np.int32(seq),
                np.int32(n_total), kernel_path=kernel_path,
                with_fp=with_fp,
            )
        if with_fp:
            out, chunk_out, k_pool, v_pool, self._last_step_fp, self._last_chunk_fp = res
        else:
            out, chunk_out, k_pool, v_pool = res
            self._last_step_fp = None
            self._last_chunk_fp = None
        if chunk_out.shape[1] != seq:
            chunk_out = chunk_out[:, :seq]
        return out, chunk_out, (k_pool, v_pool)

    @functools.cached_property
    def _paged_lane_gather_fn(self):
        """Assemble one lane's dense session-shaped view [n_blocks, 1,
        max_len, hkv, d] from its block-table row — the paged stand-in for
        ``_lane_extract_fn`` (exclusive ops: chunked prefill, kv export).
        Unallocated slots read as ZEROS: this view escapes attention (kv
        export crosses the wire), so it must never alias another tenant's
        page content — same contract as ops/paged_attention.py
        gather_pages."""

        from petals_tpu.ops.paged_attention import PagedPool, dequantize_kv

        @tracked_jit(name="paged_lane_gather")
        def f(k_pool, v_pool, table_row):
            n_blocks, n_pages, page_size = k_pool.shape[0], k_pool.shape[1], k_pool.shape[2]
            max_pages = table_row.shape[0]
            safe = jnp.clip(table_row, 0, n_pages - 1)

            def gather_leaf(arr):
                g = jnp.take(arr, safe, axis=1)
                hole = (table_row >= 0).reshape(1, -1, *([1] * (arr.ndim - 2)))
                return jnp.where(hole, g, jnp.zeros((), arr.dtype))

            def one(pool):
                # quantized pools dequantize here: the dense lane view is the
                # fp-facing boundary (prefill compute, kv export, snapshots)
                if isinstance(pool, PagedPool):
                    return dequantize_kv(
                        gather_leaf(pool.codes), gather_leaf(pool.scales), pool.kind
                    )
                return gather_leaf(pool)

            k, v = one(k_pool), one(v_pool)
            shape = (n_blocks, 1, max_pages * page_size, *k_pool.shape[3:])
            return k.reshape(shape), v.reshape(shape)

        return f

    @functools.cached_property
    def _paged_lane_scatter_fn(self):
        """Write a session-shaped lane buffer back into its pages — the paged
        stand-in for ``_lane_insert_fn`` (prefill lands its KV directly in
        the pages; unallocated slots drop). Quantized pools REQUANTIZE the
        checked-in buffer row by row — the write range was freshly computed,
        untouched rows round-trip within one quant step."""
        from petals_tpu.ops.paged_attention import PagedPool, quantize_kv_rows

        @tracked_jit(name="paged_lane_scatter", donate_argnums=(0, 1))
        def f(k_pool, v_pool, k, v, table_row):
            n_blocks, n_pages, page_size = k_pool.shape[0], k_pool.shape[1], k_pool.shape[2]
            max_pages = table_row.shape[0]
            safe = jnp.where(table_row >= 0, table_row, n_pages)

            def one(pool, buf):
                pages = buf.reshape(n_blocks, max_pages, page_size, *pool.shape[3:])
                if isinstance(pool, PagedPool):
                    codes, scales = quantize_kv_rows(pages, pool.kind)
                    return PagedPool(
                        pool.codes.at[:, safe].set(
                            codes.astype(pool.codes.dtype), mode="drop"
                        ),
                        pool.scales.at[:, safe].set(
                            scales.astype(pool.scales.dtype), mode="drop"
                        ),
                    )
                return pool.at[:, safe].set(pages.astype(pool.dtype), mode="drop")

            return one(k_pool, k), one(v_pool, v)

        return f

    @functools.cached_property
    def _swap_out_pages_fn(self):
        """Gather an explicit page list out of the pool as [n_blocks, n_slots,
        page_size, hkv, d] pairs, bound for the host swap tier (scheduler
        preemption). Non-donating: the pool stays live — the pages are only
        FREED once the host copy has landed (server/batching.py
        _swap_out_lane validates the lane generation first). Per-leaf, so a
        quantized pool swaps its PACKED codes + scales — the host tier holds
        (and the ledger bills) wire bytes, never re-inflated fp pages."""

        @tracked_jit(name="swap_out_pages")
        def f(k_pool, v_pool, pages):
            take = lambda a: jnp.take(a, pages, axis=1)
            return (
                jax.tree_util.tree_map(take, k_pool),
                jax.tree_util.tree_map(take, v_pool),
            )

        return f

    @functools.cached_property
    def _swap_in_pages_fn(self):
        """Scatter swapped-out page contents back into the pool on a FRESH
        page list (block tables make relocation free). The donating twin of
        ``_swap_out_pages_fn``; negative entries drop, mirroring
        ``_paged_lane_scatter_fn``. Per-leaf: packed pages land back
        byte-exact — swap round trips lose nothing on a quantized pool."""

        @tracked_jit(name="swap_in_pages", donate_argnums=(0, 1))
        def f(k_pool, v_pool, k_pages, v_pages, pages):
            n_pages = k_pool.shape[1]
            safe = jnp.where(pages >= 0, pages, n_pages)

            def put(pool, pg):
                return jax.tree_util.tree_map(
                    lambda a, b: a.at[:, safe].set(b.astype(a.dtype), mode="drop"),
                    pool, pg,
                )

            return put(k_pool, k_pages), put(v_pool, v_pages)

        return f

    @functools.cached_property
    def _copy_page_fn(self):
        """Duplicate one page across all blocks of the pool (the copy-on-write
        fork: a shared page must be copied before a lane writes into it).
        Per-leaf: a quantized fork copies codes + scales bytes verbatim."""

        @tracked_jit(name="copy_page", donate_argnums=(0, 1))
        def f(k_pool, v_pool, src, dst):
            def cp(a):
                page = jax.lax.dynamic_slice_in_dim(a, src, 1, axis=1)
                return jax.lax.dynamic_update_slice_in_dim(a, page, dst, axis=1)

            return (
                jax.tree_util.tree_map(cp, k_pool),
                jax.tree_util.tree_map(cp, v_pool),
            )

        return f

    @functools.cached_property
    def _lane_extract_fn(self):
        """Copy one lane out of the pool as a [n_blocks, 1, max_len, hkv, d]
        session-shaped KV pair (for non-batchable work: prefill, kv export)."""

        @tracked_jit(name="lane_extract")
        def f(k_pool, v_pool, lane):
            k = jax.lax.dynamic_slice_in_dim(k_pool, lane, 1, axis=1)
            v = jax.lax.dynamic_slice_in_dim(v_pool, lane, 1, axis=1)
            return k, v

        return f

    @functools.cached_property
    def _lane_insert_fn(self):
        # only the pool buffers are donatable (the lane tensors cannot alias
        # an output: their shapes differ from both outputs)
        @tracked_jit(name="lane_insert", donate_argnums=(0, 1))
        def f(k_pool, v_pool, k, v, lane):
            k_pool = jax.lax.dynamic_update_slice_in_dim(
                k_pool, k.astype(k_pool.dtype), lane, axis=1
            )
            v_pool = jax.lax.dynamic_update_slice_in_dim(
                v_pool, v.astype(v_pool.dtype), lane, axis=1
            )
            return k_pool, v_pool

        return f

    @functools.cached_property
    def _forward_fn(self):
        family, cfg = self.family, self.cfg
        tp_mesh = self.mesh
        # sequence parallelism on the stateless (no-KV) path: activations ride
        # the "sp" axis and attention runs as a ring over it (ops/
        # ring_attention.py) — the long-context training/forward path scales
        # past one chip's activation memory
        sp_size = self.mesh.shape.get("sp", 1) if self.mesh is not None else 1
        supports_ring = family.supports_ring_attention and sp_size > 1

        # The training path (forward + vjp-recompute backward) NEVER uses the
        # Pallas flash kernel: it has no reverse-mode AD rule, and keeping
        # forward and backward on the same (XLA) attention means the backward
        # recompute linearizes exactly what the client saw.
        @tracked_jit(name="forward", static_argnames=("with_prompts",))
        def fwd(params, hidden, prompts, *, with_prompts: bool):
            use_ring = supports_ring and hidden.shape[1] % sp_size == 0
            if use_ring:
                from jax.sharding import NamedSharding, PartitionSpec as P

                hidden = jax.lax.with_sharding_constraint(
                    hidden, NamedSharding(tp_mesh, P(None, "sp", None))
                )

            def body(h, xs):
                p_block, prompt = xs
                if with_prompts:
                    pre = prompt.shape[1]
                    h = h.at[:, :pre].add(prompt.astype(h.dtype))
                extra = (
                    {"ring_mesh": tp_mesh if use_ring else None}
                    if family.supports_ring_attention
                    else {}
                )
                out, _ = family.block_apply(
                    p_block, h, None, 0, cfg, use_flash=False, tp_mesh=tp_mesh, **extra
                )
                return out, None

            hidden, _ = jax.lax.scan(body, hidden, (params, prompts))
            return hidden

        return fwd

    @functools.cached_property
    def _backward_fn(self):
        fwd_raw = self._forward_fn.__wrapped__  # un-jitted closure for vjp

        @tracked_jit(name="backward", static_argnames=("with_prompts",))
        def bwd(params, hidden, prompts, grad_out, *, with_prompts: bool):
            def f(h, p):
                return fwd_raw(params, h, p, with_prompts=with_prompts)

            _, vjp = jax.vjp(f, hidden, prompts)
            grad_hidden, grad_prompts = vjp(grad_out.astype(hidden.dtype))
            return grad_hidden, grad_prompts

        return bwd

    @functools.cached_property
    def _server_gen_fn(self):
        """Device-resident greedy generation: sample -> embed -> span-scan ->
        sample, the whole multi-token loop as ONE jitted lax.scan. The
        per-token serving path pays a host<->device round trip per token for
        the logits (on this testbed's tunnel that is ~65 ms of a ~72 ms step;
        on local hardware it is still the dominant single-stream decode cost
        after weights) — a full-span server holding the client leaves can
        amortize it over n tokens. Token parity with the client path: the
        same family client_head/client_embed hooks compute logits in f32 and
        the embed rides the identical cast into the span step.

        Ordering keeps the session resume convention: the FIRST token comes
        from the caller-provided last hidden (the prefill/step output), each
        scan iteration feeds token t_i and samples t_{i+1}, and the LAST
        sampled token is never fed — exactly like the client loop, so a
        follow-up step sends it as the unseen suffix."""
        family, cfg = self.family, self.cfg
        step_fn = self._inference_step_fn
        client_embed, client_head = family.client_embed, family.client_head

        @tracked_jit(
            name="server_gen", static_argnames=("n_tokens",), donate_argnums=(2, 3)
        )
        def gen(span_params, client_params, k_stack, v_stack, last_hidden,
                position, dummy_prompts, dummy_hypo, *, n_tokens: int):
            def sample(h):
                logits = client_head(client_params, h[:, -1:], cfg)
                return jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)  # [b]

            t0 = sample(last_hidden)

            def body(carry, _):
                tok, k_stack, v_stack, pos = carry
                h_in = client_embed(client_params, tok[:, None], cfg)
                out, k_stack, v_stack = step_fn(
                    span_params, k_stack, v_stack, h_in, pos, jnp.int32(1),
                    pos + 1, dummy_prompts, dummy_hypo,
                    with_prompts=False, with_hypo=False, padded=False,
                )
                nt = sample(out)
                return (nt, k_stack, v_stack, pos + 1), nt

            (_, k_stack, v_stack, _), toks = jax.lax.scan(
                body,
                (t0, k_stack, v_stack, jnp.asarray(position, jnp.int32)),
                None,
                length=n_tokens - 1,
            )
            tokens = jnp.concatenate([t0[None], toks], axis=0)  # [n, b]
            return tokens.T, k_stack, v_stack

        return gen

    @functools.cached_property
    def _server_gen_sampled_fn(self):
        """Sampling twin of ``_server_gen_fn``: the same sample -> embed ->
        span-scan loop with the ops/sampling warp pipeline (repetition
        penalty -> temperature -> top-k -> top-p -> inverse-CDF draw)
        compiled into each iteration. The PRNG schedule is stateless —
        draw ``i`` uses fold_in(PRNGKey(seed), i) — so the client can
        replay the uniform stream for mid-stream fallback, and a fixed
        seed is bit-reproducible across runs. The greedy fn stays separate
        and untouched: greedy sessions keep their existing (already
        compiled) executable and never pay for the warp stages."""
        family, cfg = self.family, self.cfg
        step_fn = self._inference_step_fn
        client_embed, client_head = family.client_embed, family.client_head

        @tracked_jit(
            name="server_gen_sampled", static_argnames=("n_tokens",),
            donate_argnums=(2, 3),
        )
        def gen(span_params, client_params, k_stack, v_stack, last_hidden,
                position, dummy_prompts, dummy_hypo, do_sample, temperature,
                top_k, top_p, rep_penalty, seeds, draw0, seen0,
                *, n_tokens: int):
            batch = seen0.shape[0]

            def sample(h, seen, idx):
                logits = client_head(client_params, h[:, -1:], cfg)[:, -1, :]
                return sample_tokens(
                    logits, do_sample=do_sample, temperature=temperature,
                    top_k=top_k, top_p=top_p, repetition_penalty=rep_penalty,
                    seen_mask=seen, seeds=seeds, draw_idx=idx,
                )

            def mark(seen, tok):
                return seen.at[jnp.arange(batch), tok].set(True)

            t0 = sample(last_hidden, seen0, draw0)

            def body(carry, _):
                tok, k_stack, v_stack, pos, seen, idx = carry
                seen = mark(seen, tok)
                h_in = client_embed(client_params, tok[:, None], cfg)
                out, k_stack, v_stack = step_fn(
                    span_params, k_stack, v_stack, h_in, pos, jnp.int32(1),
                    pos + 1, dummy_prompts, dummy_hypo,
                    with_prompts=False, with_hypo=False, padded=False,
                )
                nt = sample(out, seen, idx)
                return (nt, k_stack, v_stack, pos + 1, seen, idx + 1), nt

            (_, k_stack, v_stack, _, _, _), toks = jax.lax.scan(
                body,
                (t0, k_stack, v_stack, jnp.asarray(position, jnp.int32),
                 seen0, draw0 + 1),
                None,
                length=n_tokens - 1,
            )
            tokens = jnp.concatenate([t0[None], toks], axis=0)  # [n, b]
            return tokens.T, k_stack, v_stack

        return gen

    def generate_tokens(
        self, client_params, last_hidden, kv, position: int, n_tokens: int,
        *, active_adapter: Optional[str] = None,
        sampling: Optional[dict] = None,
    ):
        """Generate ``n_tokens`` on device from ``last_hidden`` (the span
        output of the last fed token) — greedy by default, sampled when a
        validated ``sampling`` dict (rpc/protocol.validate_gen_sampling
        schema) is given. Feeds n_tokens - 1 tokens into the cache (the
        final token stays unfed, client-loop convention).
        Returns (tokens [batch, n_tokens] int32, (k_stack, v_stack))."""
        assert client_params is not None
        k_stack, v_stack = kv
        batch = k_stack.shape[1]
        if position + n_tokens - 1 > k_stack.shape[2]:
            raise ValueError(
                f"Generating {n_tokens} tokens at position {position} overflows "
                f"the allocated cache ({k_stack.shape[2]} tokens)"
            )
        span_params = self.params_for(active_adapter)
        dummy_p = self._dummy_operand(
            (self.n_blocks, batch, 0, self.hidden_size), self.compute_dtype
        )
        dummy_h = self._dummy_operand((batch,), jnp.int32)
        with self._quant_ctx():
            if sampling is None:
                tokens, k_stack, v_stack = self._server_gen_fn(
                    span_params, client_params, k_stack, v_stack,
                    jnp.asarray(last_hidden), np.int32(position), dummy_p,
                    dummy_h, n_tokens=int(n_tokens),
                )
            else:
                vec = sampling_vectors(batch, self.cfg.vocab_size, sampling)
                tokens, k_stack, v_stack = self._server_gen_sampled_fn(
                    span_params, client_params, k_stack, v_stack,
                    jnp.asarray(last_hidden), np.int32(position), dummy_p,
                    dummy_h, vec["do_sample"], vec["temperature"],
                    vec["top_k"], vec["top_p"], vec["repetition_penalty"],
                    vec["seeds"], vec["draw_idx"], vec["seen_mask"],
                    n_tokens=int(n_tokens),
                )
        return tokens, (k_stack, v_stack)

    @functools.cached_property
    def _sample_hidden_fn(self):
        """Head + sample from a last-hidden, jitted: the lane-pool gen
        bootstrap (t0 comes from the caller's prefill/step output before the
        pooled per-token loop takes over)."""
        family, cfg = self.family, self.cfg
        client_head = family.client_head

        @tracked_jit(name="sample_hidden")
        def f(client_params, last_hidden, do_sample, temperature, top_k,
              top_p, rep_penalty, seen, seeds, draw_idx):
            logits = client_head(client_params, last_hidden[:, -1:], cfg)[:, -1, :]
            return sample_tokens(
                logits, do_sample=do_sample, temperature=temperature,
                top_k=top_k, top_p=top_p, repetition_penalty=rep_penalty,
                seen_mask=seen, seeds=seeds, draw_idx=draw_idx,
            )

        return f

    def sample_from_hidden(self, client_params, last_hidden,
                           sampling: Optional[dict] = None) -> np.ndarray:
        """Pick the next token(s) [batch] int32 from a span output — greedy
        unless a validated ``sampling`` dict is given."""
        assert client_params is not None
        batch = last_hidden.shape[0]
        vec = sampling_vectors(batch, self.cfg.vocab_size, sampling)
        with self._quant_ctx():
            tok = self._sample_hidden_fn(
                client_params, jnp.asarray(last_hidden), vec["do_sample"],
                vec["temperature"], vec["top_k"], vec["top_p"],
                vec["repetition_penalty"], vec["seen_mask"], vec["seeds"],
                vec["draw_idx"],
            )
        return np.asarray(tok)

    @functools.cached_property
    def _batched_gen_decode_fn(self):
        """One decode step over the whole lane pool with the client leaves in
        the loop: gen lanes feed their previous TOKEN (embedded on device)
        while plain decode lanes feed their client-provided hidden, the pool
        scan advances every lane at its own position, and the head + sampling
        pipeline picks each gen lane's next token — N server-gen sessions at
        different depths advance in ONE compiled program per token, sharing
        the step with ordinary per-token traffic. Per-lane sampling vectors
        let greedy and sampling sessions coexist in the same step."""
        family, cfg = self.family, self.cfg
        tp_mesh = self.mesh
        split_quant = self._split_quant
        use_quant_consts = self._use_quant_consts
        reattach = self._reattach_quant
        client_embed, client_head = family.client_embed, family.client_head
        fp_proj = fp_ops.projection(cfg.hidden_size)  # baked constant

        cache_dtype = jnp.dtype(self.cache_dtype)

        @tracked_jit(
            name="batched_gen_decode", steady=True,
            static_argnames=("with_fp",), donate_argnums=(2, 3),
        )
        def step(params, client_params, k_pool, v_pool, hidden, tokens,
                 use_token, positions, do_sample, temperature, top_k, top_p,
                 rep_penalty, seeds, draw_idx, seen_mask, *, with_fp: bool):
            # hidden: [n_lanes, 1, hidden]; tokens/use_token/positions: [n_lanes]
            emb = client_embed(client_params, tokens[:, None], cfg)
            hidden = jnp.where(
                use_token[:, None, None],
                emb.astype(cache_dtype),
                hidden.astype(cache_dtype),
            )
            if use_quant_consts:
                dense_params, quant_params, outlier_names = split_quant(params)
                xs_params = dense_params
                block_indices = jnp.arange(k_pool.shape[0], dtype=jnp.int32)
            else:
                xs_params = params
                block_indices = jnp.zeros((k_pool.shape[0],), jnp.int32)  # unused

            def body(h, xs):
                p_block, k_block, v_block, block_idx = xs
                if use_quant_consts:
                    p_block = reattach(p_block, quant_params, outlier_names, block_idx)
                out, (k_new, v_new) = family.block_apply(
                    p_block, h, (k_block, v_block), positions, cfg,
                    use_flash=False, tp_mesh=tp_mesh,
                )
                return out, (k_new, v_new)

            hidden, (k_pool, v_pool) = jax.lax.scan(
                body, hidden, (xs_params, k_pool, v_pool, block_indices)
            )
            logits = client_head(client_params, hidden, cfg)[:, -1, :]
            next_tok = sample_tokens(
                logits, do_sample=do_sample, temperature=temperature,
                top_k=top_k, top_p=top_p, repetition_penalty=rep_penalty,
                seen_mask=seen_mask, seeds=seeds, draw_idx=draw_idx,
            )
            if with_fp:
                fp = fp_ops.fingerprint_rows(hidden[:, -1, :], fp_proj)
                return hidden, next_tok, k_pool, v_pool, fp
            return hidden, next_tok, k_pool, v_pool

        return step

    def batched_gen_decode_step(self, client_params, hidden, tokens,
                                use_token, pool_kv, positions, *,
                                sampling_vecs, handles=None):
        """One coalesced decode+generate step over the whole lane pool.

        Args:
          client_params: the full-model client leaves (embed + head).
          hidden: [n_lanes, 1, hidden] — plain decode lanes' inputs (idle and
            gen lanes: any finite filler).
          tokens: int32 [n_lanes] — gen lanes' previous token (others: 0).
          use_token: bool [n_lanes] — True where the embedded token (not
            ``hidden``) is this lane's step input.
          pool_kv / positions: as in batched_decode_step (idle sentinel =
            pool length).
          sampling_vecs: per-lane parameter dict (ops/sampling.sampling_vectors
            layout: do_sample/temperature/top_k/top_p/repetition_penalty/
            seen_mask/seeds/draw_idx).
        Returns (hidden_out, next_tokens [n_lanes] i32, (k_pool, v_pool)).
        """
        k_pool, v_pool = pool_kv
        if not isinstance(hidden, jax.Array):
            hidden = np.ascontiguousarray(hidden)
        v = sampling_vecs
        with_fp = fp_ops.enabled()
        with self._quant_ctx():
            res = self._batched_gen_decode_fn(
                self.params, client_params, k_pool, v_pool, hidden,
                np.asarray(tokens, np.int32), np.asarray(use_token, bool),
                np.asarray(positions, np.int32), v["do_sample"],
                v["temperature"], v["top_k"], v["top_p"],
                v["repetition_penalty"], v["seeds"], v["draw_idx"],
                v["seen_mask"], with_fp=with_fp,
            )
        if with_fp:
            out, toks, k_pool, v_pool, self._last_step_fp = res
        else:
            out, toks, k_pool, v_pool = res
            self._last_step_fp = None
        return out, toks, (k_pool, v_pool)

    def pop_step_fp(self):
        """Take (and clear) the last batched step's fused fingerprints:
        ``(lane_fp, chunk_fp)`` device arrays or Nones. Called by the
        batcher on its single compute thread right after the step's host
        sync, so the stash never outlives its step. getattr-tolerant so
        wrapper backends (multihost lockstep) that do not run our
        ``__init__`` report (None, None) instead of raising."""
        fp = getattr(self, "_last_step_fp", None)
        chunk = getattr(self, "_last_chunk_fp", None)
        self._last_step_fp = None
        self._last_chunk_fp = None
        return fp, chunk

    # ------------------------------------------------------------- public API

    def inference_step(
        self,
        hidden: np.ndarray,  # [batch, seq, hidden] (real tokens, unpadded)
        kv: Tuple[jax.Array, jax.Array],
        position: int,
        *,
        prompts: Optional[np.ndarray] = None,  # [n_blocks, batch, pre_seq, hidden]
        hypo_ids: Optional[np.ndarray] = None,  # [batch]
        active_adapter: Optional[str] = None,
        handles=None,  # session identity for the multi-host lockstep wrapper; unused here
        n_total: Optional[int] = None,  # final sequence length override (chunked callers)
    ) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array]]:
        """One (chunked-as-needed) inference step over the whole span chain.

        ``n_total`` lets a caller that ALREADY chunked the prompt (the
        batcher's dense-prefill path submits one inference_step per chunk)
        declare the full final sequence length, so length-dependent rotary
        variants (LongRoPE short/long factor selection) see the same n_total
        in every chunk instead of flipping factors mid-prompt. Defaults to
        position + seq — exact for unchunked callers."""
        k_stack, v_stack = kv
        max_length = k_stack.shape[2]
        batch, total_seq, _ = hidden.shape
        if position + total_seq > max_length:
            raise ValueError(
                f"Step of {total_seq} tokens at position {position} overflows the "
                f"allocated cache ({max_length} tokens)"
            )
        if n_total is not None and n_total < position + total_seq:
            raise ValueError(
                f"n_total={n_total} is shorter than this step's own end "
                f"({position} + {total_seq})"
            )

        # keep hidden host-side (numpy): each chunk ships inside its step's ONE
        # jit dispatch (the jit casts to compute dtype); an eager asarray+cast
        # here cost two extra device round trips per decode token
        if not isinstance(hidden, jax.Array):
            hidden = np.ascontiguousarray(hidden)
        span_params = self.params_for(active_adapter)
        outputs = []
        offset = 0
        # The final sequence length after this step is known up front: thread
        # it through so longrope (phi3) selects rotary factors from it in
        # EVERY chunk — a chunked prefill then matches HF's single full
        # forward instead of flipping factors mid-prompt.
        if n_total is None:
            n_total = position + total_seq
        for chunk_len in self.chunk_plan(batch, total_seq, kv_buf_len=max_length):
            chunk = hidden[:, offset : offset + chunk_len]
            out, k_stack, v_stack = self._step_once(
                span_params, chunk, k_stack, v_stack, position + offset, prompts,
                hypo_ids if offset == 0 else None, n_total=n_total,
            )
            outputs.append(out)
            offset += chunk_len

        result = outputs[0] if len(outputs) == 1 else jnp.concatenate(outputs, axis=1)
        return result, (k_stack, v_stack)

    def _step_once(self, span_params, chunk, k_stack, v_stack, position, prompts,
                   hypo_ids, n_total=None):
        batch, seq, _ = chunk.shape
        n_valid = seq
        if n_total is None:
            n_total = position + seq
        if seq == 1:
            padded, is_padded = chunk, False
        else:
            bucket = bucket_length(seq)
            if bucket != seq:
                padded = jnp.pad(chunk, ((0, 0), (0, bucket - seq), (0, 0)))
                is_padded = True
            else:
                padded, is_padded = chunk, False

        with_prompts = prompts is not None
        with_hypo = hypo_ids is not None
        # dummy prompts/hypo operands: device-resident and cached per shape —
        # allocating them per step added host->device dispatches on the
        # per-token path (decode is called hundreds of times per second)
        if prompts is None:
            prompts_arr = self._dummy_operand(
                (self.n_blocks, batch, 0, self.hidden_size), self.compute_dtype
            )
        else:
            prompts_arr = jnp.asarray(prompts, self.compute_dtype)
        hypo_arr = (
            jnp.asarray(hypo_ids, jnp.int32)
            if hypo_ids is not None
            else self._dummy_operand((batch,), jnp.int32)
        )

        with self._quant_ctx():
            out, k_stack, v_stack = self._inference_step_fn(
                span_params,
                k_stack,
                v_stack,
                padded,
                np.int32(position),
                np.int32(n_valid),
                np.int32(n_total),
                prompts_arr,
                hypo_arr,
                with_prompts=with_prompts,
                with_hypo=with_hypo,
                padded=is_padded,
            )
        if out.shape[1] != seq:
            out = out[:, :seq]
        return out, k_stack, v_stack

    def _dummy_operand(self, shape, dtype) -> jax.Array:
        key = (shape, jnp.dtype(dtype).name)
        arr = self._dummy_operands.get(key)
        if arr is None:
            arr = self._dummy_operands[key] = jnp.zeros(shape, dtype)
        return arr

    def chunk_plan(self, batch: int, total_seq: int, kv_buf_len: int = None,
                   page_size: int = None, start: int = 0) -> Sequence[int]:
        """Split a long prefill so each chunk's attention footprint stays under
        max_chunk_size_bytes (reference backend.py:126-152 semantics). Public:
        the continuous batcher plans queue-task boundaries with it, so the
        chunk policy lives here in exactly one place.

        ``page_size`` (paged lanes): chunk ENDS are aligned to absolute page
        boundaries — each chunk's KV scatter is whole-page writes, with a
        partial tail page only on the final chunk. ``start`` is the absolute
        position of the first token (alignment is in absolute positions, so
        an unaligned start self-corrects after the first chunk)."""
        if total_seq <= 1:
            return [total_seq]
        # The linear sizing below is only sound when the flash kernel will
        # actually run: attend() silently falls back to the logit-materializing
        # XLA path when the kernel can't handle the shapes (cache length not a
        # multiple of 128), and then chunks must be sized by the quadratic
        # formula. Sliding windows are handled by the kernel.
        flash_will_run = self.use_flash and (kv_buf_len is None or kv_buf_len % 128 == 0)
        if flash_will_run:
            # flash never materializes the [chunk, total_seq] logits; the
            # footprint is the chunk's activations (hidden + MLP intermediate +
            # per-head rows), linear in chunk length
            itemsize = jnp.dtype(self.compute_dtype).itemsize
            per_token = batch * itemsize * (
                2 * self.hidden_size
                + getattr(self.cfg, "intermediate_size", 4 * self.hidden_size)
                + self.cfg.num_attention_heads * self.head_dim
            )
            max_chunk = max(self.max_chunk_size_bytes // max(per_token, 1), 1)
        else:
            # attention logits per chunk ≈ batch * heads * chunk * total_seq * 4 bytes
            heads = self.cfg.num_attention_heads
            denom = max(batch * heads * total_seq * 4, 1)
            max_chunk = max(self.max_chunk_size_bytes // denom, 1)
        chunks = []
        remaining = total_seq
        pos = int(start)
        while remaining > 0:
            step = min(max_chunk, remaining)
            if page_size and step < remaining:
                # align this chunk's end DOWN to an absolute page boundary
                # (whole-page scatters); keep the unaligned step when the
                # boundary is out of reach (max_chunk < one page span)
                end = pos + step
                aligned = end - end % page_size
                if aligned > pos:
                    step = aligned - pos
            chunks.append(step)
            remaining -= step
            pos += step
        return chunks

    def forward(
        self, hidden: np.ndarray, prompts: Optional[np.ndarray] = None,
        active_adapter: Optional[str] = None,
    ) -> jax.Array:
        """Training-style forward over the span (no KV cache)."""
        hidden = jnp.asarray(hidden, self.compute_dtype)
        span_params = self.params_for(active_adapter)
        with_prompts = prompts is not None
        prompts_arr = (
            jnp.asarray(prompts, self.compute_dtype)
            if prompts is not None
            else jnp.zeros((self.n_blocks, hidden.shape[0], 0, self.hidden_size), self.compute_dtype)
        )
        with self._quant_ctx():
            return self._forward_fn(span_params, hidden, prompts_arr, with_prompts=with_prompts)

    def backward(
        self, hidden: np.ndarray, grad_out: np.ndarray, prompts: Optional[np.ndarray] = None,
        active_adapter: Optional[str] = None,
    ) -> Tuple[jax.Array, Optional[jax.Array]]:
        """Grads wrt inputs (and deep prompts if given) — recomputes the chain
        forward like the reference (run_rpc_backward, block_functions.py:84-141)."""
        hidden = jnp.asarray(hidden, self.compute_dtype)
        grad_out = jnp.asarray(grad_out, self.compute_dtype)
        with_prompts = prompts is not None
        prompts_arr = (
            jnp.asarray(prompts, self.compute_dtype)
            if prompts is not None
            else jnp.zeros((self.n_blocks, hidden.shape[0], 0, self.hidden_size), self.compute_dtype)
        )
        with self._quant_ctx():
            grad_hidden, grad_prompts = self._backward_fn(
                self.params_for(active_adapter), hidden, prompts_arr, grad_out,
                with_prompts=with_prompts,
            )
        return grad_hidden, (grad_prompts if with_prompts else None)
