"""Decentralized load balancing: which span should this server host?
(counterpart of reference src/petals/server/block_selection.py:12-95 — the
algorithm is hardware-agnostic numpy and keeps the same semantics: maximize the
swarm's bottleneck throughput, move only when it meaningfully helps).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from petals_tpu.data_structures import PeerID, RemoteModuleInfo, ServerState

BALANCE_QUALITY = 0.75  # rebalance iff actual/optimal throughput drops below this


def compute_throughputs(
    module_infos: Sequence[Optional[RemoteModuleInfo]],
    *,
    exclude_peer: Optional[PeerID] = None,
) -> np.ndarray:
    """Per-block aggregate swarm throughput (JOINING servers count: they will
    arrive soon — reference block_selection.py:12-20)."""
    throughputs = np.zeros(len(module_infos))
    for block_idx, info in enumerate(module_infos):
        if info is None:
            continue
        for peer_id, server in info.servers.items():
            if peer_id == exclude_peer:
                continue
            if server.state.value >= ServerState.JOINING.value:
                throughputs[block_idx] += server.throughput
    return throughputs


def choose_best_start(throughputs: np.ndarray, num_blocks: int) -> int:
    """Start index whose span covers the weakest blocks (reference :23-33)."""
    options = [
        (throughputs[i : i + num_blocks].min(), throughputs[i : i + num_blocks].sum(), i)
        for i in range(0, len(throughputs) - num_blocks + 1)
    ]
    # host the span with the lowest bottleneck; break ties toward the span
    # that is weakest overall (then leftmost)
    best = min(options)
    return best[2]


def should_choose_other_blocks(
    local_peer: PeerID,
    module_infos: Sequence[Optional[RemoteModuleInfo]],
    num_blocks: Optional[int] = None,
    *,
    balance_quality: float = BALANCE_QUALITY,
    rng: Optional[np.random.RandomState] = None,
) -> bool:
    """Would the swarm's bottleneck improve enough if this server moved?

    Simulates our own best move AND everyone else's greedy follow-up moves
    until no server wants to move (reference block_selection.py:40-95) — a
    single-move simulation systematically over-estimates the benefit and
    thrashes in swarms of 3+ servers, because the spot we vacate looks weak
    to whoever evaluates next.
    """
    if balance_quality > 1.0:
        return True  # debugging override: force a move on every check

    from petals_tpu.utils.dht_utils import compute_spans

    spans = compute_spans(module_infos, min_state=ServerState.JOINING)
    if local_peer not in spans:
        return False
    local_span = spans[local_peer]
    if num_blocks is not None and (local_span.end - local_span.start) != num_blocks:
        # the DHT shows only a fragment of our span (expired/partial records):
        # a verdict computed on the fragment would justify moves the caller's
        # real num_blocks-sized reload never matches — wait for a clean view
        return False
    if (local_span.server_info.throughput or 0.0) <= 0:
        return False  # still measuring: moving a zero-throughput span changes nothing
    eps = 1e-3
    rng = rng or np.random

    total = len(module_infos)
    throughputs = np.zeros(total)
    sim: Dict[PeerID, list] = {}  # peer -> [start, length, throughput]
    for pid, span in spans.items():
        tp = span.server_info.throughput or 0.0
        sim[pid] = [span.start, span.end - span.start, tp]
        throughputs[span.start : span.end] += tp
    initial = throughputs.min()

    def best_move(pid) -> int:
        """Lift the span out (eps-biased so near-ties prefer staying put) and
        return its best start under the current simulated layout."""
        start, length, tp = sim[pid]
        throughputs[start : start + length] -= tp * (1 + eps)
        new_start = choose_best_start(throughputs, length)
        throughputs[start : start + length] += tp * eps
        return new_start

    def settle(pid, new_start) -> None:
        sim[pid][0] = new_start
        _, length, tp = sim[pid]
        throughputs[new_start : new_start + length] += tp

    # our own move first
    start, length, tp = sim[local_peer]
    without_us = throughputs.copy()
    without_us[start : start + length] -= tp
    if initial > eps and without_us.min() <= 0:
        return False  # moving would disconnect the swarm
    new_start = best_move(local_peer)
    if new_start == start:
        throughputs[start : start + length] += tp  # put ourselves back
        return False  # already in the best place
    settle(local_peer, new_start)

    # everyone else's greedy follow-ups, to convergence (bounded for safety)
    for _round in range(10 * max(len(sim), 1)):
        peers = list(sim)
        rng.shuffle(peers)
        moved = False
        for pid in peers:
            prev = sim[pid][0]
            target = best_move(pid)
            settle(pid, target)
            moved = moved or target != prev
        if not moved:
            break

    converged = throughputs.min()
    if converged < initial or converged < eps:
        return False
    actual_quality = initial / converged
    return actual_quality < balance_quality - eps
