"""Decentralized load balancing: which span should this server host?
(counterpart of reference src/petals/server/block_selection.py:12-95 — the
algorithm is hardware-agnostic numpy and keeps the same semantics: maximize the
swarm's bottleneck throughput, move only when it meaningfully helps).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from petals_tpu.data_structures import PeerID, RemoteModuleInfo, ServerState

BALANCE_QUALITY = 0.75  # rebalance iff actual/optimal throughput drops below this


def compute_throughputs(
    module_infos: Sequence[Optional[RemoteModuleInfo]],
    *,
    exclude_peer: Optional[PeerID] = None,
) -> np.ndarray:
    """Per-block aggregate swarm throughput (JOINING servers count: they will
    arrive soon — reference block_selection.py:12-20)."""
    throughputs = np.zeros(len(module_infos))
    for block_idx, info in enumerate(module_infos):
        if info is None:
            continue
        for peer_id, server in info.servers.items():
            if peer_id == exclude_peer:
                continue
            if server.state.value >= ServerState.JOINING.value:
                throughputs[block_idx] += server.throughput
    return throughputs


def choose_best_start(throughputs: np.ndarray, num_blocks: int) -> int:
    """Start index whose span covers the weakest blocks (reference :23-33)."""
    options = [
        (throughputs[i : i + num_blocks].min(), throughputs[i : i + num_blocks].sum(), i)
        for i in range(0, len(throughputs) - num_blocks + 1)
    ]
    # host the span with the lowest bottleneck; break ties toward the span
    # that is weakest overall (then leftmost)
    best = min(options)
    return best[2]


def should_choose_other_blocks(
    local_peer: PeerID,
    module_infos: Sequence[Optional[RemoteModuleInfo]],
    num_blocks: int,
    *,
    balance_quality: float = BALANCE_QUALITY,
) -> bool:
    """Would the swarm's bottleneck improve enough if this server moved?
    Simulates our move plus greedy follow-up moves by others (reference :40-95)."""
    throughputs_with_us = compute_throughputs(module_infos)
    local_throughput = _local_throughput(local_peer, module_infos)
    if local_throughput == 0:
        return False

    throughputs = compute_throughputs(module_infos, exclude_peer=local_peer)
    actual_quality = throughputs_with_us.min() / max(throughputs_with_us.mean(), 1e-9)
    if actual_quality >= balance_quality:
        return False  # already well balanced

    # simulate: we move to the best start given everyone else stays
    new_start = choose_best_start(throughputs, num_blocks)
    moved = throughputs.copy()
    moved[new_start : new_start + num_blocks] += local_throughput

    # if the bottleneck after our move is no better than now, don't thrash
    eps = 1e-3
    return moved.min() > throughputs_with_us.min() + eps


def _local_throughput(local_peer, module_infos) -> float:
    for info in module_infos:
        if info is not None and local_peer in info.servers:
            return info.servers[local_peer].throughput
    return 0.0
