"""Preemptive session scheduler: arbitration of the paged KV pool.

Petals' public-swarm premise is bursty demand from many independent clients,
yet before this subsystem a full page pool ended a session hard: admission
and prepare_write parked the caller on a waiter and raised AllocationFailed
at timeout. The scheduler converts that central failure mode into a
scheduling decision, in two layers:

- **Admission** (acquire_lane): lane waiters are ordered by priority class
  (session-open "priority" hint: high/normal/low, default normal), ties
  broken by per-peer fair share — among equal-priority waiters the peer
  consuming the least is admitted first, so one chatty client cannot
  monopolize the pool — then FIFO. Fair share ranks by the resource
  ledger's dominant-resource share (``usage_fn``: rolling-window DRF over
  page-seconds / compute-seconds / tokens / swap bytes) when wired, which
  sees page and prefill hogging that a raw lane count is blind to; the
  lanes-held count remains the inner tie-break and the whole rank when no
  ledger is attached.

- **Preemption** (prepare_write / swap-in on pool exhaustion): instead of
  only waiting for a page to free, the batcher asks the scheduler for a
  victim — an IDLE resident lane of equal-or-lower priority, lowest priority
  class first, least-recently-stepped within a class ("lru" policy; "largest"
  prefers the lane holding the most pages; "off" disables preemption). The
  victim's pages are gathered on device, copied to the host-RAM swap tier
  (memory_cache.HostSwapPool budget), and freed — waking the waiters. When
  the victim's session next steps, the batcher transparently swaps it back
  in onto whatever pages are then free (block tables make relocation free),
  so oversubscribed sessions stall briefly instead of dying.

This module holds POLICY and accounting only (victim ordering, fair share,
swap-entry bookkeeping, stats); the MECHANICS — device gather/scatter, table
mutation, page refcounts, the suspend/resume locking — live in
server/batching.py, which owns those structures. The dense lane pool and
TP/lockstep spans keep priority/fair-share ADMISSION but never preempt:
their pool exhaustion stays on the old waiter backpressure path (paged mode
is gated off there too, so there are no relocatable pages to swap).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, Iterable, Optional, Sequence

import numpy as np

from petals_tpu.data_structures import SESSION_PRIORITY_NORMAL
from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)

PREEMPTION_POLICIES = ("lru", "largest", "off")


@dataclasses.dataclass
class SwapEntry:
    """One suspended lane's KV, resident in host RAM.

    ``k``/``v`` are [n_blocks, n_slots, page_size, hkv, d] host arrays
    holding exactly the pages that were resident at suspend time — or, for
    a quantized pool (``kv_quant_type != none``), ``PagedPool`` pytrees of
    host arrays holding the PACKED codes + scales, so the swap tier stores
    wire bytes and the round trip back to the device is byte-exact.
    ``slots`` records WHICH table slots they back, so swap-in can restore
    the row onto fresh physical pages. ``generation`` pins the entry to the
    pool generation it was taken under — a pool reset invalidates it."""

    k: "np.ndarray | object"  # host pages, or a PagedPool of host arrays
    v: "np.ndarray | object"
    slots: np.ndarray  # [n_slots] int32 table-slot indices
    nbytes: int  # WIRE bytes reserved in the HostSwapPool
    generation: int
    suspended_at: float = 0.0  # time.monotonic() at swap-out commit


@dataclasses.dataclass
class SessionSlot:
    """Scheduler-side state of one admitted lane."""

    lane: int
    peer_id: Optional[str]
    priority: int  # SESSION_PRIORITY_*: lower value = more important
    # request-scoped trace id (telemetry.trace): the contextvar cannot cross
    # into the flush loop or the compute thread, so the slot carries it —
    # victim/swap journal events read it from here to tag the right session
    trace_id: Optional[str] = None
    last_step: int = 0  # scheduler clock tick of the most recent step
    swap: Optional[SwapEntry] = None  # non-None while suspended
    suspending: bool = False  # swap-out in flight (device gather queued)
    resumed_at: float = 0.0  # time.monotonic() of the last swap-in

    @property
    def suspended(self) -> bool:
        return self.swap is not None


class SessionScheduler:
    """Priority + fair-share arbitration of lanes and pages across sessions."""

    def __init__(
        self,
        swap_pool,  # memory_cache.HostSwapPool
        *,
        policy: str = "lru",
        pages_fn: Optional[Callable[[int], int]] = None,
        resume_quantum_s: float = 0.5,
        usage_fn: Optional[Callable[[Optional[str]], float]] = None,
    ):
        if policy not in PREEMPTION_POLICIES:
            raise ValueError(
                f"preemption_policy must be one of {PREEMPTION_POLICIES}, got {policy!r}"
            )
        self.swap_pool = swap_pool
        self.policy = policy
        # minimum residency after a resume (an OS timeslice, in effect):
        # without it, a just-swapped-in lane is re-victimized in the sliver
        # between its next two steps and the pool degenerates into swap
        # ping-pong — measured 5x more preemptions than burst boundaries
        # warrant under an oversubscribed interactive load
        self.resume_quantum_s = resume_quantum_s
        # resident page count of a lane ("largest" victim ordering + fair-share
        # page accounting); the batcher wires its block tables in, unit tests
        # wire a dict — the scheduler never reaches into batcher internals
        self.pages_fn = pages_fn or (lambda lane: 0)
        # peer -> dominant-resource share in [0, 1] (telemetry.ledger
        # peer_dominant_share); None keeps the raw lanes-held fair share.
        # Shares are quantized to avoid float jitter flapping the order.
        self.usage_fn = usage_fn
        self.lanes: Dict[int, SessionSlot] = {}
        self._clock = 0
        # every key pre-initialized, like DecodeBatcher.stats: rpc_info spreads
        # this dict and the schema must not depend on which paths have run
        self.stats = {
            "preemptions": 0,
            "swap_outs": 0,
            "swap_ins": 0,
            "swap_aborted": 0,
            "swap_dropped_on_reset": 0,
        }

    # ------------------------------------------------------------- lifecycle

    def register(
        self,
        lane: int,
        peer_id: Optional[str],
        priority: int,
        trace_id: Optional[str] = None,
    ) -> SessionSlot:
        self._clock += 1
        slot = SessionSlot(
            lane=lane, peer_id=peer_id, priority=int(priority),
            trace_id=trace_id, last_step=self._clock,
        )
        self.lanes[lane] = slot
        return slot

    def trace_id_of(self, lane: int) -> Optional[str]:
        slot = self.lanes.get(lane)
        return slot.trace_id if slot is not None else None

    def unregister(self, lane: int) -> None:
        slot = self.lanes.pop(lane, None)
        if slot is not None and slot.swap is not None:
            self.swap_pool.free(slot.swap.nbytes)
            # swarmlint: disable=lane-typestate — the slot is already popped from lanes: unreachable to new transitions, and a swap-out racing this release aborts on its post-gather re-registration check
            slot.swap = None

    def touch(self, lane: int) -> None:
        slot = self.lanes.get(lane)
        if slot is not None:
            self._clock += 1
            slot.last_step = self._clock

    def reset(self) -> None:
        """Pool reset: every swap entry's content targets a dead generation —
        drop them (freeing swap bytes) so suspended sessions fail loudly
        through the normal lane-generation check instead of scattering stale
        KV into the rebuilt pool."""
        for slot in self.lanes.values():
            # swarmlint: disable=lane-typestate — pool-wide reset: callers (batcher close / failed-donation reset under _reset_lock) invalidate every lane wholesale; racing swap paths fail on the generation check, and per-lane locking here would deadlock against them
            slot.suspending = False
            if slot.swap is not None:
                self.swap_pool.free(slot.swap.nbytes)
                # swarmlint: disable=lane-typestate — same pool-wide reset as the suspending flag above: dead-generation entries are dropped wholesale
                slot.swap = None
                self.stats["swap_dropped_on_reset"] += 1

    # ------------------------------------------------------------ admission

    def peer_lanes_held(self, peer_id: Optional[str]) -> int:
        return sum(1 for s in self.lanes.values() if s.peer_id == peer_id)

    def peer_pages_held(self, peer_id: Optional[str]) -> int:
        return sum(
            self.pages_fn(s.lane) for s in self.lanes.values() if s.peer_id == peer_id
        )

    def peer_usage_share(self, peer_id: Optional[str]) -> float:
        """Quantized dominant-resource share of ``peer_id`` (0.0 without a
        ledger — every rank below then degrades to the pre-ledger order)."""
        if self.usage_fn is None:
            return 0.0
        try:
            return round(float(self.usage_fn(peer_id)), 3)
        except Exception as e:
            # an accounting bug must degrade ranking, never block admission
            logger.warning(f"usage_fn failed for {peer_id!r}: {e}")
            return 0.0

    def pick_waiter(self, waiters: Sequence) -> Optional[object]:
        """Admission order for lane waiters: highest priority class first,
        then the peer with the smallest dominant-resource share (DRF fair
        share via the ledger; 0 for everyone without one), then the peer
        holding the fewest lanes, then FIFO. ``waiters`` entries expose
        .priority, .peer_id, .seq (batching.py _LaneWaiter); returns the
        entry to admit, or None when empty."""
        live = [w for w in waiters if not w.fut.done()]
        if not live:
            return None
        return min(
            live,
            key=lambda w: (
                w.priority,
                self.peer_usage_share(w.peer_id),
                self.peer_lanes_held(w.peer_id),
                w.seq,
            ),
        )

    # ------------------------------------------------------------ preemption

    def pick_victim(
        self, candidates: Iterable[int], *, max_priority: Optional[int] = None
    ) -> Optional[int]:
        """Choose the lane to preempt among ``candidates`` (already filtered
        by the batcher for idleness and residency). Victims must be of equal
        or LOWER importance than the requester (priority value >=
        ``max_priority``); ordering is lowest priority class first, then the
        owning peer's dominant-resource share (the ledger's DRF view: a
        noisy peer's lanes go first, 0 for everyone without a ledger), then
        least-recently-stepped ("lru") or most pages held ("largest")."""
        if self.policy == "off":
            return None
        now = time.monotonic()
        best, best_key = None, None
        for lane in candidates:
            slot = self.lanes.get(lane)
            if slot is None or slot.suspending or slot.swap is not None:
                continue
            if max_priority is not None and slot.priority < max_priority:
                continue  # never preempt a more important session
            if now - slot.resumed_at < self.resume_quantum_s:
                continue  # just resumed: let it run its quantum (anti-thrash)
            share = self.peer_usage_share(slot.peer_id)
            if self.policy == "largest":
                key = (-slot.priority, -share, -self.pages_fn(lane), slot.last_step)
            else:  # lru
                key = (-slot.priority, -share, slot.last_step, -self.pages_fn(lane))
            if best_key is None or key < best_key:
                best, best_key = lane, key
        return best

    # --------------------------------------------------------- observability

    @property
    def suspended_count(self) -> int:
        return sum(1 for s in self.lanes.values() if s.swap is not None)

    def oldest_swap_age(self, now: Optional[float] = None) -> float:
        """Seconds the longest-suspended session has been resident in the
        host swap tier (0.0 when nothing is suspended) — the residency-age
        half of the swap-tier economics: a large age under load means a
        session is starving, not merely preempted."""
        if now is None:
            now = time.monotonic()
        ages = [
            now - s.swap.suspended_at
            for s in self.lanes.values()
            if s.swap is not None and s.swap.suspended_at > 0
        ]
        return max(ages, default=0.0)

    def summary(self) -> dict:
        # the ONE swap budget splits two ways: suspended sessions and the
        # radix prefix cache's demoted nodes (kind="cache" reservations)
        cache_bytes = getattr(self.swap_pool, "cache_bytes_in_use", 0)
        return {
            "policy": self.policy,
            "suspended": self.suspended_count,
            "swap_oldest_s": round(self.oldest_swap_age(), 1),
            "swap_bytes_in_use": self.swap_pool.bytes_in_use,
            "swap_session_bytes": self.swap_pool.bytes_in_use - cache_bytes,
            "swap_cache_bytes": cache_bytes,
            "swap_bytes_total": self.swap_pool.max_size_bytes,
            "swap_peak_bytes": self.swap_pool.stats["peak_bytes"],
            "swap_rejected": self.swap_pool.stats["rejected"],
            "swap_cache_rejected": self.swap_pool.stats.get("cache_rejected", 0),
            **self.stats,
        }


__all__ = [
    "PREEMPTION_POLICIES",
    "SESSION_PRIORITY_NORMAL",
    "SessionScheduler",
    "SessionSlot",
    "SwapEntry",
]
