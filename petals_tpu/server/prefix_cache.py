"""Content-addressed prefix cache: identical prompt prefixes across sessions
skip their prefill compute (beats the reference, which recomputes every
session's full prompt; the vLLM-style automatic-prefix-caching idea, built
for this server's hidden-state wire protocol).

Servers receive prefills as HIDDEN STATES, which are deterministic functions
of the prompt prefix for a fixed model/span — so a prefix is identified by a
hash CHAIN over fixed-size token segments: key_i = H(key_{i-1}, bytes of
segment i). A session's prefill probes the chain for its longest cached
prefix, seeds its KV buffers from host RAM, computes only the tail, and
stores the new segments for the next session. Rollbacks can't poison the
store: entries are content-addressed (same segment bytes -> same KV), never
keyed by session state.

Storage is host-RAM numpy with an LRU byte budget — HBM stays dedicated to
live sessions; re-staging a hit costs one host->device copy, which is far
cheaper than recomputing the prefix through the span.

Trust model (standard automatic-prefix-caching tradeoff): the cache is
shared across ALL clients of this server by default, and a hit is faster
than a miss in a way a client can time — so any client that can produce the
same hidden states (i.e. knows the model and a candidate prompt) can probe
whether that prompt prefix was recently served to someone else. In an open
swarm this is consistent with the existing trust model: prompt hidden
states already transit servers in the clear, so a server (or anyone who can
hash candidate prompts) learns nothing new from the cache — only OTHER
clients gain the timing probe. Deployments that care can set the handler's
``prefix_share_scope="peer"``, which folds the requesting peer's id into
the hash salt: each client then only ever hits its own entries, closing the
cross-tenant channel at the cost of cross-client sharing.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import List, Optional, Sequence, Tuple

import numpy as np

from petals_tpu.telemetry import instruments as tm
from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)

SEGMENT_TOKENS = 128


def segment_keys(hidden: np.ndarray, salt: str) -> List[str]:
    """Hash-chain keys for every FULL segment of ``hidden`` [1, seq, h].
    blake2b (fast, keyed by the span salt so spans never cross-pollute)."""
    seq = hidden.shape[1]
    keys = []
    prev = salt.encode()
    for s in range(seq // SEGMENT_TOKENS):
        seg = np.ascontiguousarray(hidden[:, s * SEGMENT_TOKENS : (s + 1) * SEGMENT_TOKENS])
        h = hashlib.blake2b(prev, digest_size=16)
        h.update(seg.tobytes())
        prev = h.digest()
        keys.append(prev.hex())
    return keys


class PrefixCache:
    """LRU store of per-segment (k, v, out) host arrays, budgeted by bytes.

    A second, smaller DEVICE tier (``device_max_bytes``) keeps the most
    recently stored segments' k/v additionally resident in HBM: a hit whose
    whole prefix is device-resident seeds the session without any
    host->device transfer, which is what makes a prefix hit decisively
    cheaper than the prefill it skips (measured on the axon tunnel: the
    host-tier hit's KV re-upload cost about as much as the skipped compute
    — 1.04x TTFT; on local PCIe the transfer is cheaper but still the
    dominant hit cost at long prefixes). Device entries are an optimization
    only: eviction drops the HBM reference, the host copy stays, and the
    seed path falls back to the host staging route."""

    def __init__(self, max_bytes: int, device_max_bytes: int = 0):
        self.max_bytes = max_bytes
        self.device_max_bytes = device_max_bytes
        self._store: "OrderedDict[str, dict]" = OrderedDict()
        self._bytes = 0
        self._dev_bytes = 0
        self.stats = {
            "hits": 0, "misses": 0, "hit_tokens": 0, "stored_segments": 0,
            "evictions": 0,
        }

    @property
    def current_bytes(self) -> int:
        return self._bytes

    def probe(self, keys: Sequence[str]) -> int:
        """Longest cached prefix (in segments); touches hits for LRU."""
        n = 0
        for key in keys:
            entry = self._store.get(key)
            if entry is None:
                break
            self._store.move_to_end(key)
            n += 1
        if n:
            self.stats["hits"] += 1
            self.stats["hit_tokens"] += n * SEGMENT_TOKENS
            tm.PREFIX_HIT.inc()
        else:
            self.stats["misses"] += 1
            tm.PREFIX_MISS.inc()
        return n

    def get_entries(self, keys: Sequence[str], n: int) -> List[dict]:
        """Entry references for segments [0, n). Cheap dict lookups — callers
        on the event loop resolve these BEFORE handing the multi-MB
        concatenation to a worker thread: a concurrent put()'s LRU eviction
        only pops dict slots, so already-held references stay valid, whereas
        re-looking keys up from the thread can raise KeyError mid-read."""
        return [self._store[k] for k in keys[:n]]

    @staticmethod
    def concat_entries(entries: Sequence[dict]) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Concatenate resolved entries along the token axis:
        k/v [n_blocks, 1, n*SEG, hkv, d], out [1, n*SEG, hidden]."""
        k = np.concatenate([e["k"] for e in entries], axis=2)
        v = np.concatenate([e["v"] for e in entries], axis=2)
        out = np.concatenate([e["out"] for e in entries], axis=1)
        return k, v, out

    def get_range(self, keys: Sequence[str], n: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """get_entries + concat_entries in one call (single-threaded users)."""
        return self.concat_entries(self.get_entries(keys, n))

    def put(
        self, keys: Sequence[str], first: int,
        k: np.ndarray, v: np.ndarray, out: np.ndarray,
        k_dev=None, v_dev=None,
        pages: Optional[Sequence[int]] = None, pages_pool=None, pages_epoch: int = 0,
    ) -> None:
        """Store segments [first, len(keys)) from span-shaped arrays COVERING
        those segments: k/v [n_blocks, 1, tokens, hkv, d] and out
        [1, tokens, hidden] whose token axis starts at segment ``first``.
        ``k_dev``/``v_dev``, when given, are the same token range as DEVICE
        arrays; their per-segment slices populate the device tier.

        ``pages``/``pages_pool``/``pages_epoch``: page-granular sharing for a
        paged batcher. ``pages`` are PINNED page indices (pin_lane_pages)
        covering the same token range; each segment's slice rides on its
        entry so a later hit can adopt_pages the prefix with zero copies.
        Ownership transfers to the cache here: every incoming page reference
        is either attached to an entry or unpinned before put returns, and
        attached pins are unpinned on eviction/clear — copy-on-write in the
        batcher keeps pinned pages immutable while referenced."""
        spp = 0
        if pages is not None and pages_pool is not None and pages_pool.page_size:
            spp = SEGMENT_TOKENS // pages_pool.page_size  # pages per segment

        def unpin_from(seg: int) -> None:
            if spp and pages[seg * spp:]:
                pages_pool.unpin_pages(pages[seg * spp:], pages_epoch)

        for i, key in enumerate(keys[first:]):
            t0, t1 = i * SEGMENT_TOKENS, (i + 1) * SEGMENT_TOKENS
            seg_pages = list(pages[i * spp : (i + 1) * spp]) if spp else None
            if key in self._store:
                self._store.move_to_end(key)
                # a hot entry first stored host-only (pooled/lockstep store,
                # or after device eviction) gains HBM residency on its next
                # device-capable store — otherwise popular prefixes would be
                # locked out of the tier forever while one-offs fill it
                if t1 <= k.shape[2]:
                    self._attach_device(self._store[key], k_dev, v_dev, t0, t1)
                if seg_pages and not self._attach_pages(
                    self._store[key], seg_pages, pages_pool, pages_epoch
                ):
                    pages_pool.unpin_pages(seg_pages, pages_epoch)
                continue
            if t1 > k.shape[2]:
                unpin_from(i)
                break
            entry = {
                "k": np.ascontiguousarray(k[:, :, t0:t1]),
                "v": np.ascontiguousarray(v[:, :, t0:t1]),
                "out": np.ascontiguousarray(out[:, t0:t1]),
            }
            entry_bytes = sum(a.nbytes for a in entry.values())
            if entry_bytes > self.max_bytes:
                unpin_from(i)
                return  # a single segment over budget: nothing fits
            while self._bytes + entry_bytes > self.max_bytes and self._store:
                _, old = self._store.popitem(last=False)
                self._bytes -= old["bytes"]
                self._dev_bytes -= old.pop("dev_bytes", 0)
                self._unpin_entry(old)
                self.stats["evictions"] += 1
                tm.PREFIX_EVICT.inc()
            entry["bytes"] = entry_bytes
            self._attach_device(entry, k_dev, v_dev, t0, t1)
            if seg_pages:
                self._attach_pages(entry, seg_pages, pages_pool, pages_epoch)
            self._store[key] = entry
            self._bytes += entry_bytes
            self.stats["stored_segments"] += 1

    def _attach_device(self, entry: dict, k_dev, v_dev, t0: int, t1: int) -> None:
        """Pin the [t0, t1) token slice of the device arrays onto ``entry``
        (no-op without device arrays, budget, or when already resident)."""
        if k_dev is None or self.device_max_bytes <= 0 or "kd" in entry:
            return
        kd = k_dev[:, :, t0:t1]
        vd = v_dev[:, :, t0:t1]
        dev_bytes = int(kd.nbytes) + int(vd.nbytes)
        if dev_bytes <= self.device_max_bytes:
            self._evict_device(self.device_max_bytes - dev_bytes)
            entry["kd"], entry["vd"] = kd, vd
            entry["dev_bytes"] = dev_bytes
            self._dev_bytes += dev_bytes

    def _attach_pages(self, entry: dict, seg_pages, pool, epoch: int) -> bool:
        """Attach a pinned page run to ``entry`` (paged tier). Replaces a
        stale-epoch run; returns False when the entry already holds a live
        one (caller unpins the incoming duplicate)."""
        if "pages" in entry:
            if entry.get("pages_epoch") == getattr(pool, "page_epoch", -1):
                return False
            self._unpin_entry(entry)  # stale epoch: pins died with the pool
        entry["pages"] = list(seg_pages)
        entry["pages_pool"] = pool
        entry["pages_epoch"] = epoch
        return True

    def _unpin_entry(self, entry: dict) -> None:
        """Release an entry's page pins back to its batcher (eviction/clear).
        Best-effort: a reset batcher ignores stale-epoch unpins."""
        pages = entry.pop("pages", None)
        pool = entry.pop("pages_pool", None)
        epoch = entry.pop("pages_epoch", 0)
        if pages and pool is not None:
            try:
                pool.unpin_pages(pages, epoch)
            except Exception:  # swarmlint: disable=no-silent-except — racing batcher close/reset: the pool (and its pins) are gone anyway
                pass

    def _evict_device(self, target_bytes: int) -> None:
        """Drop HBM references (oldest first) until the device tier fits
        ``target_bytes``; host copies stay, so this only downgrades hits."""
        if self._dev_bytes <= target_bytes:
            return
        for entry in list(self._store.values()):
            if self._dev_bytes <= target_bytes:
                break
            dev = entry.pop("dev_bytes", 0)
            if dev:
                entry.pop("kd", None)
                entry.pop("vd", None)
                self._dev_bytes -= dev

    def clear(self) -> None:
        """Drop every entry (stats are kept — they describe the lifetime)."""
        for entry in self._store.values():
            self._unpin_entry(entry)
        self._store.clear()
        self._bytes = 0
        self._dev_bytes = 0

    def worth_storing(self, keys: Sequence[str], first: int, est_entry_bytes: int) -> bool:
        """Whether a store pass would actually add anything: at least one
        novel key, and a single entry fits the budget (callers use this to
        skip the device->host snapshot entirely otherwise)."""
        if est_entry_bytes > self.max_bytes:
            return False
        return any(k not in self._store for k in keys[first:])

    def summary(self) -> dict:
        return {
            "segments": len(self._store),
            "bytes": self._bytes,
            "max_bytes": self.max_bytes,
            "device_segments": sum(1 for e in self._store.values() if "kd" in e),
            "device_bytes": self._dev_bytes,
            "device_max_bytes": self.device_max_bytes,
            "page_segments": sum(1 for e in self._store.values() if "pages" in e),
            **self.stats,
        }
