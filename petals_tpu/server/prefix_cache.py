"""Cross-session radix prefix tree: identical prompt prefixes across sessions
skip their prefill compute (beats the reference, which recomputes every
session's full prompt; the vLLM/SGLang automatic-prefix-caching idea, built
for this server's hidden-state wire protocol).

Servers receive prefills as HIDDEN STATES, which are deterministic functions
of the prompt prefix for a fixed model/span — so a prefix is identified by a
hash CHAIN over fixed-size token segments: key_i = H(key_{i-1}, bytes of
segment i). Because every key commits to its whole ancestry, the chain IS a
radix tree: two prompts that share j segments share exactly keys[0..j), and
the store's per-key nodes link parent -> children along the chains they were
stored under. A session's prefill probes its chain for the longest cached
path, seeds its KV buffers, computes only the tail, and stores the new
segments as a fresh branch. Rollbacks can't poison the store: nodes are
content-addressed (same segment bytes -> same KV), never keyed by session
state.

Every node carries one of three residency states:

- **HBM** — the node's k/v additionally live on device, either as pinned
  copy-on-write page runs in the batcher's paged pool (a pooled hit adopts
  them by block-table reference: zero bytes copied) or as device-array
  slices (``kd``/``vd``); a whole-path HBM hit seeds the session without any
  host->device transfer.
- **host** — numpy k/v/out in the cache's own byte budget (``max_bytes``);
  a hit re-uploads through the staging path.
- **swapped** — the arrays' bytes are charged to the PR-4 ``HostSwapPool``
  (the same budget session preemption swaps into) instead of the cache
  budget; a hit promotes the node back to the host tier through the same
  accounting, evicting colder nodes to make room.

Eviction walks leaf-first down the tiers — device refs drop before host
bytes, host bytes demote to swap before nodes are removed outright — and
victims are ranked by the prefix-cache economics counters (per-node hit
count, recency) *after* the owning tenant's ledger share: the node of the
peer with the highest dominant-resource share (``usage_fn``, the PR-10
DRF rank) goes first, so one tenant's cold subtree can never squat in HBM
past its fair share while other tenants churn. Interior nodes are never
removed while a descendant survives (probes walk keys in order; removing an
ancestor would orphan the whole subtree) — they demote to swap instead,
which keeps the path probe-able. Per-tenant resident bytes are billed to
the ResourceLedger as a piecewise-constant cache-residency rate
(``set_cache_rates``), so /ledger shows who the cache is spending its
budget on.

Trust model (standard automatic-prefix-caching tradeoff): the cache is
shared across ALL clients of this server by default, and a hit is faster
than a miss in a way a client can time — so any client that can produce the
same hidden states (i.e. knows the model and a candidate prompt) can probe
whether that prompt prefix was recently served to someone else. In an open
swarm this is consistent with the existing trust model: prompt hidden
states already transit servers in the clear, so a server (or anyone who can
hash candidate prompts) learns nothing new from the cache — only OTHER
clients gain the timing probe. Deployments that care can set the handler's
``prefix_share_scope="peer"``, which folds the requesting peer's id into
the hash salt: each client then only ever hits its own entries, closing the
cross-tenant channel at the cost of cross-client sharing.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from petals_tpu.telemetry import instruments as tm
from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)

SEGMENT_TOKENS = 128

# device-tier promotion threshold: a host-resident node must be hit this many
# times before maybe_promote_device uploads it (a one-off hit does not pay
# for an HBM slot; the second hit predicts a third). Env-tunable so revival
# step 10/10 can retune the silicon crossover without code edits.
PROMOTE_MIN_HITS = int(os.environ.get("PETALS_TPU_PROMOTE_MIN_HITS", "2"))


def resolve_device_bytes(prefix_cache_bytes: int, prefix_device_bytes: int) -> int:
    """The radix cache's HBM tier size: ``PETALS_TPU_RADIX_DEVICE_FRAC``
    (a fraction of the host budget, clamped to [0, 1]) overrides the
    configured byte count, so operators can retune the device/host split per
    silicon generation from the environment."""
    frac = os.environ.get("PETALS_TPU_RADIX_DEVICE_FRAC")
    if frac is None:
        return prefix_device_bytes
    try:
        f = min(max(float(frac), 0.0), 1.0)
    except ValueError:
        logger.warning(
            f"Ignoring malformed PETALS_TPU_RADIX_DEVICE_FRAC={frac!r}"
        )
        return prefix_device_bytes
    return int(f * max(prefix_cache_bytes, 0))

# the cache may reserve at most this fraction of the HostSwapPool for demoted
# nodes: session preemption and the prefix cache share ONE budget, and a cold
# cache must never make a live session unswappable
CACHE_SWAP_FRAC = 0.5


def segment_keys(hidden: np.ndarray, salt: str) -> List[str]:
    """Hash-chain keys for every FULL segment of ``hidden`` [1, seq, h].
    blake2b (fast, keyed by the span salt so spans never cross-pollute)."""
    seq = hidden.shape[1]
    keys = []
    prev = salt.encode()
    for s in range(seq // SEGMENT_TOKENS):
        seg = np.ascontiguousarray(hidden[:, s * SEGMENT_TOKENS : (s + 1) * SEGMENT_TOKENS])
        h = hashlib.blake2b(prev, digest_size=16)
        h.update(seg.tobytes())
        prev = h.digest()
        keys.append(prev.hex())
    return keys


class RadixPrefixCache:
    """Radix tree of per-segment (k, v, out) nodes with three-tier residency.

    The node store stays an ``OrderedDict`` keyed by chain hash (insertion /
    touch order doubles as the flat-LRU order for ``policy="lru"``); tree
    structure rides on per-node ``parent``/``children`` links derived from
    the chains nodes are stored under. ``policy="radix"`` (the default)
    enables tree-aware eviction, economics scoring, and swap spillover;
    ``policy="lru"`` reproduces the flat byte-budgeted LRU (the A/B baseline
    the bench rows compare against — same budgets, no tree protection).

    The DEVICE tier (``device_max_bytes``) keeps hot nodes' k/v additionally
    resident in HBM: a hit whose whole path is device-resident seeds the
    session without any host->device transfer, which is what makes a prefix
    hit decisively cheaper than the prefill it skips (stale axon-tunnel
    measurement — the host-tier hit's KV re-upload cost about as much as the
    skipped compute, 1.04x TTFT; re-measure via on_tunnel_revival.sh step
    10/10 before trusting the crossover on current silicon). Device entries
    are an optimization only: eviction drops the HBM reference, the host
    copy stays, and the seed path falls back to the host staging route."""

    def __init__(
        self,
        max_bytes: int,
        device_max_bytes: int = 0,
        *,
        policy: str = "radix",
        swap_pool=None,  # memory_cache.HostSwapPool (shared with session swap)
        usage_fn: Optional[Callable[[Optional[str]], float]] = None,
        ledger=None,  # telemetry.ledger.ResourceLedger (cache-residency billing)
        swap_frac: float = CACHE_SWAP_FRAC,
    ):
        if policy not in ("radix", "lru"):
            raise ValueError(f"policy must be 'radix' or 'lru', got {policy!r}")
        self.max_bytes = max_bytes
        self.device_max_bytes = device_max_bytes
        self.policy = policy
        self.swap_pool = swap_pool
        self.usage_fn = usage_fn
        self.ledger = ledger
        self.swap_frac = float(swap_frac)
        self._store: "OrderedDict[str, dict]" = OrderedDict()
        self._bytes = 0  # host tier (swapped nodes charge the pool instead)
        self._dev_bytes = 0
        self._swap_bytes = 0  # our share of swap_pool.bytes_in_use
        self._tick = 0  # logical clock for recency scoring
        # all methods may be called from the event loop AND from worker
        # threads (maybe_promote_device runs its uploads off-loop), so every
        # mutation holds the mutex; get_entries returns plain references,
        # which stay valid across a concurrent eviction (dict pops only)
        self._mutex = threading.RLock()
        self.stats = {
            "hits": 0, "misses": 0, "hit_tokens": 0, "stored_segments": 0,
            "evictions": 0, "demotions": 0, "promotions": 0,
            "swap_evictions": 0, "device_evictions": 0,
        }

    @property
    def current_bytes(self) -> int:
        return self._bytes

    @property
    def swap_bytes(self) -> int:
        return self._swap_bytes

    # ------------------------------------------------------------------ probe

    def probe(self, keys: Sequence[str]) -> int:
        """Longest cached path (in segments). Touches every node on the path
        (hit count + recency — the economics counters scoring stays/evicts)
        and promotes swapped nodes back to the host tier so the seed path
        reads them at host cost, evicting colder nodes to make room."""
        with self._mutex:
            self._tick += 1
            n = 0
            path: List[str] = []
            for key in keys:
                entry = self._store.get(key)
                if entry is None:
                    break
                entry["hits"] += 1
                entry["last_use"] = self._tick
                self._store.move_to_end(key)
                path.append(key)
                n += 1
            if n and self.policy == "radix" and self.swap_pool is not None:
                protect = frozenset(keys)
                for key in path:
                    self._promote_host(key, protect)
            if n:
                self.stats["hits"] += 1
                self.stats["hit_tokens"] += n * SEGMENT_TOKENS
                tm.PREFIX_HIT.inc()
            else:
                self.stats["misses"] += 1
                tm.PREFIX_MISS.inc()
            self._bill()
            return n

    def get_entries(self, keys: Sequence[str], n: int) -> List[dict]:
        """Entry references for segments [0, n). Cheap dict lookups — callers
        on the event loop resolve these BEFORE handing the multi-MB
        concatenation to a worker thread: a concurrent put()'s eviction only
        pops dict slots, so already-held references stay valid, whereas
        re-looking keys up from the thread can raise KeyError mid-read."""
        with self._mutex:
            return [self._store[k] for k in keys[:n]]

    @staticmethod
    def concat_entries(entries: Sequence[dict]) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Concatenate resolved entries along the token axis:
        k/v [n_blocks, 1, n*SEG, hkv, d], out [1, n*SEG, hidden]."""
        k = np.concatenate([e["k"] for e in entries], axis=2)
        v = np.concatenate([e["v"] for e in entries], axis=2)
        out = np.concatenate([e["out"] for e in entries], axis=1)
        return k, v, out

    def get_range(self, keys: Sequence[str], n: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """get_entries + concat_entries in one call (single-threaded users)."""
        return self.concat_entries(self.get_entries(keys, n))

    # ------------------------------------------------------------------- put

    def put(
        self, keys: Sequence[str], first: int,
        k: np.ndarray, v: np.ndarray, out: np.ndarray,
        k_dev=None, v_dev=None,
        pages: Optional[Sequence[int]] = None, pages_pool=None, pages_epoch: int = 0,
        tenant: Optional[str] = None,
    ) -> None:
        """Store segments [first, len(keys)) from span-shaped arrays COVERING
        those segments: k/v [n_blocks, 1, tokens, hkv, d] and out
        [1, tokens, hidden] whose token axis starts at segment ``first``.
        ``k_dev``/``v_dev``, when given, are the same token range as DEVICE
        arrays; their per-segment slices populate the device tier.

        ``pages``/``pages_pool``/``pages_epoch``: page-granular sharing for a
        paged batcher. ``pages`` are PINNED page indices (pin_lane_pages)
        covering the same token range; each segment's slice rides on its
        entry so a later hit can adopt_pages the prefix with zero copies.
        Ownership transfers to the cache here: every incoming page reference
        is either attached to an entry or unpinned before put returns, and
        attached pins are unpinned on eviction/clear — copy-on-write in the
        batcher keeps pinned pages immutable while referenced.

        ``tenant`` is the storing peer's id: residency is billed to it
        through the ledger, and eviction under pressure takes the dominant
        tenant's nodes first (the DRF victim ordering)."""
        with self._mutex:
            self._put_locked(
                keys, first, k, v, out, k_dev, v_dev,
                pages, pages_pool, pages_epoch, tenant,
            )
            self._bill()

    def _put_locked(
        self, keys, first, k, v, out, k_dev, v_dev,
        pages, pages_pool, pages_epoch, tenant,
    ) -> None:
        self._tick += 1
        spp = 0
        if pages is not None and pages_pool is not None and pages_pool.page_size:
            spp = SEGMENT_TOKENS // pages_pool.page_size  # pages per segment

        def unpin_from(seg: int) -> None:
            if spp and pages[seg * spp:]:
                pages_pool.unpin_pages(pages[seg * spp:], pages_epoch)

        protect = frozenset(keys)
        for i, key in enumerate(keys[first:]):
            t0, t1 = i * SEGMENT_TOKENS, (i + 1) * SEGMENT_TOKENS
            j = first + i  # absolute segment index along the chain
            seg_pages = list(pages[i * spp : (i + 1) * spp]) if spp else None
            if key in self._store:
                entry = self._store[key]
                self._store.move_to_end(key)
                entry["last_use"] = self._tick
                # a re-store is evidence of heat: a swapped node regaining
                # HBM residency (pages / device refs below) must come back
                # to the host tier first — swap never holds device pins
                if entry.get("swapped"):
                    self._promote_host(key, protect)
                # a hot entry first stored host-only (pooled/lockstep store,
                # or after device eviction) gains HBM residency on its next
                # device-capable store — otherwise popular prefixes would be
                # locked out of the tier forever while one-offs fill it
                if not entry.get("swapped"):
                    if t1 <= k.shape[2]:
                        self._attach_device(entry, k_dev, v_dev, t0, t1)
                    if seg_pages and not self._attach_pages(
                        entry, seg_pages, pages_pool, pages_epoch
                    ):
                        pages_pool.unpin_pages(seg_pages, pages_epoch)
                        seg_pages = None
                elif seg_pages:
                    pages_pool.unpin_pages(seg_pages, pages_epoch)
                continue
            if t1 > k.shape[2]:
                unpin_from(i)
                break
            entry = {
                "k": np.ascontiguousarray(k[:, :, t0:t1]),
                "v": np.ascontiguousarray(v[:, :, t0:t1]),
                "out": np.ascontiguousarray(out[:, t0:t1]),
            }
            entry_bytes = sum(a.nbytes for a in entry.values())
            if entry_bytes > self.max_bytes:
                unpin_from(i)
                return  # a single segment over budget: nothing fits
            if not self._make_room(entry_bytes, protect):
                # budget full of hotter/unevictable nodes: stop the whole
                # chain here — storing a deeper segment whose ancestor was
                # refused would leave an unreachable orphan
                unpin_from(i)
                return
            entry["bytes"] = entry_bytes
            parent = keys[j - 1] if j > 0 else None
            parent_entry = self._store.get(parent) if parent is not None else None
            entry["parent"] = parent if parent_entry is not None else None
            entry["children"] = set()
            entry["depth"] = (
                parent_entry["depth"] + 1 if parent_entry is not None else 0
            )
            entry["tenant"] = tenant
            entry["hits"] = 0
            entry["last_use"] = self._tick
            entry["swapped"] = False
            if parent_entry is not None:
                parent_entry["children"].add(key)
            self._attach_device(entry, k_dev, v_dev, t0, t1)
            if seg_pages:
                self._attach_pages(entry, seg_pages, pages_pool, pages_epoch)
            self._store[key] = entry
            self._bytes += entry_bytes
            self.stats["stored_segments"] += 1

    # -------------------------------------------------------------- residency

    def _tenant_share(self, shares: Dict, tenant: Optional[str]) -> float:
        """Cached dominant-resource share of ``tenant`` (0.0 without a
        usage_fn — victim ordering then falls back to pure economics)."""
        if tenant not in shares:
            share = 0.0
            if self.usage_fn is not None:
                try:
                    share = float(self.usage_fn(tenant))
                except Exception as e:
                    logger.warning(f"prefix-cache usage_fn failed for {tenant!r}: {e}")
            shares[tenant] = share
        return shares[tenant]

    def _host_leaf(self, entry: dict) -> bool:
        """Host-resident with no host-resident child: the bottom of the
        host tier under this node — demotion/eviction works upward from
        these (never strands a hotter descendant below a removed ancestor)."""
        if entry.get("swapped"):
            return False
        for c in entry["children"]:
            ce = self._store.get(c)
            if ce is not None and not ce.get("swapped"):
                return False
        return True

    def _pick_victim(self, protect: frozenset, skip: set) -> Optional[str]:
        """Leaf-first economics victim: among host-tier leaves, the node of
        the most dominant tenant, then fewest hits, then least recent. The
        hit count is the bytes-saved-per-byte-held economics in one number:
        every node is one segment, so hits * SEGMENT_TOKENS of prefill saved
        per entry_bytes held — comparing hit counts compares the ratios."""
        best_key = None
        best_rank = None
        shares: Dict = {}
        for key, entry in self._store.items():
            if key in protect or key in skip:
                continue
            if not self._host_leaf(entry):
                continue
            rank = (
                -self._tenant_share(shares, entry.get("tenant")),
                entry["hits"],
                entry["last_use"],
            )
            if best_rank is None or rank < best_rank:
                best_key, best_rank = key, rank
        return best_key

    def _make_room(self, need: int, protect: frozenset) -> bool:
        """Free host-tier bytes until ``need`` fits. Flat policy evicts in
        store (LRU) order; radix demotes leaf-first into the swap tier and
        only removes nodes outright when they have no surviving descendants
        (or no swap room)."""
        if self._bytes + need <= self.max_bytes:
            return True
        if self.policy != "radix":
            while self._bytes + need > self.max_bytes and self._store:
                self._evict_node(next(iter(self._store)))
            return self._bytes + need <= self.max_bytes
        skip: set = set()
        while self._bytes + need > self.max_bytes:
            victim = self._pick_victim(protect, skip)
            if victim is None:
                return False
            if self._demote_node(victim, protect):
                continue
            entry = self._store[victim]
            if any(c in self._store for c in entry["children"]):
                # interior node (its children are swapped): removal would
                # orphan the subtree, and it can't demote — leave it and
                # look for another victim
                skip.add(victim)
                continue
            self._evict_node(victim)
        return True

    def _demote_node(self, key: str, protect: frozenset) -> bool:
        """host -> swapped: move the node's byte charge from the cache
        budget into the HostSwapPool (the arrays stay where they are — the
        tier is an accounting boundary; what changes is whose budget holds
        the bytes and that the node sheds all HBM residency)."""
        entry = self._store[key]
        if self.swap_pool is None:
            return False
        if not self._swap_reserve(entry["bytes"], protect):
            return False
        self._drop_device(entry)
        self._unpin_entry(entry)
        entry["swapped"] = True
        self._bytes -= entry["bytes"]
        self._swap_bytes += entry["bytes"]
        self.stats["demotions"] += 1
        tm.PREFIX_DEMOTE.inc()
        return True

    def _swap_reserve(self, nbytes: int, protect: frozenset) -> bool:
        """Reserve cache-tagged swap bytes, evicting our own coldest swapped
        nodes to stay under the cache's fraction of the shared budget (the
        session swap path must always find room the cache didn't eat)."""
        cap = int(self.swap_frac * self.swap_pool.max_size_bytes)
        if nbytes > cap:
            return False
        while True:
            # ownership transfer: the reservation belongs to the demoted
            # node; _promote_host / _evict_node free(kind="cache") it
            if self._swap_bytes + nbytes <= cap and self.swap_pool.try_reserve(
                nbytes, kind="cache"
            ):
                return True
            victim = self._pick_swapped_victim(protect)
            if victim is None:
                return False
            self._evict_node(victim)
            self.stats["swap_evictions"] += 1
            tm.PREFIX_SWAP_EVICT.inc()

    def _pick_swapped_victim(self, protect: frozenset) -> Optional[str]:
        """Coldest childless swapped node (swap-tier eviction order).
        ``protect`` covers the chain being probed/stored — a node mid-
        promotion must not be evicted out from under its own promotion."""
        best_key = None
        best_rank = None
        shares: Dict = {}
        for key, entry in self._store.items():
            if not entry.get("swapped") or key in protect:
                continue
            if any(c in self._store for c in entry["children"]):
                continue
            rank = (
                -self._tenant_share(shares, entry.get("tenant")),
                entry["hits"],
                entry["last_use"],
            )
            if best_rank is None or rank < best_rank:
                best_key, best_rank = key, rank
        return best_key

    def _promote_host(self, key: str, protect: frozenset) -> bool:
        """swapped -> host on a hit (the swap-in of the cache plane): free
        the pool reservation and re-charge the cache budget, making room by
        demoting colder nodes. Failure is benign — the node still serves,
        it just keeps charging the swap pool until a later hit succeeds."""
        entry = self._store.get(key)
        if entry is None or not entry.get("swapped"):
            return False
        if not self._make_room(entry["bytes"], protect):
            return False
        self.swap_pool.free(entry["bytes"], kind="cache")
        self._swap_bytes -= entry["bytes"]
        entry["swapped"] = False
        self._bytes += entry["bytes"]
        self.stats["promotions"] += 1
        tm.PREFIX_PROMOTE.inc()
        return True

    def maybe_promote_device(self, keys: Sequence[str], n: int) -> int:
        """host -> HBM for hot hit-path nodes: upload k/v of every node on
        ``keys[:n]`` that has been hit at least PROMOTE_MIN_HITS times and
        lacks device refs. Called by the handler OFF the event loop after a
        host-tier hit (uploads are multi-MB device transfers); by the next
        probe the whole path is device-resident and the session seeds with
        zero host->device traffic. Returns the number promoted."""
        if self.device_max_bytes <= 0 or self.policy != "radix":
            return 0
        import jax.numpy as jnp  # lazy: host-only users never touch jax

        promoted = 0
        for key in list(keys[:n]):
            with self._mutex:
                entry = self._store.get(key)
                if (
                    entry is None
                    or entry.get("swapped")
                    or "kd" in entry
                    or entry["hits"] < PROMOTE_MIN_HITS
                ):
                    continue
                k_host, v_host = entry["k"], entry["v"]
            # the uploads run OUTSIDE the mutex: a concurrent probe must not
            # stall behind a host->device copy
            kd = jnp.asarray(k_host)
            vd = jnp.asarray(v_host)
            with self._mutex:
                entry = self._store.get(key)
                if entry is None or "kd" in entry or entry.get("swapped"):
                    continue
                dev_bytes = int(kd.nbytes) + int(vd.nbytes)
                if dev_bytes > self.device_max_bytes:
                    continue
                self._evict_device(self.device_max_bytes - dev_bytes)
                entry["kd"], entry["vd"] = kd, vd
                entry["dev_bytes"] = dev_bytes
                self._dev_bytes += dev_bytes
                promoted += 1
                self.stats["promotions"] += 1
                tm.PREFIX_PROMOTE.inc()
        if promoted:
            with self._mutex:
                self._bill()
        return promoted

    # ------------------------------------------------------------ device tier

    def _attach_device(self, entry: dict, k_dev, v_dev, t0: int, t1: int) -> None:
        """Pin the [t0, t1) token slice of the device arrays onto ``entry``
        (no-op without device arrays, budget, or when already resident)."""
        if k_dev is None or self.device_max_bytes <= 0 or "kd" in entry:
            return
        kd = k_dev[:, :, t0:t1]
        vd = v_dev[:, :, t0:t1]
        dev_bytes = int(kd.nbytes) + int(vd.nbytes)
        if dev_bytes <= self.device_max_bytes:
            self._evict_device(self.device_max_bytes - dev_bytes)
            entry["kd"], entry["vd"] = kd, vd
            entry["dev_bytes"] = dev_bytes
            self._dev_bytes += dev_bytes

    def _attach_pages(self, entry: dict, seg_pages, pool, epoch: int) -> bool:
        """Attach a pinned page run to ``entry`` (paged tier). Replaces a
        stale-epoch run; returns False when the entry already holds a live
        one (caller unpins the incoming duplicate)."""
        if "pages" in entry:
            if entry.get("pages_epoch") == getattr(pool, "page_epoch", -1):
                return False
            self._unpin_entry(entry)  # stale epoch: pins died with the pool
        entry["pages"] = list(seg_pages)
        entry["pages_pool"] = pool
        entry["pages_epoch"] = epoch
        return True

    def _unpin_entry(self, entry: dict) -> None:
        """Release an entry's page pins back to its batcher (eviction/clear/
        demotion). Best-effort: a reset batcher ignores stale-epoch unpins."""
        pages = entry.pop("pages", None)
        pool = entry.pop("pages_pool", None)
        epoch = entry.pop("pages_epoch", 0)
        if pages and pool is not None:
            try:
                pool.unpin_pages(pages, epoch)
            except Exception:  # swarmlint: disable=no-silent-except — racing batcher close/reset: the pool (and its pins) are gone anyway
                pass

    def _drop_device(self, entry: dict) -> None:
        """Drop one entry's HBM array refs (host copy stays). Counted: the
        device tier's churn was invisible in telemetry before this."""
        dev = entry.pop("dev_bytes", 0)
        if dev:
            entry.pop("kd", None)
            entry.pop("vd", None)
            self._dev_bytes -= dev
            self.stats["device_evictions"] += 1
            tm.PREFIX_DEVICE_EVICT.inc()

    def _evict_device(self, target_bytes: int) -> None:
        """Drop HBM references until the device tier fits ``target_bytes``;
        host copies stay, so this only downgrades hits. Flat policy drops
        oldest-first (store order); radix drops coldest-first (economics)."""
        if self._dev_bytes <= target_bytes:
            return
        entries = list(self._store.values())
        if self.policy == "radix":
            entries.sort(key=lambda e: (e["hits"], e["last_use"]))
        for entry in entries:
            if self._dev_bytes <= target_bytes:
                break
            self._drop_device(entry)

    # -------------------------------------------------------------- eviction

    def _evict_node(self, key: str) -> None:
        """Remove a node outright from whatever tier holds it, releasing its
        HBM pins and its byte charge, and detaching it from the tree."""
        entry = self._store.pop(key)
        self._drop_device(entry)
        self._unpin_entry(entry)
        if entry.get("swapped"):
            self.swap_pool.free(entry["bytes"], kind="cache")
            self._swap_bytes -= entry["bytes"]
        else:
            self._bytes -= entry["bytes"]
        parent = self._store.get(entry.get("parent"))
        if parent is not None:
            parent["children"].discard(key)
        self.stats["evictions"] += 1
        tm.PREFIX_EVICT.inc()

    def clear(self) -> None:
        """Drop every node (stats are kept — they describe the lifetime)."""
        with self._mutex:
            for entry in self._store.values():
                self._unpin_entry(entry)
                if entry.get("swapped") and self.swap_pool is not None:
                    self.swap_pool.free(entry["bytes"], kind="cache")
            self._store.clear()
            self._bytes = 0
            self._dev_bytes = 0
            self._swap_bytes = 0
            self._bill()

    # ------------------------------------------------------------------ views

    def worth_storing(
        self, keys: Sequence[str], first: int, est_entry_bytes: int,
        device_capable: bool = False, pages_pool=None,
    ) -> bool:
        """Whether a store pass would actually add anything (callers use
        this to skip the device->host snapshot entirely otherwise):

        - at least one novel key whose single entry fits the budget; or
        - ``device_capable`` and a host-resident key that lacks device refs
          (a hot entry first stored by a pooled/lockstep path gains HBM
          residency on its next device-capable store — without this check a
          host-resident hot entry reported "nothing to add" and was locked
          out of the tier forever); or
        - ``pages_pool`` given and a key without a live page run in THAT
          pool at its current epoch (pool resets kill pins; the re-store
          re-pins them).
        """
        if est_entry_bytes > self.max_bytes:
            return False
        with self._mutex:
            tail = keys[first:]
            if any(k not in self._store for k in tail):
                return True
            if device_capable and self.device_max_bytes > 0:
                for k in tail:
                    entry = self._store[k]
                    if "kd" not in entry and not entry.get("swapped"):
                        return True
            if pages_pool is not None and getattr(pages_pool, "page_size", None):
                epoch = getattr(pages_pool, "page_epoch", -1)
                for k in tail:
                    entry = self._store[k]
                    if entry.get("swapped"):
                        continue
                    if (
                        entry.get("pages") is None
                        or entry.get("pages_pool") is not pages_pool
                        or entry.get("pages_epoch") != epoch
                    ):
                        return True
            return False

    def _bill(self) -> None:
        """Push per-tenant resident bytes (host + device + swap + pinned
        pages) to the ledger as the new piecewise-constant cache-residency
        rate. Called (under the mutex) at the end of every mutating op."""
        if self.ledger is None:
            return
        by_tenant: Dict[Optional[str], float] = {}
        for entry in self._store.values():
            nbytes = entry["bytes"] + entry.get("dev_bytes", 0)
            pages = entry.get("pages")
            if pages:
                nbytes += len(pages) * int(
                    getattr(entry.get("pages_pool"), "page_nbytes", 0) or 0
                )
            tenant = entry.get("tenant")
            by_tenant[tenant] = by_tenant.get(tenant, 0.0) + nbytes
        try:
            self.ledger.set_cache_rates(by_tenant)
        except Exception as e:
            logger.warning(f"prefix-cache ledger billing failed: {e}")

    def summary(self) -> dict:
        with self._mutex:
            page_bytes = 0
            swapped = 0
            max_depth = 0
            for e in self._store.values():
                if e.get("swapped"):
                    swapped += 1
                pages = e.get("pages")
                if pages:
                    page_bytes += len(pages) * int(
                        getattr(e.get("pages_pool"), "page_nbytes", 0) or 0
                    )
                max_depth = max(max_depth, e.get("depth", 0))
            return {
                "policy": self.policy,
                "segments": len(self._store),
                "bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "host_segments": len(self._store) - swapped,
                "swap_segments": swapped,
                "swap_bytes": self._swap_bytes,
                "device_segments": sum(1 for e in self._store.values() if "kd" in e),
                "device_bytes": self._dev_bytes,
                "device_max_bytes": self.device_max_bytes,
                "page_segments": sum(1 for e in self._store.values() if "pages" in e),
                "page_bytes": page_bytes,
                "hbm_bytes": self._dev_bytes + page_bytes,
                "max_depth": max_depth,
                **self.stats,
            }


# the handler (and every test written against the flat cache) constructs
# ``PrefixCache``; the radix tree IS the prefix cache now, with the flat
# behavior preserved behind policy="lru"
PrefixCache = RadixPrefixCache
