"""The server's RPC surface (counterpart of reference
src/petals/server/handler.py:55-592 — rpc_inference / rpc_forward /
rpc_backward / rpc_info; streaming variants are subsumed by the framed
transport, which chunks large frames at the protocol level).

One handler instance serves one span of blocks. Sessions (multi-step inference
with server-held KV) are plain dicts in this process — the reference's
cross-process session registry (handler.py:197-245) is unnecessary in a
single-process JAX server.

Wire payloads (msgpack):
- inference open:  {uids, max_length, batch_size, active_adapter?, session_id?}
- inference step:  {tensors: {hidden, prompts?, hypo_ids?}, start_from_position?, step_id?}
- inference reply: {tensors: {hidden}, position}
- forward:         {uids, tensors: {hidden, prompts?}, active_adapter?}
- backward:        {uids, tensors: {hidden, grad_out, prompts?}, active_adapter?}
- info:            {} -> ServerInfo dict + cache stats
"""

from __future__ import annotations

import asyncio
from typing import Dict, Optional, Tuple

import numpy as np

from petals_tpu.data_structures import CHAIN_DELIMITER, ModuleUID, parse_uid
from petals_tpu.rpc.serialization import deserialize_array, serialize_array, CompressionType
from petals_tpu.rpc.server import RpcContext, RpcServer
from petals_tpu.server.backend import TransformerBackend
from petals_tpu.server.memory_cache import MemoryCache
from petals_tpu.server.task_queue import (
    PRIORITY_INFERENCE,
    PRIORITY_TRAINING,
    PriorityTaskQueue,
)
from petals_tpu.utils.logging import get_logger
from petals_tpu.utils.misc import is_dummy

logger = get_logger(__name__)


class TransformerHandler:
    def __init__(
        self,
        backend: TransformerBackend,
        *,
        dht_prefix: str,
        memory_cache: MemoryCache,
        server_info_fn=None,
        request_timeout: float = 3 * 60,
        session_timeout: float = 30 * 60,
        step_timeout: float = 5 * 60,
        compression: CompressionType = CompressionType.NONE,
    ):
        self.backend = backend
        self.dht_prefix = dht_prefix
        self.memory_cache = memory_cache
        self.server_info_fn = server_info_fn
        self.request_timeout = request_timeout
        self.session_timeout = session_timeout
        self.step_timeout = step_timeout
        self.compression = compression
        self.queue = PriorityTaskQueue()
        self.queue.start()
        self._sub_backends: Dict[Tuple[int, int], TransformerBackend] = {}

    def register(self, server: RpcServer) -> None:
        server.add_unary_handler("ptu.forward", self.rpc_forward)
        server.add_unary_handler("ptu.backward", self.rpc_backward)
        server.add_unary_handler("ptu.info", self.rpc_info)
        server.add_stream_handler("ptu.inference", self.rpc_inference)

    def shutdown(self) -> None:
        self.queue.shutdown()

    # ------------------------------------------------------------------ helpers

    def _parse_chain(self, uids: str) -> Tuple[int, int]:
        """Validate a chain of UIDs against our span; return (start, end) relative
        to the backend's first block."""
        parts = uids.split(CHAIN_DELIMITER) if isinstance(uids, str) else list(uids)
        if not parts:
            raise ValueError("Empty uid chain")
        indices = []
        for uid in parts:
            prefix, idx = parse_uid(uid)
            if prefix != self.dht_prefix:
                raise ValueError(f"UID {uid!r} does not match served prefix {self.dht_prefix!r}")
            indices.append(idx)
        lo, hi = indices[0], indices[-1] + 1
        if indices != list(range(lo, hi)):
            raise ValueError(f"UID chain must be contiguous, got {indices}")
        first, last = self.backend.first_block, self.backend.first_block + self.backend.n_blocks
        if lo < first or hi > last:
            raise ValueError(
                f"Requested blocks [{lo}, {hi}) outside served span [{first}, {last})"
            )
        return lo - first, hi - first

    def _get_tensor(self, payload: dict, name: str) -> Optional[np.ndarray]:
        wire = (payload.get("tensors") or {}).get(name)
        if wire is None:
            return None
        arr = deserialize_array(wire)
        return None if is_dummy(arr) else arr

    # ------------------------------------------------------------------ rpc methods

    async def rpc_forward(self, payload, ctx: RpcContext):
        start, end = self._parse_chain(payload["uids"])
        hidden = self._get_tensor(payload, "hidden")
        prompts = self._get_tensor(payload, "prompts")
        if hidden is None or hidden.ndim != 3:
            raise ValueError("rpc_forward expects a [batch, seq, hidden] tensor")
        backend = self._sub_backend(start, end)
        adapter = payload.get("active_adapter")
        out = await asyncio.wait_for(
            self.queue.submit(
                lambda: np.asarray(backend.forward(hidden, prompts=prompts, active_adapter=adapter)),
                priority=PRIORITY_TRAINING,
                size=hidden.shape[0] * hidden.shape[1],
            ),
            self.request_timeout,
        )
        return {"tensors": {"hidden": serialize_array(out, self.compression)}}

    async def rpc_backward(self, payload, ctx: RpcContext):
        start, end = self._parse_chain(payload["uids"])
        hidden = self._get_tensor(payload, "hidden")
        grad_out = self._get_tensor(payload, "grad_out")
        prompts = self._get_tensor(payload, "prompts")
        if hidden is None or grad_out is None:
            raise ValueError("rpc_backward expects hidden and grad_out tensors")
        backend = self._sub_backend(start, end)
        adapter = payload.get("active_adapter")

        def run():
            grad_hidden, grad_prompts = backend.backward(
                hidden, grad_out, prompts=prompts, active_adapter=adapter
            )
            return np.asarray(grad_hidden), (
                np.asarray(grad_prompts) if grad_prompts is not None else None
            )

        grad_hidden, grad_prompts = await asyncio.wait_for(
            self.queue.submit(
                run, priority=PRIORITY_TRAINING, size=hidden.shape[0] * hidden.shape[1]
            ),
            self.request_timeout,
        )
        tensors = {"grad_hidden": serialize_array(grad_hidden, self.compression)}
        if grad_prompts is not None:
            tensors["grad_prompts"] = serialize_array(grad_prompts, self.compression)
        return {"tensors": tensors}

    async def rpc_info(self, payload, ctx: RpcContext):
        info = dict(self.server_info_fn()) if self.server_info_fn else {}
        info.update(
            cache_tokens_available=max(
                self.memory_cache.bytes_left // max(self.backend.cache_bytes_per_token(), 1), 0
            ),
            first_block=self.backend.first_block,
            n_blocks=self.backend.n_blocks,
            dht_prefix=self.dht_prefix,
        )
        return info

    async def rpc_inference(self, requests, ctx: RpcContext):
        """Bidirectional inference stream: open -> step* (reference
        handler.py:132-195 + block_functions.iterate_rpc_inference)."""
        open_msg = await asyncio.wait_for(anext(requests), self.step_timeout)
        start, end = self._parse_chain(open_msg["uids"])
        max_length = int(open_msg["max_length"])
        batch_size = int(open_msg.get("batch_size", 1))
        active_adapter = open_msg.get("active_adapter")
        backend = self._sub_backend(start, end)
        backend.params_for(active_adapter)  # validate the adapter exists up front

        descriptors = backend.cache_descriptors(batch_size, max_length, 0, end - start)
        async with self.memory_cache.allocate_cache(
            *descriptors, timeout=open_msg.get("alloc_timeout")
        ) as handles:
            with self.memory_cache.use_cache(*handles) as (k_buf, v_buf):
                kv = (k_buf, v_buf)
            position = 0
            yield {"session_open": True, "position": 0, "max_length": max_length}

            while True:
                try:
                    step = await asyncio.wait_for(anext(requests), self.session_timeout)
                except StopAsyncIteration:
                    break
                if step is None:
                    break

                start_from = step.get("start_from_position")
                if start_from is not None:
                    if start_from > position:
                        raise ValueError(
                            f"start_from_position {start_from} is ahead of cache ({position})"
                        )
                    position = int(start_from)  # rollback (speculative decoding)

                hidden = self._get_tensor(step, "hidden")
                prompts = self._get_tensor(step, "prompts")
                hypo_ids = self._get_tensor(step, "hypo_ids")
                seq = 0 if hidden is None else hidden.shape[1]
                if hidden is not None and position + seq > max_length:
                    raise ValueError(
                        f"Step of {seq} tokens at position {position} exceeds max_length {max_length}"
                    )

                if hidden is None or seq == 0:
                    # cache probe step (reference block_functions.py:209-211)
                    yield {"tensors": {}, "position": position}
                    continue

                pos = position

                def run_step():
                    out, new_kv = backend.inference_step(
                        hidden, kv, pos, prompts=prompts, hypo_ids=hypo_ids,
                        active_adapter=active_adapter,
                    )
                    return np.asarray(out), new_kv

                out, kv = await asyncio.wait_for(
                    self.queue.submit(
                        run_step, priority=PRIORITY_INFERENCE, size=batch_size * seq
                    ),
                    self.step_timeout,
                )
                # keep the allocator's view coherent (old buffers were donated)
                self.memory_cache.update_cache(handles[0], kv[0])
                self.memory_cache.update_cache(handles[1], kv[1])
                position += seq
                yield {
                    "tensors": {"hidden": serialize_array(out, self.compression)},
                    "position": position,
                }

    def _sub_backend(self, start: int, end: int) -> TransformerBackend:
        if start == 0 and end == self.backend.n_blocks:
            return self.backend
        # Partial chains get their own backend over a sliced param stack —
        # cached so each (start, end) compiles its programs exactly once.
        key = (start, end)
        if key not in self._sub_backends:
            sliced = self.backend._slice_params(start, end)
            sub = TransformerBackend(
                self.backend.family,
                self.backend.cfg,
                sliced,
                first_block=self.backend.first_block + start,
                n_blocks=end - start,
                memory_cache=self.memory_cache,
                compute_dtype=self.backend.compute_dtype,
                cache_dtype=self.backend.cache_dtype,
                max_chunk_size_bytes=self.backend.max_chunk_size_bytes,
                use_flash=self.backend.use_flash,
                mesh=self.backend.mesh,
            )
            import jax

            sub.adapters = {
                name: (jax.tree_util.tree_map(lambda x: x[start:end], stacked), scaling)
                for name, (stacked, scaling) in self.backend.adapters.items()
            }
            self._sub_backends[key] = sub
        return self._sub_backends[key]

