"""The server's RPC surface (counterpart of reference
src/petals/server/handler.py:55-592 — rpc_inference / rpc_forward /
rpc_backward / rpc_info; streaming variants are subsumed by the framed
transport, which chunks large frames at the protocol level).

One handler instance serves one span of blocks. Sessions (multi-step inference
with server-held KV) are plain dicts in this process — the reference's
cross-process session registry (handler.py:197-245) is unnecessary in a
single-process JAX server.

Wire payloads (msgpack):
- inference open:  {uids, max_length, batch_size, active_adapter?, session_id?}
- inference step:  {tensors: {hidden, prompts?, hypo_ids?}, start_from_position?, step_id?}
- inference reply: {tensors: {hidden}, position}
- kv import step:  {kv_import: {position}, tensors: {k, v}} (first step only)
- kv adopt step:   {kv_adopt: {session_id, position}} (first step only; seeds
                   from KV this server already holds — migrated in or parked)
- session export:  {session_id, start, end, compression?} -> {position, tensors: {k, v}, ...}
                   (or {migrated_to: {peer_id, addr, position}} redirect)
- session migrate: {session_id, start, end, position, batch_size, max_length,
                   trace_id?, tensors: {k, v}} -> {ok, position} (server->server)
- forward:         {uids, tensors: {hidden, prompts?}, active_adapter?}
- backward:        {uids, tensors: {hidden, grad_out, prompts?}, active_adapter?}
- info:            {} -> ServerInfo dict + cache stats
"""

from __future__ import annotations

import asyncio
import contextlib
import time
from typing import Dict, Optional, Tuple

import numpy as np

from petals_tpu import chaos
from petals_tpu.data_structures import CHAIN_DELIMITER, ModuleUID, parse_uid
from petals_tpu.rpc.protocol import validate_gen_sampling
from petals_tpu.rpc.serialization import deserialize_array, serialize_array, CompressionType
from petals_tpu.rpc.server import RpcContext, RpcServer
from petals_tpu.server.backend import TransformerBackend
from petals_tpu.server.memory_cache import MemoryCache
from petals_tpu.server.task_queue import (
    PRIORITY_INFERENCE,
    PRIORITY_TRAINING,
    PriorityTaskQueue,
)
from petals_tpu.telemetry import (
    new_trace_id,
    normalize_trace_id,
    reset_trace_id,
    set_trace_id,
)
from petals_tpu.telemetry import instruments as tm
from petals_tpu.telemetry.exposition import telemetry_digest
from petals_tpu.telemetry.observatory import compile_stats_digest
from petals_tpu.utils.asyncio_utils import log_exception_callback
from petals_tpu.utils.logging import get_logger
from petals_tpu.utils.misc import is_dummy
from petals_tpu.utils.tracing import device_annotation, get_tracer

logger = get_logger(__name__)


class TransformerHandler:
    def __init__(
        self,
        backend: TransformerBackend,
        *,
        dht_prefix: str,
        memory_cache: MemoryCache,
        server_info_fn=None,
        request_timeout: float = 3 * 60,
        session_timeout: float = 30 * 60,
        step_timeout: float = 5 * 60,
        compression: CompressionType = CompressionType.NONE,
        identity=None,  # authenticates the server->server push plane
        inference_max_length: Optional[int] = None,  # cap on per-session max_length
        batching: bool = True,  # continuous batching across decode sessions
        batch_lanes: int = 8,
        batch_max_length: Optional[int] = None,  # pool lane length (tokens)
        page_size: Optional[int] = None,  # paged KV: tokens per page; None/0 = dense pool
        n_pages: Optional[int] = None,  # paged KV pool size; None = lanes * max_pages
        prefill_token_budget: int = 512,  # prefill tokens per mixed batched step
        swap_host_bytes: int = 0,  # host-RAM KV swap tier for preemption; 0 disables
        preemption_policy: str = "lru",  # victim choice: lru | largest | off
        prefix_cache_bytes: int = 256 * 2**20,  # 0 disables prefix caching
        prefix_share_scope: str = "swarm",  # "swarm" shares across clients; "peer" salts per client
        prefix_device_bytes: int = 256 * 2**20,  # HBM tier of the prefix cache; 0 disables
        prefix_cache_policy: str = "radix",  # "radix" tree + tiers | "lru" flat baseline
        server_gen_params=None,  # client leaves (embed/norm/head) for device-side generation
        draft_model=None,  # server.spec_decode.DraftModel: speculative decoding
        spec_k: Optional[int] = None,  # drafts per lane per tick; None -> draft's k
    ):
        self.backend = backend
        self.dht_prefix = dht_prefix
        self.memory_cache = memory_cache
        self.server_info_fn = server_info_fn
        self.request_timeout = request_timeout
        self.session_timeout = session_timeout
        self.step_timeout = step_timeout
        self.compression = compression
        self.inference_max_length = inference_max_length
        self.queue = PriorityTaskQueue()
        self.queue.start()
        self._sub_backends: Dict[Tuple[int, int], TransformerBackend] = {}
        # own peer id string, for integrity chaos targeting (a single-process
        # test swarm shares ONE chaos plane: rules single out a replica by
        # matching the detail string, which therefore must carry the peer)
        self._peer_str = ""
        try:
            if identity is not None:
                self._peer_str = identity.peer_id.to_string()
        except Exception as e:
            logger.debug(f"Peer id unavailable for chaos targeting: {e}")
        import zlib

        self._corrupt_seed = zlib.crc32(self._peer_str.encode("utf-8"))
        # server-to-server activation push (reference handler.py:310-350):
        # session_id -> queue of pushed step payloads
        self._push_queues: Dict[str, asyncio.Queue] = {}
        # KV migration (beyond reference): live-session registry for
        # ptu.session_export, and host-RAM parking of session KV so a
        # draining server can hand caches to replacements instead of making
        # clients recompute the prefill (client/inference_session.py repair).
        self._session_registry: Dict[str, dict] = {}
        self._parked: Dict[str, dict] = {}
        self.park_ttl = 60.0
        self.draining = False
        # Peer-to-peer migration (ptu.session_migrate): KV pushed here by a
        # draining/rebalancing peer, held until the client re-opens and adopts
        # it (kv_adopt step) or the TTL lapses. Byte-budgeted: a swarm of
        # draining peers must not be able to OOM this host.
        self._migrated: Dict[str, dict] = {}
        # sessions we pushed away: session_id -> forwarding address, served
        # as a redirect from rpc_session_export so the client finds the KV
        self._migrated_away: Dict[str, dict] = {}
        self._migrated_bytes = 0
        self.migrate_in_budget_bytes = 512 * 2**20
        self.migrate_ttl = 120.0
        from petals_tpu.rpc.pool import ConnectionPool

        self._push_pool = ConnectionPool(identity=identity)
        self._push_tasks: set = set()
        # set by abort_migrations() (Server.shutdown): in-flight migration
        # pushes stop waiting on their peer and abort immediately, so a
        # slow/chaos-delayed destination can never hang teardown
        self._migrate_abort = asyncio.Event()

        # Continuous batching (server/batching.py): concurrent single-stream
        # decode sessions on the full span coalesce into one device step.
        # Composes with TP meshes (the batched program shards like the
        # single-session one) and with multi-host lockstep (pool + lane ops
        # broadcast — parallel/multihost.py v3).
        self.batcher = None
        if batching:
            from petals_tpu.server.batching import DecodeBatcher

            self.batcher = DecodeBatcher(
                backend,
                memory_cache,
                self.queue,
                n_lanes=batch_lanes,
                max_length=batch_max_length or inference_max_length or 1024,
                gen_params=server_gen_params,
                page_size=page_size,
                n_pages=n_pages,
                prefill_token_budget=prefill_token_budget,
                swap_host_bytes=swap_host_bytes,
                preemption_policy=preemption_policy,
                draft_model=draft_model,
                spec_k=spec_k,
            )

        # Content-addressed prefix cache (server/prefix_cache.py): sessions
        # sharing a prompt prefix skip its prefill compute. Under lockstep
        # the staging rides the v2 broadcast ops (import_kv / export_kv).
        self.prefix_cache = None
        if prefix_share_scope not in ("swarm", "peer"):
            raise ValueError(f"prefix_share_scope must be 'swarm' or 'peer', got {prefix_share_scope!r}")
        # "peer" folds the requester's peer id into the hash salt: no
        # cross-client sharing, which closes the cache-hit timing side
        # channel an open swarm otherwise accepts (server/prefix_cache.py
        # module docstring spells out the tradeoff)
        self.prefix_share_scope = prefix_share_scope
        self.server_gen_params = server_gen_params
        self.draft_model = draft_model
        self.spec_k = spec_k
        if prefix_cache_bytes > 0:
            from petals_tpu.server.prefix_cache import PrefixCache
            from petals_tpu.telemetry.ledger import get_ledger

            ledger = get_ledger()
            self.prefix_cache = PrefixCache(
                prefix_cache_bytes, device_max_bytes=prefix_device_bytes,
                policy=prefix_cache_policy,
                # the radix swap tier rides the batcher's HostSwapPool (one
                # budget with session preemption); a private-session-only
                # server has no pool, so demotion degrades to eviction
                swap_pool=(
                    self.batcher.swap_pool if self.batcher is not None else None
                ),
                # eviction consults the DRF rank: the dominant tenant's cold
                # nodes go first, and residency bills to the owning tenant
                usage_fn=ledger.peer_dominant_share,
                ledger=ledger,
            )
        if (
            self.prefix_cache is not None
            and self.batcher is not None
            and self.batcher.page_size is not None
        ):
            from petals_tpu.server.prefix_cache import SEGMENT_TOKENS

            # page-granular prefix sharing slices pinned page runs at segment
            # boundaries, so segments must tile exactly into pages
            if SEGMENT_TOKENS % self.batcher.page_size != 0:
                raise ValueError(
                    f"page_size={self.batcher.page_size} must divide the prefix-cache "
                    f"segment size ({SEGMENT_TOKENS} tokens)"
                )

    async def swap_backend(self, new_backend) -> None:
        """Retarget the handler at a freshly built backend (span reload /
        rebalance). Private sessions opened on the old span keep computing
        against the old backend object (captured at session open) until they
        close; POOLED sessions cannot — the lane pool is shared — so the old
        batcher is closed (its tenants' next step fails loudly and clients
        failover, the same recovery path as a pool reset) and a fresh pool
        opens lazily for the new span. Without this swap the old batcher
        kept serving the NEW span's pooled decode steps with the OLD span's
        weights — silently wrong outputs after every rebalance."""
        self.backend = new_backend
        self._sub_backends = {}
        if self.batcher is not None:
            from petals_tpu.server.batching import DecodeBatcher

            old = self.batcher
            self.batcher = DecodeBatcher(
                new_backend,
                self.memory_cache,
                self.queue,
                n_lanes=old.n_lanes,
                max_length=old.max_length,
                gen_params=self.server_gen_params,
                page_size=old.page_size,
                n_pages=old.n_pages or None,
                prefill_token_budget=old.prefill_token_budget,
                swap_host_bytes=old.swap_pool.max_size_bytes,
                preemption_policy=old._scheduler.policy,
                draft_model=self.draft_model,
                spec_k=self.spec_k,
            )
            await old.close()

    def register(self, server: RpcServer) -> None:
        server.add_unary_handler("ptu.forward", self.rpc_forward)
        server.add_unary_handler("ptu.backward", self.rpc_backward)
        server.add_unary_handler("ptu.info", self.rpc_info)
        server.add_unary_handler("ptu.push", self.rpc_push)
        server.add_unary_handler("ptu.session_export", self.rpc_session_export)
        server.add_unary_handler("ptu.session_migrate", self.rpc_session_migrate)
        server.add_unary_handler("ptu.session_handoff", self.rpc_session_handoff)
        server.add_unary_handler("ptu.probe", self.rpc_probe)
        server.add_stream_handler("ptu.inference", self.rpc_inference)

    async def rpc_push(self, payload, ctx: RpcContext):
        """Accept hidden states pushed by the previous server in a chain
        (reference handler.py:310-318)."""
        session_id = payload.get("session_id")
        queue = self._push_queues.get(session_id)
        if queue is None:
            raise KeyError(f"No active inference session {session_id!r} on this server")
        try:
            queue.put_nowait(payload)
        except asyncio.QueueFull:
            # Push is best-effort (the client relay is authoritative); refusing
            # beats buffering an unbounded backlog from a runaway upstream peer.
            raise RuntimeError(f"Push queue full for session {session_id!r}")
        return {"ok": True}

    async def rpc_session_export(self, payload, ctx: RpcContext):
        """Hand a session's KV cache (sliced to its position) to the caller so a
        replacement server can be seeded without recomputing the prefill.
        Serves live sessions and sessions parked by a draining server."""
        session_id = payload.get("session_id")
        want_start = int(payload["start"])
        want_end = int(payload["end"])
        comp = CompressionType(payload.get("compression", "none"))
        self._prune_parked()

        # migrated-away first, even while the drained stream is still open:
        # the copy at the destination is the authoritative one now, and an
        # adopt there (plus a replayed tail if a step raced the park) moves
        # zero KV bytes over the client's link
        fwd = self._migrated_away.get(session_id)
        if fwd is not None:
            return {"migrated_to": dict(fwd)}

        # live first: a parked snapshot goes stale if steps kept flowing
        # between drain and shutdown
        live = self._session_registry.get(session_id)
        if live is not None:
            if not (live["start"] <= want_start < want_end <= live["end"]):
                raise ValueError(
                    f"Requested blocks [{want_start}, {want_end}) outside session span "
                    f"[{live['start']}, {live['end']})"
                )
            # slice the requested block range ON DEVICE: a route upgrade may
            # ask for a narrow range of a long-context span, and the full-span
            # host copy would be 100s of wasted MB per request
            src = await self._snapshot_session(
                live, want_start - live["start"], want_end - live["start"]
            )
            b0, b1 = 0, want_end - want_start
        else:
            self._prune_migrated()
            # parked (we are draining) or migrated-in (a peer drained onto us
            # but the client's new chain doesn't end here): both are host
            # snapshots with the same layout
            src = self._parked.get(session_id) or self._migrated.get(session_id)
            if src is None:
                raise KeyError(f"No live or parked session {session_id!r}")
            if not (src["start"] <= want_start < want_end <= src["end"]):
                raise ValueError(
                    f"Requested blocks [{want_start}, {want_end}) outside session span "
                    f"[{src['start']}, {src['end']})"
                )
            b0, b1 = want_start - src["start"], want_end - src["start"]
        position = src["position"]
        if position <= 0:
            raise ValueError(f"Session {session_id!r} has no cached tokens yet")
        # migrated-in entries may hold PACKED codes + scales (quantized wire);
        # the client-facing export protocol stays dense, so decode the slice
        kv_quant = src.get("kv_quant") or "none"

        def _dense(name: str):
            arr = src[name][b0:b1]
            if kv_quant != "none":
                from petals_tpu.ops.paged_attention import dequantize_kv_np

                arr = dequantize_kv_np(arr, src[name + "_scales"][b0:b1], kv_quant)
            return serialize_array(arr, comp)

        return {
            "position": position,
            "start": want_start,
            "end": want_end,
            "batch_size": src["batch_size"],
            "tensors": {"k": _dense("k"), "v": _dense("v")},
        }

    async def rpc_session_migrate(self, payload, ctx: RpcContext):
        """Accept a session's KV pushed by a draining/rebalancing peer
        (server->server, no client in the loop). The entry is held in host
        RAM under a byte budget until the client re-opens here and adopts it
        with a ``kv_adopt`` step, exports it onward, or the TTL lapses."""
        from petals_tpu.telemetry import get_journal

        session_id = payload["session_id"]
        src_start = int(payload["start"])
        src_end = int(payload["end"])
        position = int(payload["position"])
        batch_size = int(payload["batch_size"])
        max_length = int(payload["max_length"])
        trace_id = normalize_trace_id(payload.get("trace_id"))
        if self.draining:
            raise RuntimeError("Server is draining: not accepting migrated sessions")
        first = self.backend.first_block
        if not (first <= src_start < src_end <= first + self.backend.n_blocks):
            raise ValueError(
                f"Migrated span [{src_start}, {src_end}) outside this server's "
                f"blocks [{first}, {first + self.backend.n_blocks})"
            )
        if position <= 0:
            raise ValueError("Refusing to migrate a session with no cached tokens")
        tensors = payload.get("tensors") or {}
        if "k" not in tensors or "v" not in tensors:
            raise ValueError("session_migrate needs k and v tensors")
        from petals_tpu.ops.paged_attention import KV_QUANT_KINDS

        kv_quant = str(payload.get("kv_quant") or "none")
        if kv_quant not in KV_QUANT_KINDS:
            raise ValueError(f"Unknown kv_quant {kv_quant!r} in session_migrate")

        def parse(wire):
            arr = deserialize_array(wire)
            want = (src_end - src_start, batch_size, position)
            if tuple(arr.shape[:3]) != want:
                raise ValueError(
                    f"migrated KV shape {arr.shape} != (blocks, batch, position) {want}"
                )
            return arr

        k_arr = await asyncio.to_thread(parse, tensors["k"])
        v_arr = await asyncio.to_thread(parse, tensors["v"])
        k_scales = v_scales = None
        if kv_quant != "none":
            # packed wire entry: codes ride in k/v, per-row scales alongside.
            # Stored as-is (wire bytes against the budget); kv_adopt / export
            # dequantize on the way out.
            if "k_scales" not in tensors or "v_scales" not in tensors:
                raise ValueError(
                    "quantized session_migrate needs k_scales and v_scales tensors"
                )
            k_scales = await asyncio.to_thread(parse, tensors["k_scales"])
            v_scales = await asyncio.to_thread(parse, tensors["v_scales"])
        nbytes = k_arr.nbytes + v_arr.nbytes + (
            k_scales.nbytes + v_scales.nbytes if k_scales is not None else 0
        )
        self._prune_migrated()
        if self._migrated_bytes + nbytes > self.migrate_in_budget_bytes:
            tm.MIGRATIONS.labels(direction="in", outcome="refused").inc()
            get_journal().event(
                "migrate_refused", trace_id=trace_id, session_id=session_id,
                nbytes=nbytes, in_use=self._migrated_bytes,
                budget=self.migrate_in_budget_bytes,
            )
            raise RuntimeError(
                f"Migration budget exhausted ({self._migrated_bytes + nbytes} "
                f"> {self.migrate_in_budget_bytes} bytes)"
            )
        old = self._migrated.pop(session_id, None)
        if old is not None:  # re-push after a failed adopt: replace, re-account
            self._migrated_bytes -= old["nbytes"]
        self._migrated[session_id] = {
            "k": k_arr, "v": v_arr, "position": position,
            "k_scales": k_scales, "v_scales": v_scales, "kv_quant": kv_quant,
            "start": src_start, "end": src_end,
            "batch_size": batch_size, "max_length": max_length,
            "trace_id": trace_id, "nbytes": nbytes,
            "expires": time.monotonic() + self.migrate_ttl,
        }
        self._migrated_bytes += nbytes
        tm.MIGRATIONS.labels(direction="in", outcome="ok").inc()
        tm.MIGRATION_BYTES.labels(direction="in").inc(nbytes)
        get_journal().event(
            "migrate_in", trace_id=trace_id,
            occupancy=self.batcher.occupancy_info() if self.batcher is not None else None,
            session_id=session_id, position=position, nbytes=nbytes,
            start=src_start, end=src_end,
        )
        return {"ok": True, "position": position}

    async def rpc_session_handoff(self, payload, ctx: RpcContext):
        """Disaggregated prefill->decode boundary: the client (between steps,
        so the cut lands exactly on a step boundary) asks this prefill-tier
        server to push one LIVE session's finished KV to a decode-tier
        replica over the page-push path, then adopts it there with
        ``kv_adopt`` — zero KV bytes ever cross the client link. Unlike
        drain-to-migrate the session stays live here: no redirect is
        installed and nothing is torn down, so a failed push (or a failed
        adopt at the destination) degrades to colocated decode on this
        replica with no session loss."""
        session_id = payload["session_id"]
        peer_id = str(payload["peer_id"])
        addr = str(payload["addr"])
        deadline_s = min(max(float(payload.get("deadline_s") or 30.0), 0.1), 120.0)
        reg = self._session_registry.get(session_id)
        if reg is None:
            raise KeyError(f"No live session {session_id!r} to hand off")
        if reg["position"] <= 0:
            raise ValueError(f"Session {session_id!r} has no cached tokens yet")
        snap = await self._snapshot_session(reg)
        snap["trace_id"] = reg.get("trace_id")
        snap["peer"] = reg.get("peer")  # ledger attribution of the push bytes
        ok = await self.migrate_parked_to(
            session_id, snap, peer_id, addr, deadline_s=deadline_s, kind="handoff",
        )
        return {"ok": bool(ok), "position": int(snap["position"])}

    async def migrate_parked_to(
        self, session_id: str, snap: dict, peer_id: str, addr: str,
        *, deadline_s: float = 30.0, budget_bytes: Optional[int] = None,
        kind: str = "migrate",
    ) -> bool:
        """Push one session snapshot's KV to a live replica over the
        server-to-server page-push path. Two callers share the transport:

        - ``kind="migrate"`` (drain-to-migrate / rebalance): on success the
          local parked copy becomes a redirect (``_migrated_away``) so
          exports forward the client to the new home.
        - ``kind="handoff"`` (disaggregated prefill->decode boundary): the
          source session stays LIVE and no redirect is installed — the
          client adopts at the destination, and if that fails it simply
          keeps decoding here (colocated fallback, no session loss).

        Returns False — with flight-recorder evidence — when the push fails;
        the parked/live entry stays, and the client falls back to
        export/replay (migrate) or colocated decode (handoff)."""
        from petals_tpu.dht.routing import PeerAddr
        from petals_tpu.telemetry import get_journal

        assert kind in ("migrate", "handoff"), kind
        handoff = kind == "handoff"

        def note_outcome(outcome: str, nbytes: int = 0) -> None:
            if handoff:
                tm.HANDOFFS.labels(outcome=outcome).inc()
                if outcome == "ok":
                    tm.HANDOFF_BYTES.inc(nbytes)
            else:
                tm.MIGRATIONS.labels(direction="out", outcome=outcome).inc()
                if outcome == "ok":
                    tm.MIGRATION_BYTES.labels(direction="out").inc(nbytes)

        trace_id = snap.get("trace_id")
        kv_quant = getattr(self.backend, "kv_quant_type", "none")
        if kv_quant != "none":
            # pack the dense snapshot to per-row codes + scales before it hits
            # the wire: the push moves ~4x fewer bytes and the receiver banks
            # the packed entry verbatim against its migration budget
            from petals_tpu.ops.paged_attention import quantize_kv_rows_np

            def _pack():
                kc, ks = quantize_kv_rows_np(np.asarray(snap["k"], np.float32), kv_quant)
                vc, vs = quantize_kv_rows_np(np.asarray(snap["v"], np.float32), kv_quant)
                return kc, ks, vc, vs

            k_codes, k_scales, v_codes, v_scales = await asyncio.to_thread(_pack)
            nbytes = int(
                k_codes.nbytes + k_scales.nbytes + v_codes.nbytes + v_scales.nbytes
            )
        else:
            k_codes = k_scales = v_codes = v_scales = None
            nbytes = int(snap["k"].nbytes + snap["v"].nbytes)
        t0 = time.perf_counter()

        async def _push() -> None:
            if budget_bytes is not None and nbytes > budget_bytes:
                raise RuntimeError(
                    f"session KV ({nbytes}B) exceeds the migration budget ({budget_bytes}B)"
                )
            if chaos.ENABLED:
                await chaos.inject(
                    chaos.SITE_HANDOFF_PUSH if handoff else chaos.SITE_MIGRATE_PUSH,
                    detail=session_id,
                )
            if kv_quant != "none":
                # codes are integer (lossy float codecs pass them through
                # verbatim); scales go uncompressed so the packed entry
                # round-trips the wire byte-exactly
                tensors = await asyncio.to_thread(
                    lambda: {
                        "k": serialize_array(k_codes, self.compression),
                        "v": serialize_array(v_codes, self.compression),
                        "k_scales": serialize_array(k_scales, CompressionType.NONE),
                        "v_scales": serialize_array(v_scales, CompressionType.NONE),
                    }
                )
            else:
                tensors = await asyncio.to_thread(
                    lambda: {
                        "k": serialize_array(snap["k"], self.compression),
                        "v": serialize_array(snap["v"], self.compression),
                    }
                )
            payload = {
                "session_id": session_id,
                "start": snap["start"], "end": snap["end"],
                "position": snap["position"], "batch_size": snap["batch_size"],
                "max_length": snap["max_length"], "trace_id": trace_id,
                "kv_quant": kv_quant, "tensors": tensors,
            }
            client = await self._push_pool.get_addr(PeerAddr.from_string(addr))
            await client.call("ptu.session_migrate", payload)

        # Race the push against shutdown's abort signal, with the deadline
        # covering the WHOLE push (chaos delays and serialization included —
        # previously only the RPC call was deadlined, so a chaos-delayed
        # serialize phase could hang drain past the deadline).
        push_task = asyncio.create_task(_push())
        abort_task = asyncio.create_task(self._migrate_abort.wait())
        try:
            await asyncio.wait(
                {push_task, abort_task},
                timeout=deadline_s,
                return_when=asyncio.FIRST_COMPLETED,
            )
        finally:
            abort_task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await abort_task
        if not push_task.done():
            reason = "shutdown" if self._migrate_abort.is_set() else "deadline"
            push_task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await push_task
            note_outcome("aborted")
            get_journal().event(
                "handoff_aborted" if handoff else "migrate_aborted",
                trace_id=trace_id, session_id=session_id,
                dest=peer_id, nbytes=nbytes, reason=reason,
                elapsed_s=time.perf_counter() - t0,
            )
            logger.warning(
                f"{kind.capitalize()} of {session_id!r} to {peer_id} aborted ({reason})"
            )
            return False
        try:
            push_task.result()
        except Exception as e:
            note_outcome("failed")
            get_journal().event(
                "handoff_failed" if handoff else "migrate_failed",
                trace_id=trace_id, session_id=session_id,
                dest=peer_id, nbytes=nbytes, error=repr(e),
            )
            from petals_tpu.telemetry.flight import flight_from_env

            flight_from_env().record(
                "handoff_failed" if handoff else "migrate_failed",
                trace_id=trace_id,
                journal=lambda: get_journal().events(trace_id=trace_id)[-50:],
                session_id=session_id, dest_peer=peer_id, dest_addr=addr,
                nbytes=nbytes, error=repr(e),
                elapsed_s=time.perf_counter() - t0,
            )
            logger.warning(f"{kind.capitalize()} of {session_id!r} to {peer_id} failed: {e}")
            return False
        if not handoff:
            # a handoff source stays live (the client may fall back to
            # colocated decode here); only a drained migration redirects
            self._migrated_away[session_id] = {
                "peer_id": peer_id, "addr": addr, "position": snap["position"],
            }
            self._parked.pop(session_id, None)
        note_outcome("ok", nbytes)
        # the parked session's lane — and ledger session — already closed
        # (and a handoff source's live session keeps its own bill), so the
        # push bills straight to the owning peer's rollup as migration bytes
        from petals_tpu.telemetry.ledger import get_ledger

        get_ledger().note_migrated(None, nbytes, peer_id=snap.get("peer"))
        get_journal().event(
            "handoff_out" if handoff else "migrate_out",
            trace_id=trace_id,
            occupancy=self.batcher.occupancy_info() if self.batcher is not None else None,
            session_id=session_id, dest=peer_id, nbytes=nbytes,
            position=snap["position"], elapsed_s=time.perf_counter() - t0,
        )
        return True

    def _prune_migrated(self) -> None:
        now = time.monotonic()
        for sid in [s for s, m in self._migrated.items() if m["expires"] < now]:
            self._migrated_bytes -= self._migrated[sid]["nbytes"]
            del self._migrated[sid]

    def _consume_migrated(self, session_id: str) -> None:
        entry = self._migrated.pop(session_id, None)
        if entry is not None:
            self._migrated_bytes -= entry["nbytes"]

    async def _install_kv_import(
        self, step, kv, handles, position, *, batch_size: int, n_blocks: int, max_length: int
    ) -> int:
        """Seed this session's KV buffers from another server's exported cache
        (must arrive before any compute so the caches never mix histories).
        Under multi-host lockstep the prefix is broadcast once and every
        process materializes its own shards (multihost.py import_kv)."""
        if position != 0:
            raise ValueError("kv_import must be the first step of a session")
        new_position = int(step["kv_import"]["position"])
        if not 0 < new_position <= max_length:
            raise ValueError(f"kv_import position {new_position} outside (0, {max_length}]")
        tensors = step.get("tensors") or {}
        if "k" not in tensors or "v" not in tensors:
            raise ValueError("kv_import needs k and v tensors")
        k_buf, v_buf = kv
        want_shape = (n_blocks, batch_size, new_position, *k_buf.shape[3:])

        def parse(name, wire):
            arr = deserialize_array(wire)
            if tuple(arr.shape) != want_shape:
                raise ValueError(f"kv_import {name} shape {arr.shape} != {want_shape}")
            return arr

        arr_k = await asyncio.to_thread(parse, "k", tensors["k"])
        arr_v = await asyncio.to_thread(parse, "v", tensors["v"])
        if getattr(self.backend, "is_lockstep", False):
            new_k, new_v = await asyncio.to_thread(
                self.backend.import_kv, handles, arr_k, arr_v,
                new_position, batch_size, max_length, n_blocks,
            )
            self.memory_cache.update_cache(handles[0], new_k)
            self.memory_cache.update_cache(handles[1], new_v)
        else:
            # staging shared with the prefix-cache hit path
            await self._seed_session_kv(
                None, kv, handles, arr_k, arr_v, new_position,
                batch_size=batch_size, n_blocks=n_blocks,
            )
        return new_position

    @contextlib.asynccontextmanager
    async def _lane_ctx(self, lane: int, batcher):
        """Session-lifetime scope of a borrowed pool lane (yields None in the
        position of the private path's cache handles). ``batcher`` is the
        pool the lane was acquired from, captured at session open — after a
        live span move self.batcher is a NEW pool whose lane indices alias
        other tenants, so releasing (or stepping) through it would corrupt
        them."""
        try:
            yield None
        finally:
            batcher.release_lane(lane)

    async def _install_kv_import_pooled(
        self, step, lane: int, position, *, batch_size: int, n_blocks: int, max_length: int,
        batcher,
    ) -> int:
        """Seed a pooled session's lane from another server's exported cache
        (validation here; the staging is shared with the prefix-cache hit
        path in _seed_session_kv)."""
        backend = batcher.backend
        if position != 0:
            raise ValueError("kv_import must be the first step of a session")
        new_position = int(step["kv_import"]["position"])
        if not 0 < new_position <= max_length:
            raise ValueError(f"kv_import position {new_position} outside (0, {max_length}]")
        tensors = step.get("tensors") or {}
        if "k" not in tensors or "v" not in tensors:
            raise ValueError("kv_import needs k and v tensors")
        want_shape = (
            n_blocks, batch_size, new_position, backend.num_kv_heads, backend.head_dim,
        )

        def parse(name, wire):
            arr = deserialize_array(wire)
            if tuple(arr.shape) != want_shape:
                raise ValueError(f"kv_import {name} shape {arr.shape} != {want_shape}")
            return arr

        arr_k = await asyncio.to_thread(parse, "k", tensors["k"])
        arr_v = await asyncio.to_thread(parse, "v", tensors["v"])
        await self._seed_session_kv(
            lane, None, None, arr_k, arr_v, new_position,
            batch_size=batch_size, n_blocks=n_blocks, batcher=batcher,
        )
        return new_position

    async def _install_kv_adopt(
        self, step, lane, kv, handles, position, *,
        abs_start: int, batch_size: int, n_blocks: int, max_length: int, batcher,
    ) -> int:
        """Seed a fresh session's cache from KV already ON THIS SERVER — a
        migrated-in entry (peer drain/rebalance pushed it here) or our own
        parked snapshot. The client sends only ``{session_id, position}``:
        the bytes never cross the client link, which is the whole point of
        peer-to-peer migration vs export/import."""
        if position != 0:
            raise ValueError("kv_adopt must be the first step of a session")
        spec = step["kv_adopt"]
        src_sid = spec["session_id"]
        cut = int(spec["position"])
        self._prune_migrated()
        self._prune_parked()
        entry = self._migrated.get(src_sid) or self._parked.get(src_sid)
        if entry is None:
            raise KeyError(f"No migrated or parked KV for session {src_sid!r}")
        if not 0 < cut <= entry["position"]:
            raise ValueError(
                f"kv_adopt position {cut} outside (0, {entry['position']}]"
            )
        if cut > max_length:
            raise ValueError(f"kv_adopt position {cut} exceeds max_length {max_length}")
        if batch_size != entry["batch_size"]:
            raise ValueError(
                f"kv_adopt batch_size {batch_size} != source {entry['batch_size']}"
            )
        if not (entry["start"] <= abs_start and abs_start + n_blocks <= entry["end"]):
            raise ValueError(
                f"Session blocks [{abs_start}, {abs_start + n_blocks}) outside "
                f"migrated span [{entry['start']}, {entry['end']})"
            )
        b0 = abs_start - entry["start"]
        kv_quant = entry.get("kv_quant") or "none"
        if kv_quant != "none":
            # packed wire entry (row-granular codes + scales, position-
            # sliceable): dequantize the adopted cut to the dense prefix the
            # seed path expects — the pool write requantizes on insert
            from petals_tpu.ops.paged_attention import dequantize_kv_np

            k_codes = np.ascontiguousarray(entry["k"][b0:b0 + n_blocks, :, :cut])
            v_codes = np.ascontiguousarray(entry["v"][b0:b0 + n_blocks, :, :cut])
            k_sc = np.ascontiguousarray(entry["k_scales"][b0:b0 + n_blocks, :, :cut])
            v_sc = np.ascontiguousarray(entry["v_scales"][b0:b0 + n_blocks, :, :cut])
            wire_nbytes = int(
                k_codes.nbytes + v_codes.nbytes + k_sc.nbytes + v_sc.nbytes
            )
            k_arr = await asyncio.to_thread(dequantize_kv_np, k_codes, k_sc, kv_quant)
            v_arr = await asyncio.to_thread(dequantize_kv_np, v_codes, v_sc, kv_quant)
        else:
            k_arr = np.ascontiguousarray(entry["k"][b0:b0 + n_blocks, :, :cut])
            v_arr = np.ascontiguousarray(entry["v"][b0:b0 + n_blocks, :, :cut])
            wire_nbytes = int(k_arr.nbytes + v_arr.nbytes)
        await self._seed_session_kv(
            lane, kv, handles, k_arr, v_arr, cut,
            batch_size=batch_size, n_blocks=n_blocks, batcher=batcher,
        )
        # consume only after the seed landed — a failed adopt leaves the
        # entry for a retry or an export until its TTL says otherwise
        self._consume_migrated(src_sid)
        self._parked.pop(src_sid, None)
        if lane is not None and batcher is not None:
            # migrated-in KV becomes this tenant's working set: bill the
            # adopted bytes to the lane's live ledger session at WIRE size
            key = batcher._ledger_keys.get(lane)
            if key is not None:
                batcher._ledger.note_migrated(key, wire_nbytes)
        from petals_tpu.telemetry import get_journal

        get_journal().event(
            "migrate_adopt", trace_id=entry.get("trace_id"),
            occupancy=self.batcher.occupancy_info() if self.batcher is not None else None,
            session_id=src_sid, position=cut, nbytes=wire_nbytes,
        )
        return cut

    async def _seed_session_kv(
        self, lane, kv, handles, k_arr, v_arr, new_position: int,
        *, batch_size: int, n_blocks: int, batcher=None,
    ):
        """Install k/v prefix rows [0, new_position) into a FRESH session's
        cache (pooled lane or private buffers) — the prefix-cache hit path.
        Returns the updated kv pair for the private path."""
        import jax
        import jax.numpy as jnp

        if lane is not None:
            backend0 = batcher.backend
            if getattr(backend0, "is_lockstep", False):
                # multihost pooled session: broadcast the prefix and let every
                # process shard its own lane-shaped mirror (v2 import op on
                # the synthetic lane handle), then check it into the pool
                def replace_lockstep(kv_lane, lane_handles):
                    return None, backend0.import_kv(
                        lane_handles, k_arr, v_arr, new_position,
                        batch_size, batcher.max_length, n_blocks,
                    )

                # extract=False: the import REPLACES the lane wholesale, so
                # checking the old content out first would waste a full-lane
                # device copy on every process
                await batcher.run_exclusive(lane, replace_lockstep, extract=False)
                return kv
            lane_shape = (
                n_blocks, batch_size, batcher.max_length,
                backend0.num_kv_heads, backend0.head_dim,
            )
            cache_dtype = jnp.dtype(backend0.cache_dtype)

            def build(arr):
                full = np.zeros(lane_shape, cache_dtype)
                full[:, :, :new_position] = arr.astype(cache_dtype)
                return full

            new_k = await asyncio.to_thread(build, k_arr)
            new_v = await asyncio.to_thread(build, v_arr)

            def replace(kv_lane, lane_handles):
                return None, (jnp.asarray(new_k), jnp.asarray(new_v))

            # paged lanes must own pages for the seeded rows before check-in
            await batcher.run_exclusive(
                lane, replace, extract=False, write_range=(0, new_position)
            )
            return kv

        k_buf, v_buf = kv
        if getattr(self.backend, "is_lockstep", False):
            # multihost: every process shards its own mirror (v2 import op)
            new_k, new_v = await asyncio.to_thread(
                self.backend.import_kv, handles, k_arr, v_arr,
                new_position, batch_size, k_buf.shape[2], n_blocks,
            )
            self.memory_cache.update_cache(handles[0], new_k)
            self.memory_cache.update_cache(handles[1], new_v)
            return (new_k, new_v)

        def stage(arr, buf):
            full = np.zeros(buf.shape, jnp.dtype(buf.dtype))
            full[:, :, :new_position] = arr.astype(full.dtype)
            return (
                jax.device_put(full, buf.sharding)
                if getattr(buf, "sharding", None) is not None
                else jnp.asarray(full)
            )

        new_k = await asyncio.to_thread(stage, k_arr, k_buf)
        new_v = await asyncio.to_thread(stage, v_arr, v_buf)
        self.memory_cache.update_cache(handles[0], new_k)
        self.memory_cache.update_cache(handles[1], new_v)
        return (new_k, new_v)

    @staticmethod
    def _build_device_seed(parts, shape, dtype, new_position: int):
        """Fresh zeroed buffer of ``shape`` with the HBM-resident prefix
        slices concatenated into rows [0, new_position) — the single seed
        construction every device-tier path shares."""
        import jax.numpy as jnp

        pref = jnp.concatenate(parts, axis=2).astype(dtype)
        return jnp.zeros(shape, dtype).at[:, :, :new_position].set(pref)

    async def _seed_lane_kv_device(
        self, batcher, lane, kd_list, vd_list, new_position: int,
        batch_size: int, n_blocks: int,
    ):
        """Pooled-lane twin of _seed_session_kv_device: build the lane-shaped
        buffer on device from the HBM-resident prefix slices and check it in
        wholesale — the host route builds a max_length-sized zeros array and
        uploads all of it."""
        import jax.numpy as jnp

        backend0 = batcher.backend
        lane_shape = (
            n_blocks, batch_size, batcher.max_length,
            backend0.num_kv_heads, backend0.head_dim,
        )
        cache_dtype = jnp.dtype(backend0.cache_dtype)
        new_k = self._build_device_seed(kd_list, lane_shape, cache_dtype, new_position)
        new_v = self._build_device_seed(vd_list, lane_shape, cache_dtype, new_position)

        def replace(kv_lane, lane_handles):
            return None, (new_k, new_v)

        await batcher.run_exclusive(
            lane, replace, extract=False, write_range=(0, new_position)
        )

    def _seed_session_kv_device(self, kv, handles, kd_list, vd_list, new_position: int):
        """Prefix-hit seeding entirely on device: concatenate the HBM-resident
        segment slices and write them into fresh zeroed buffers. No
        host->device transfer — the host staging route uploads the whole
        max_length-shaped buffer, which on slow links costs as much as the
        skipped prefill."""
        k_buf, v_buf = kv
        new_k = self._build_device_seed(kd_list, k_buf.shape, k_buf.dtype, new_position)
        new_v = self._build_device_seed(vd_list, v_buf.shape, v_buf.dtype, new_position)
        self.memory_cache.update_cache(handles[0], new_k)
        self.memory_cache.update_cache(handles[1], new_v)
        return (new_k, new_v)

    async def _store_prefix_async(
        self, keys, n_hit: int, boundary: int, lane, handles, out_full, n_blocks: int,
        batcher=None, tenant: Optional[str] = None,
    ) -> None:
        """Snapshot KV rows [0, boundary) and store the freshly computed
        segments. Runs as a task after the prefill reply; the session loop
        awaits it before executing any LATER step of the same session, so the
        stored rows always match the content hash (content-addressed: a
        rollback later cannot poison the mapping)."""
        from petals_tpu.server.prefix_cache import SEGMENT_TOKENS

        L = n_hit * SEGMENT_TOKENS
        lane_k_dev = lane_v_dev = None
        lane_pages = None
        lane_pages_epoch = 0
        try:
            if lane is not None:
                # guard on the BATCHER's backend: the session captured its
                # batcher at open, and swap_backend can retarget self.backend
                # while this snapshot still reads the old pool
                lane_backend = batcher.backend
                if batcher.page_size is not None:
                    # page tier: pin the freshly computed segments' pages so a
                    # later hit adopts them in place of any KV re-upload; only
                    # whole stored segments pin (both bounds page-aligned
                    # because page_size divides SEGMENT_TOKENS)
                    seg_end = (boundary // SEGMENT_TOKENS) * SEGMENT_TOKENS
                    if seg_end > L:
                        lane_pages_epoch = batcher.page_epoch
                        lane_pages = batcher.pin_lane_pages(lane, L, seg_end)
                if (
                    self.prefix_cache.device_max_bytes > 0
                    and batcher.page_size is None
                    and getattr(lane_backend, "mesh", None) is None
                    and not getattr(lane_backend, "is_lockstep", False)
                ):
                    k, v, lane_k_dev, lane_v_dev = await batcher.snapshot_lane(
                        lane, boundary, 0, n_blocks, return_device=True
                    )
                else:
                    k, v = await batcher.snapshot_lane(lane, boundary, 0, n_blocks)
            elif getattr(self.backend, "is_lockstep", False):
                # multihost: per-shard all_gather (v2 export op), bounded to
                # the 128-bucketed boundary inside export_kv
                k, v = await asyncio.to_thread(
                    self.backend.export_kv, handles,
                    lambda: self.memory_cache.get_buffers(*handles),
                    0, n_blocks, boundary,
                )
            else:
                for attempt in range(20):
                    try:
                        k_buf, v_buf = self.memory_cache.get_buffers(*handles)
                        k, v = await asyncio.to_thread(
                            lambda: (
                                np.asarray(k_buf[:, :, :boundary]),
                                np.asarray(v_buf[:, :, :boundary]),
                            )
                        )
                        break
                    except Exception as e:
                        if attempt == 19:
                            logger.warning(
                                "KV snapshot read kept failing after retries "
                                "(skipping prefix store): %r", e,
                            )
                            return
                        await asyncio.sleep(0.05)
        except BaseException as e:
            # release the pins on EVERY abnormal exit, cancellation included:
            # this coroutine awaits between the pin and the cache commit, and
            # an `except Exception` here would skip the unpin when the
            # session task is cancelled mid-snapshot — the pinned pages'
            # refcounts would leak until pool reset
            if lane_pages:
                batcher.unpin_pages(lane_pages, lane_pages_epoch)
            if not isinstance(e, Exception):
                raise
            # storing is best-effort; the session must never notice
            logger.debug("Prefix store skipped: %r", e)
            return
        # device tier: single-device private sessions only — lane snapshots
        # are host-side, lockstep mirrors are per-process shards, and sliced
        # TP-sharded buffers would pin sharded HBM references of unclear
        # placement. The slices are lazy device copies of the session's
        # buffers, so they stay valid after the session's cache is freed.
        k_dev = v_dev = None
        if lane is not None:
            if lane_k_dev is not None:
                k_dev = lane_k_dev[:, :, L:]
                v_dev = lane_v_dev[:, :, L:]
        elif (
            not getattr(self.backend, "is_lockstep", False)
            and getattr(self.backend, "mesh", None) is None
            and self.prefix_cache.device_max_bytes > 0
        ):
            try:
                k_buf, v_buf = self.memory_cache.get_buffers(*handles)
                k_dev = k_buf[:, :, L:boundary]
                v_dev = v_buf[:, :, L:boundary]
            except Exception:  # swarmlint: disable=no-silent-except — device-tier pin is opportunistic: a racing free only downgrades this entry to the host tier
                k_dev = v_dev = None
        self.prefix_cache.put(
            keys, n_hit, k[:, :, L:], v[:, :, L:], out_full[:, L:boundary],
            k_dev=k_dev, v_dev=v_dev,
            pages=lane_pages, pages_pool=batcher if lane_pages else None,
            pages_epoch=lane_pages_epoch,
            tenant=tenant,  # residency bills to the storing peer (ledger)
        )

    async def _snapshot_session(
        self, reg: dict, b0: Optional[int] = None, b1: Optional[int] = None
    ) -> dict:
        """Host copy of a live session's KV (optionally just blocks [b0, b1)
        relative to the span), sliced to its position. The step loop donates
        buffers into XLA, so a fetch can race a step in flight (the grabbed
        buffer gets invalidated) — retry on the fresh buffer. The device->host
        copy is 100s of MB for long contexts, so it runs off the event loop:
        other sessions' steps must not stall behind it."""
        if reg.get("lane") is not None:
            # pooled session (lockstep included — snapshot_lane routes through
            # the temp-mirror export there): the lane copy runs on the compute
            # thread, so it serializes with batched steps — no donation race
            # to retry. MUST be checked before is_lockstep: pooled sessions
            # register handles=None, so the private export below would crash.
            n = reg["end"] - reg["start"]
            position = reg["position"]
            batcher = reg.get("batcher") or self.batcher
            # suspended lanes: read the swap entry's host copy directly —
            # snapshot_lane would swap the lane back IN just to re-export it
            pair = await batcher.snapshot_from_swap(
                reg["lane"], position, b0 if b0 is not None else 0,
                b1 if b1 is not None else n,
            )
            if pair is None:
                pair = await batcher.snapshot_lane(
                    reg["lane"], position, b0 if b0 is not None else 0,
                    b1 if b1 is not None else n,
                )
            k, v = pair
            return {
                "k": k, "v": v, "position": position,
                "start": reg["start"], "end": reg["end"],
                "batch_size": reg["batch_size"], "max_length": reg["max_length"],
            }
        if getattr(self.backend, "is_lockstep", False):
            # multi-host: every process all_gathers its shards in-program
            # (multihost.py export_kv); buffer fetch + donation retry happen
            # inside, under the broadcast lock
            n = reg["end"] - reg["start"]
            position = reg["position"]
            handles = reg["handles"]
            k, v = await asyncio.to_thread(
                self.backend.export_kv, handles,
                lambda: self.memory_cache.get_buffers(*handles),
                b0 if b0 is not None else 0,
                b1 if b1 is not None else n,
                position,
            )
            return {
                "k": k, "v": v, "position": position,
                "start": reg["start"], "end": reg["end"],
                "batch_size": reg["batch_size"], "max_length": reg["max_length"],
            }
        bs = slice(b0, b1)
        for attempt in range(20):
            position = reg["position"]
            try:
                k_buf, v_buf = self.memory_cache.get_buffers(*reg["handles"])
                k, v = await asyncio.to_thread(
                    lambda: (
                        np.asarray(k_buf[bs, :, :position]),
                        np.asarray(v_buf[bs, :, :position]),
                    )
                )
                break
            except Exception:
                if attempt == 19:
                    raise
                await asyncio.sleep(0.05)
        return {
            "k": k, "v": v, "position": position,
            "start": reg["start"], "end": reg["end"],
            "batch_size": reg["batch_size"], "max_length": reg["max_length"],
        }

    async def park_sessions(self, ttl: Optional[float] = None) -> int:
        """Snapshot every live session's KV into host RAM (drain path: streams
        are about to die with the server, but exports must keep working)."""
        import time

        ttl = self.park_ttl if ttl is None else ttl
        parked = 0
        for session_id, reg in list(self._session_registry.items()):
            if reg["position"] <= 0:
                continue
            try:
                snap = await self._snapshot_session(reg)
            except Exception as e:
                logger.warning(f"Could not park session {session_id!r}: {e}")
                continue
            snap["expires"] = time.monotonic() + ttl
            snap["trace_id"] = reg.get("trace_id")
            snap["peer"] = reg.get("peer")  # ledger attribution of a later push
            self._parked[session_id] = snap
            parked += 1
        return parked

    def _prune_parked(self) -> None:
        import time

        now = time.monotonic()
        for sid in [s for s, p in self._parked.items() if p.get("expires", 0) < now]:
            del self._parked[sid]

    def abort_migrations(self) -> None:
        """Tell in-flight migration pushes to give up immediately (shutdown
        path): the parked entries stay, clients fall back to export/replay."""
        self._migrate_abort.set()

    def shutdown(self) -> None:
        self.abort_migrations()
        self.queue.shutdown()
        with contextlib.suppress(Exception):
            loop = asyncio.get_event_loop()
            if loop.is_running():
                # strong refs: the loop holds tasks weakly, and an unreferenced
                # close could be GC'd before it finishes tearing down
                closers = [loop.create_task(self._push_pool.close())]
                if self.batcher is not None:
                    closers.append(loop.create_task(self.batcher.close()))
                self._shutdown_tasks = closers
                for t in closers:
                    t.add_done_callback(log_exception_callback(logger, "shutdown close"))

    # ------------------------------------------------------------------ helpers

    def _parse_chain(self, uids: str) -> Tuple[int, int]:
        """Validate a chain of UIDs against our span; return (start, end) relative
        to the backend's first block."""
        parts = uids.split(CHAIN_DELIMITER) if isinstance(uids, str) else list(uids)
        if not parts:
            raise ValueError("Empty uid chain")
        indices = []
        for uid in parts:
            prefix, idx = parse_uid(uid)
            if prefix != self.dht_prefix:
                raise ValueError(f"UID {uid!r} does not match served prefix {self.dht_prefix!r}")
            indices.append(idx)
        lo, hi = indices[0], indices[-1] + 1
        if indices != list(range(lo, hi)):
            raise ValueError(f"UID chain must be contiguous, got {indices}")
        first, last = self.backend.first_block, self.backend.first_block + self.backend.n_blocks
        if lo < first or hi > last:
            raise ValueError(
                f"Requested blocks [{lo}, {hi}) outside served span [{first}, {last})"
            )
        return lo - first, hi - first

    def _validate_step_tensors(self, hidden, prompts, hypo_ids, batch_size: int, n_blocks: int) -> None:
        """Reject malformed step tensors with a clean error instead of an opaque
        XLA/scan failure — and keep clients from forcing fresh compilations with
        novel batch sizes on the serving hot path."""
        hsz = self.backend.cfg.hidden_size
        if hidden is not None and (
            hidden.ndim != 3 or hidden.shape[0] != batch_size or hidden.shape[2] != hsz
        ):
            raise ValueError(
                f"step hidden must be [batch={batch_size}, seq, hidden={hsz}], "
                f"got {tuple(hidden.shape)}"
            )
        if hypo_ids is not None and tuple(hypo_ids.shape) != (batch_size,):
            raise ValueError(
                f"hypo_ids must be [{batch_size}], got {tuple(hypo_ids.shape)}"
            )
        if prompts is not None and (
            prompts.ndim != 4
            or prompts.shape[0] != n_blocks
            or prompts.shape[1] != batch_size
            or prompts.shape[3] != hsz
        ):
            raise ValueError(
                f"prompts must be [{n_blocks} blocks, batch={batch_size}, pre_seq, "
                f"hidden={hsz}], got {tuple(prompts.shape)}"
            )

    def _get_tensor(self, payload: dict, name: str) -> Optional[np.ndarray]:
        wire = (payload.get("tensors") or {}).get(name)
        if wire is None:
            return None
        arr = deserialize_array(wire)
        return None if is_dummy(arr) else arr

    def _reply_compression(self, payload: dict) -> CompressionType:
        """Per-request output compression negotiation (reference
        handler.py:411-432): the client's requested codec wins over the
        server-wide default."""
        requested = payload.get("compression")
        if requested is None:
            return self.compression
        try:
            return CompressionType(requested)
        except ValueError:
            raise ValueError(f"Unknown compression {requested!r}")

    # ------------------------------------------------------------------ rpc methods

    async def rpc_forward(self, payload, ctx: RpcContext):
        start, end = self._parse_chain(payload["uids"])
        reply_comp = self._reply_compression(payload)  # reject bad codecs up front
        hidden = self._get_tensor(payload, "hidden")
        prompts = self._get_tensor(payload, "prompts")
        if hidden is None or hidden.ndim != 3 or hidden.shape[2] != self.backend.cfg.hidden_size:
            raise ValueError(
                f"rpc_forward expects a [batch, seq, hidden={self.backend.cfg.hidden_size}] "
                f"tensor, got {None if hidden is None else tuple(hidden.shape)}"
            )
        backend = self._sub_backend(start, end)
        adapter = payload.get("active_adapter")
        def run_forward():
            with device_annotation("rpc_forward"):  # on the compute thread
                return np.asarray(backend.forward(hidden, prompts=prompts, active_adapter=adapter))

        with get_tracer().span(
            "rpc_forward", annotate=False, blocks=end - start,
            tokens=hidden.shape[0] * hidden.shape[1],
        ):
            out = await asyncio.wait_for(
                self.queue.submit(
                    run_forward,
                    priority=PRIORITY_TRAINING,
                    size=hidden.shape[0] * hidden.shape[1],
                ),
                self.request_timeout,
            )
        return {"tensors": {"hidden": serialize_array(out, reply_comp)}}

    async def rpc_backward(self, payload, ctx: RpcContext):
        start, end = self._parse_chain(payload["uids"])
        reply_comp = self._reply_compression(payload)  # reject bad codecs up front
        hidden = self._get_tensor(payload, "hidden")
        grad_out = self._get_tensor(payload, "grad_out")
        prompts = self._get_tensor(payload, "prompts")
        if hidden is None or grad_out is None:
            raise ValueError("rpc_backward expects hidden and grad_out tensors")
        if hidden.ndim != 3 or hidden.shape[2] != self.backend.cfg.hidden_size:
            raise ValueError(
                f"rpc_backward expects a [batch, seq, hidden={self.backend.cfg.hidden_size}] "
                f"tensor, got {tuple(hidden.shape)}"
            )
        if grad_out.shape != hidden.shape:
            raise ValueError(
                f"grad_out shape {tuple(grad_out.shape)} != hidden shape {tuple(hidden.shape)}"
            )
        backend = self._sub_backend(start, end)
        adapter = payload.get("active_adapter")

        def run():
            with device_annotation("rpc_backward"):
                grad_hidden, grad_prompts = backend.backward(
                    hidden, grad_out, prompts=prompts, active_adapter=adapter
                )
            return np.asarray(grad_hidden), (
                np.asarray(grad_prompts) if grad_prompts is not None else None
            )

        with get_tracer().span(
            "rpc_backward", annotate=False, blocks=end - start,
            tokens=hidden.shape[0] * hidden.shape[1],
        ):
            grad_hidden, grad_prompts = await asyncio.wait_for(
                self.queue.submit(
                    run, priority=PRIORITY_TRAINING, size=hidden.shape[0] * hidden.shape[1]
                ),
                self.request_timeout,
            )
        tensors = {"grad_hidden": serialize_array(grad_hidden, reply_comp)}
        if grad_prompts is not None:
            tensors["grad_prompts"] = serialize_array(grad_prompts, reply_comp)
        return {"tensors": tensors}

    async def rpc_info(self, payload, ctx: RpcContext):
        info = dict(self.server_info_fn()) if self.server_info_fn else {}
        info.update(
            cache_tokens_available=max(
                self.memory_cache.bytes_left // max(self.backend.cache_bytes_per_token(), 1), 0
            ),
            first_block=self.backend.first_block,
            n_blocks=self.backend.n_blocks,
            dht_prefix=self.dht_prefix,
            tracing=get_tracer().summary(),
            # compact metrics digest (tok/s, TTFT/step percentiles, swap
            # pressure) — same blob that rides ServerInfo on the DHT
            telemetry=telemetry_digest(),
            # compiled-program observatory digest (programs, compile seconds,
            # anomalies) — same blob as ServerInfo.compile_stats
            compile_stats=compile_stats_digest(),
        )
        if self.batcher is not None:
            info["continuous_batching"] = {
                "lanes": self.batcher.n_lanes,
                "max_length": self.batcher.max_length,
                "prefill_token_budget": self.batcher.prefill_token_budget,
                **self.batcher.stats,
            }
            paged = self.batcher.paged_summary()
            if paged is not None:
                info["continuous_batching"]["paged"] = paged
            # scheduler occupancy (busy lanes, free pages, suspended sessions,
            # swap bytes, preemptions): lets clients route around loaded
            # servers — the same dict rides ServerInfo.pool on the DHT
            info["pool"] = self.batcher.occupancy_info()
        if self.prefix_cache is not None:
            info["prefix_cache"] = self.prefix_cache.summary()
        return info

    async def rpc_probe(self, payload, ctx: RpcContext):
        """Integrity canary probe: run a CALLER-seeded golden input through
        this span's forward pass and return its activation fingerprint
        (ops/fingerprint.py). The caller picks the seed, so a replica
        cannot pre-compute or replay an honest digest; the canary prober
        (telemetry/integrity.py) compares digests across every replica of
        a span by quorum and quarantines outliers. The probe output runs
        through the same ``integrity.corrupt`` chaos site as session
        replies, so an injected corruption is probe-visible."""
        from petals_tpu.ops import fingerprint as fp_ops

        seed = int(payload.get("seed", fp_ops.fp_seed()))
        n_tokens = max(1, min(int(payload.get("tokens", 4)), 16))
        hsz = self.backend.cfg.hidden_size
        rng = np.random.RandomState(seed & 0x7FFFFFFF)
        # activation-scale golden input: magnitudes typical of embedding
        # outputs, so the forward pass exercises realistic numerics
        hidden = (rng.standard_normal((1, n_tokens, hsz)) * 0.02).astype(np.float32)
        backend = self.backend

        def run_probe():
            with device_annotation("rpc_probe"):
                return np.asarray(backend.forward(hidden))

        out = await asyncio.wait_for(
            self.queue.submit(run_probe, priority=PRIORITY_TRAINING, size=n_tokens),
            self.request_timeout,
        )
        if chaos.ENABLED and chaos.fire(
            chaos.SITE_INTEGRITY_CORRUPT, detail=f"{self._peer_str}:probe"
        ) == "corrupt":
            out = chaos.corrupt_array(
                out, site_seed=self._corrupt_seed, position=n_tokens
            )
        fp = fp_ops.fingerprint_output(out, hsz)
        return {
            "fp": fp_ops.fp_list(fp),
            "seed": seed,
            "tokens": n_tokens,
            "fp_seed": fp_ops.fp_seed(),
            "first_block": backend.first_block,
            "n_blocks": backend.n_blocks,
        }

    async def rpc_inference(self, requests, ctx: RpcContext):
        """Bidirectional inference stream: open -> step* (reference
        handler.py:132-195 + block_functions.iterate_rpc_inference)."""
        open_msg = await asyncio.wait_for(anext(requests), self.step_timeout)
        if self.draining:
            raise RuntimeError("Server is draining: not accepting new sessions")
        client_version = open_msg.get("client_version")
        if client_version is not None:
            from petals_tpu.utils.version import incompatibility_error, is_compatible

            if not is_compatible(client_version):
                raise ValueError(incompatibility_error(client_version, peer="client"))
        start, end = self._parse_chain(open_msg["uids"])
        max_length = int(open_msg["max_length"])
        if self.inference_max_length is not None and max_length > self.inference_max_length:
            raise ValueError(
                f"max_length {max_length} exceeds this server's inference_max_length "
                f"{self.inference_max_length}"
            )
        batch_size = int(open_msg.get("batch_size", 1))
        reply_comp = self._reply_compression(open_msg)  # for every step reply
        active_adapter = open_msg.get("active_adapter")
        session_id = open_msg.get("session_id")
        # Request-scoped trace identity: the client mints it at session open
        # and sends it in the open message; a missing or malformed id gets a
        # server-minted one so the causal timeline exists for old clients
        # too. It tags every span below, rides the scheduler slot, and keys
        # the admission/preemption journal events.
        trace_id = normalize_trace_id(open_msg.get("trace_id")) or new_trace_id()
        _trace_token = set_trace_id(trace_id)
        t_open = time.perf_counter()
        ttft_observed = False
        # where to push our outputs: {"addr": "host:port/peer", "session_id": ...}
        push_to = open_msg.get("push_to")
        backend = self._sub_backend(start, end)
        backend.params_for(active_adapter)  # validate the adapter exists up front

        # Continuous batching: single-stream full-span sessions borrow a lane
        # of the shared pool and decode coalesced with their neighbors; every
        # other shape gets the classic private cache. The batcher is captured
        # ONCE (like ``backend``): a live span move swaps self.batcher for a
        # new pool whose lane indices alias other tenants — this session must
        # keep stepping/releasing through the pool it acquired from (whose
        # close() fails it loudly into the failover path).
        lane: Optional[int] = None
        open_wait_s = 0.0  # lane-admission wait, reported in the open ack
        batcher = self.batcher
        # the peer this session bills to (fair-share admission + the resource
        # ledger). A PROVEN identity (rpc identity handshake) always wins;
        # without one, an UNAUTHENTICATED self-declared "peer_hint" from the
        # open message partitions the accounting view — a liar can only make
        # itself LOOK like several peers, exactly what an anonymous transport
        # already allows — and absent both, the session bills anonymously.
        peer = getattr(ctx, "remote_peer_id", None)
        if peer is not None:
            peer_str: Optional[str] = peer.to_string()
        else:
            hint = open_msg.get("peer_hint")
            peer_str = str(hint)[:64] if hint else None
        if (
            batcher is not None
            and batch_size == 1
            and active_adapter is None
            and start == 0
            and end == self.backend.n_blocks
            and max_length <= batcher.max_length
        ):
            from petals_tpu.data_structures import parse_session_priority
            from petals_tpu.server.memory_cache import AllocationFailed

            alloc_timeout = open_msg.get("alloc_timeout")
            # optional client priority hint ("high"/"normal"/"low" or an int
            # class); absent -> normal, i.e. exactly the pre-hint behavior.
            # The peer id feeds per-peer fair-share admission and the ledger.
            priority = parse_session_priority(open_msg.get("priority"))
            t_open_wait = time.perf_counter()
            try:
                lane = await batcher.acquire_lane(
                    timeout=30.0 if alloc_timeout is None else alloc_timeout,
                    priority=priority,
                    peer_id=peer_str,
                    trace_id=trace_id,
                )
            except AllocationFailed as e:
                logger.debug(f"No decode lane ({e}); serving with a private cache")
            # reported to the client in the open ack: for short sessions
            # (a handful of steps) this admission wait is the ONLY queue
            # signal they ever see, and without it a backlogged server
            # looks identical to an idle one at route-build time
            open_wait_s = time.perf_counter() - t_open_wait

        push_queue: Optional[asyncio.Queue] = None
        if lane is not None:
            cache_ctx = self._lane_ctx(lane, batcher)
        else:
            descriptors = backend.cache_descriptors(batch_size, max_length, 0, end - start)
            cache_ctx = self.memory_cache.allocate_cache(
                *descriptors, timeout=open_msg.get("alloc_timeout")
            )
        async with cache_ctx as handles:
            if lane is None:
                k_buf, v_buf = self.memory_cache.get_buffers(*handles)
                kv = (k_buf, v_buf)
            else:
                kv = None  # lives in the batcher's pool, keyed by lane
            position = 0
            reg = None
            if session_id:
                # registered only once allocation succeeded (no leak on failure)
                push_queue = asyncio.Queue(maxsize=64)
                self._push_queues[session_id] = push_queue
                reg = {
                    "handles": handles, "lane": lane, "batcher": batcher, "position": 0,
                    "start": self.backend.first_block + start,
                    "end": self.backend.first_block + end,
                    "batch_size": batch_size, "max_length": max_length,
                    "trace_id": trace_id,  # rides into parked/migrated snapshots
                    "peer": peer_str,  # ledger attribution for migrate-out pushes
                }
                self._session_registry[session_id] = reg
            # echo the trace id so the client learns a server-minted one
            yield {
                "session_open": True, "position": 0, "max_length": max_length,
                "trace_id": trace_id,
                "open_wait_s": round(open_wait_s, 6),
            }

            next_step, cleanup_steps = self._step_source(
                requests, push_queue, self.session_timeout
            )
            seen_steps = set()  # dedup: the same step may arrive via client AND push
            pending_store = None  # in-flight prefix-cache store task
            try:
              while True:
                step = await next_step()
                # serving clock for this step's step_meta: receipt -> reply
                # ready (everything the client's wall covers except network)
                t_step_recv = time.perf_counter()
                # a later step may mutate the rows being stored (rollback,
                # overwrite): finish the store first so content stays honest
                if pending_store is not None:
                    if not pending_store.done():
                        with contextlib.suppress(Exception):
                            await pending_store
                    pending_store = None
                if step is None:
                    break
                if chaos.ENABLED:
                    # mid-step fault: a raise here kills the stream exactly at
                    # the step boundary, the worst point for a session's KV
                    await chaos.inject(chaos.SITE_HANDLER_STEP, detail=session_id)
                if self.draining:
                    # fail fast so the client repairs its chain NOW, while the
                    # parked KV export is still being served (drain window)
                    raise RuntimeError(
                        "Server is draining: migrate this session via ptu.session_export"
                    )
                if "push_to" in step:  # chain repair moved our downstream peer
                    push_to = step["push_to"] or None
                step_id = step.get("step_id")
                if step_id is not None:
                    if step_id in seen_steps:
                        continue
                    seen_steps.add(step_id)

                start_from = step.get("start_from_position")
                if start_from is not None:
                    if start_from > position:
                        raise ValueError(
                            f"start_from_position {start_from} is ahead of cache ({position})"
                        )
                    position = int(start_from)  # rollback (speculative decoding)
                    if reg is not None:
                        reg["position"] = position

                if "kv_adopt" in step:
                    # seed from KV already on this server (migrated or parked)
                    position = await self._install_kv_adopt(
                        step, lane, kv, handles, position,
                        abs_start=self.backend.first_block + start,
                        batch_size=batch_size, n_blocks=end - start,
                        max_length=max_length, batcher=batcher,
                    )
                    if lane is None:
                        kv = tuple(self.memory_cache.get_buffers(*handles))
                    if reg is not None:
                        reg["position"] = position
                    yield {"position": position, "kv_adopt": True}
                    continue

                if "kv_import" in step:
                    if lane is not None:
                        position = await self._install_kv_import_pooled(
                            step, lane, position,
                            batch_size=batch_size, n_blocks=end - start,
                            max_length=max_length, batcher=batcher,
                        )
                    else:
                        position = await self._install_kv_import(
                            step, kv, handles, position,
                            batch_size=batch_size, n_blocks=end - start, max_length=max_length,
                        )
                        kv = tuple(self.memory_cache.get_buffers(*handles))
                    if reg is not None:
                        reg["position"] = position
                    yield {"position": position, "kv_import": True}
                    continue

                hidden = self._get_tensor(step, "hidden")
                prompts = self._get_tensor(step, "prompts")
                hypo_ids = self._get_tensor(step, "hypo_ids")
                self._validate_step_tensors(hidden, prompts, hypo_ids, batch_size, end - start)
                seq = 0 if hidden is None else hidden.shape[1]
                if hidden is not None and position + seq > max_length:
                    raise ValueError(
                        f"Step of {seq} tokens at position {position} exceeds max_length {max_length}"
                    )

                if hidden is None or seq == 0:
                    # cache probe step (reference block_functions.py:209-211)
                    yield {"tensors": {}, "position": position}
                    continue

                pos = position

                # content-addressed prefix cache: a fresh session's prefill
                # probes for its longest cached prefix, seeds KV from host
                # RAM, and computes only the tail (server/prefix_cache.py)
                exec_hidden, prefix_out, pc_keys, pc_hits = hidden, None, None, 0
                if (
                    self.prefix_cache is not None
                    and position == 0
                    and batch_size == 1
                    and prompts is None and hypo_ids is None
                    and active_adapter is None
                    # "peer" scope isolates clients BY their authenticated
                    # identity: an unauthenticated connection has none, and
                    # salting with a shared 'None' would silently merge every
                    # such client back into one timing-observable pool — the
                    # exact channel the mode exists to close. No identity, no
                    # caching.
                    and (
                        self.prefix_share_scope == "swarm"
                        or getattr(ctx, "remote_peer_id", None) is not None
                    )
                ):
                    from petals_tpu.server.prefix_cache import SEGMENT_TOKENS, segment_keys

                    if seq >= SEGMENT_TOKENS:
                        salt = (
                            f"{self.dht_prefix}:{self.backend.first_block + start}:"
                            f"{self.backend.first_block + end}"
                        )
                        if self.prefix_share_scope == "peer":
                            # full id, not repr (repr truncates to 12 hex
                            # chars — 48 bits an attacker could grind a
                            # colliding keypair for); non-None: gated above
                            salt += f":{ctx.remote_peer_id.to_string()}"
                        # hashing is multi-MB work: off the event loop, like
                        # every other bulk host op in this file
                        pc_keys = await asyncio.to_thread(segment_keys, hidden, salt)
                        # probe + entry resolution stay synchronous on the
                        # loop: no await separates them, so a concurrent
                        # put()'s LRU eviction cannot invalidate a probed key
                        # before its entry reference is held (the heavy
                        # concatenation then runs off-loop on the references)
                        pc_hits = self.prefix_cache.probe(pc_keys)
                        if pc_hits:
                            hit_len = pc_hits * SEGMENT_TOKENS
                            pc_entries = self.prefix_cache.get_entries(pc_keys, pc_hits)
                            # device-tier refs resolve HERE, on the loop, for
                            # the same reason the entries do: a concurrent
                            # eviction pops dict fields, and a held array
                            # reference survives that where a later lookup
                            # would not
                            kd_list = [e.get("kd") for e in pc_entries]
                            vd_list = [e.get("vd") for e in pc_entries]
                            seed_backend = (
                                batcher.backend if lane is not None else self.backend
                            )
                            # page tier first: a pooled lane whose WHOLE hit
                            # prefix is still page-resident in THIS batcher's
                            # pool (same epoch — pins die on reset) adopts the
                            # pages by table reference: zero bytes copied,
                            # copy-on-write protects the shared rows
                            paged_adopted = False
                            if lane is not None and batcher.page_size is not None:
                                spp = SEGMENT_TOKENS // batcher.page_size
                                if all(
                                    e.get("pages") is not None
                                    and e.get("pages_pool") is batcher
                                    and e.get("pages_epoch") == batcher.page_epoch
                                    and len(e["pages"]) == spp
                                    for e in pc_entries
                                ):
                                    # swarmlint: disable=paired-refcount — ownership transfer: adopted refs belong to the lane's table row; release_lane / copy-on-write decref them
                                    batcher.adopt_pages(
                                        lane,
                                        [p for e in pc_entries for p in e["pages"]],
                                    )
                                    self.prefix_cache.stats["page_hits"] = (
                                        self.prefix_cache.stats.get("page_hits", 0) + 1
                                    )
                                    prefix_out = await asyncio.to_thread(
                                        lambda: np.concatenate(
                                            [e["out"] for e in pc_entries], axis=1
                                        )
                                    )
                                    paged_adopted = True
                            use_device = (
                                not paged_adopted
                                and not getattr(seed_backend, "is_lockstep", False)
                                # mesh guard mirrors the store path: after a
                                # swap_backend onto a TP mesh, surviving
                                # device entries must not seed unsharded
                                # buffers into a sharded session
                                and getattr(seed_backend, "mesh", None) is None
                                and all(x is not None for x in kd_list)
                            )
                            if paged_adopted:
                                pass  # the block table IS the seed
                            elif use_device:
                                # whole prefix HBM-resident: zero host->device
                                # traffic; only `out` rides from host RAM
                                self.prefix_cache.stats["device_hits"] = (
                                    self.prefix_cache.stats.get("device_hits", 0) + 1
                                )
                                prefix_out = await asyncio.to_thread(
                                    lambda: np.concatenate(
                                        [e["out"] for e in pc_entries], axis=1
                                    )
                                )
                                if lane is not None:
                                    await self._seed_lane_kv_device(
                                        batcher, lane, kd_list, vd_list, hit_len,
                                        batch_size, end - start,
                                    )
                                else:
                                    kv = self._seed_session_kv_device(
                                        kv, handles, kd_list, vd_list, hit_len
                                    )
                            else:
                                k_pre, v_pre, prefix_out = await asyncio.to_thread(
                                    self.prefix_cache.concat_entries, pc_entries
                                )
                                kv = await self._seed_session_kv(
                                    lane, kv, handles, k_pre, v_pre, hit_len,
                                    batch_size=batch_size, n_blocks=end - start,
                                    batcher=batcher,
                                )
                                # a host-staged hit is the radix promotion
                                # signal: hot path nodes move up to the HBM
                                # tier OFF the reply path (multi-MB uploads),
                                # so the NEXT session with this prefix seeds
                                # device-resident
                                if (
                                    not getattr(seed_backend, "is_lockstep", False)
                                    and getattr(seed_backend, "mesh", None) is None
                                    and self.prefix_cache.device_max_bytes > 0
                                ):
                                    promo = asyncio.create_task(
                                        asyncio.to_thread(
                                            self.prefix_cache.maybe_promote_device,
                                            pc_keys, pc_hits,
                                        )
                                    )
                                    promo.add_done_callback(
                                        log_exception_callback(
                                            logger, "prefix device promotion"
                                        )
                                    )
                            exec_hidden = hidden[:, hit_len:]
                            pos = hit_len

                # queue/compute attribution for the step_meta piggyback: the
                # pooled paths get the batcher's per-lane split; the rest
                # fall back to the execution-block wall (queue folded in)
                t_exec = time.perf_counter()
                step_timing = None
                step_fp = None  # fused activation fingerprint (integrity)
                step_variant = "cached"
                with get_tracer().span(
                    "inference_step", annotate=False, trace_id=trace_id,
                    blocks=end - start, batch=batch_size, seq=seq,
                ):
                    if exec_hidden.shape[1] == 0:
                        # the whole prefill was cached: no device work at all
                        out = prefix_out
                        prefix_out = None
                    elif lane is not None and seq == 1 and prompts is None and hypo_ids is None:
                        # the continuous-batching hot path: one token, coalesced
                        # with whatever other sessions are stepping right now
                        t_tok = time.perf_counter()
                        out = await asyncio.wait_for(
                            batcher.step(lane, hidden, pos), self.step_timeout
                        )
                        tm.TOKEN_LATENCY.observe(time.perf_counter() - t_tok)
                        step_variant = "decode"
                        step_timing = batcher.pop_step_timing(lane)
                        step_fp = batcher.pop_step_fp(lane)
                    elif (
                        lane is not None and prompts is None and hypo_ids is None
                        and batcher.page_size is not None
                    ):
                        # paged-lane prefill: admitted into the MIXED step —
                        # each tick advances every decoding lane AND one
                        # bucketed chunk of this prefill in ONE jitted
                        # program over the page pool (no lane extract/insert,
                        # no stop-the-world chunks)
                        out = await asyncio.wait_for(
                            batcher.prefill_lane(lane, exec_hidden, pos),
                            self.step_timeout,
                        )
                        step_variant = "prefill"
                        step_timing = batcher.pop_step_timing(lane)
                        step_fp = batcher.pop_step_fp(lane)
                    elif lane is not None and prompts is None and hypo_ids is None:
                        # pooled long prefill on the DENSE pool (and the
                        # TP/lockstep spans, which gate paged mode off): each
                        # chunk is its OWN queue task, so other sessions'
                        # batched decode steps interleave between chunks
                        # instead of stalling for the whole prefill
                        # (Sarathi-style)
                        step_variant = "dense_prefill"
                        chunk_fns = []
                        off = 0
                        # the full prompt length is known here: every chunk
                        # declares it so LongRoPE (phi3) selects short/long
                        # factors from the FINAL sequence length instead of
                        # flipping factors between chunks (HF parity)
                        prefill_n_total = pos + exec_hidden.shape[1]
                        for clen in backend.chunk_plan(
                            batch_size, exec_hidden.shape[1], kv_buf_len=batcher.max_length
                        ):
                            chunk = exec_hidden[:, off : off + clen]
                            chunk_pos = pos + off

                            def run_chunk(kv_lane, lane_handles, chunk=chunk, chunk_pos=chunk_pos):
                                with device_annotation("inference_step"):
                                    out, new_kv = backend.inference_step(
                                        chunk, kv_lane, chunk_pos,
                                        active_adapter=active_adapter,
                                        handles=lane_handles,
                                        n_total=prefill_n_total,
                                    )
                                return np.asarray(out), new_kv

                            chunk_fns.append(run_chunk)
                            off += clen
                        outs = await asyncio.wait_for(
                            batcher.run_exclusive_chunks(
                                lane, chunk_fns, size=batch_size * exec_hidden.shape[1],
                                write_range=(pos, pos + exec_hidden.shape[1]),
                            ),
                            self.step_timeout,
                        )
                        out = outs[0] if len(outs) == 1 else np.concatenate(outs, axis=1)
                    elif lane is not None:
                        # pooled session with deep prompts or explicit
                        # hypo_ids: one atomic exclusive pass on the lane
                        step_variant = "exclusive"

                        def run_lane(kv_lane, lane_handles, hidden=hidden, prompts=prompts, hypo_ids=hypo_ids):
                            with device_annotation("inference_step"):
                                out, new_kv = backend.inference_step(
                                    hidden, kv_lane, pos, prompts=prompts,
                                    hypo_ids=hypo_ids, active_adapter=active_adapter,
                                    handles=lane_handles,
                                )
                            return np.asarray(out), new_kv

                        out = await asyncio.wait_for(
                            batcher.run_exclusive(
                                lane, run_lane, size=batch_size * seq,
                                write_range=(pos, pos + seq),
                            ),
                            self.step_timeout,
                        )
                    else:
                        step_variant = "private"

                        def run_step(exec_hidden=exec_hidden, kv=kv):
                            with device_annotation("inference_step"):
                                out, new_kv = backend.inference_step(
                                    exec_hidden, kv, pos, prompts=prompts, hypo_ids=hypo_ids,
                                    active_adapter=active_adapter, handles=handles,
                                )
                            return np.asarray(out), new_kv

                        out, kv = await asyncio.wait_for(
                            self.queue.submit(
                                run_step, priority=PRIORITY_INFERENCE,
                                size=batch_size * exec_hidden.shape[1],
                            ),
                            self.step_timeout,
                        )
                        # keep the allocator's view coherent (old buffers donated)
                        self.memory_cache.update_cache(handles[0], kv[0])
                        self.memory_cache.update_cache(handles[1], kv[1])
                fallback_compute_s = time.perf_counter() - t_exec
                if prefix_out is not None:
                    # cached prefix outputs + the freshly computed tail
                    out = await asyncio.to_thread(
                        lambda out=out: np.concatenate(
                            [prefix_out.astype(out.dtype), out], axis=1
                        )
                    )
                if pc_keys is not None and len(pc_keys) > pc_hits:
                    from petals_tpu.server.prefix_cache import SEGMENT_TOKENS

                    # skip the device->host snapshot entirely when nothing
                    # would be stored (all keys already present — e.g. a
                    # racing session won — or one segment exceeds the budget)
                    import jax.numpy as jnp

                    backend0 = self.backend
                    seg_bytes = (
                        2 * (end - start) * SEGMENT_TOKENS
                        * backend0.num_kv_heads * backend0.head_dim
                        * jnp.dtype(backend0.cache_dtype).itemsize
                        # the stored "out" segment is np.asarray(out) — its
                        # ACTUAL host dtype, not compute_dtype: on bf16
                        # servers the wire/concat path yields float32, and
                        # estimating with bf16's itemsize undercounts 2x
                        # (approving snapshots put() then has to discard)
                        + SEGMENT_TOKENS * backend0.hidden_size
                        * np.asarray(out).dtype.itemsize
                    )
                    # mirrors the store path's tier eligibility: a re-store
                    # of fully-known keys is still worth it when it would
                    # grant HBM residency (device refs for a host-only hot
                    # entry, or fresh page pins after a pool reset)
                    store_backend = batcher.backend if lane is not None else self.backend
                    device_capable = (
                        self.prefix_cache.device_max_bytes > 0
                        and getattr(store_backend, "mesh", None) is None
                        and not getattr(store_backend, "is_lockstep", False)
                        and (lane is None or batcher.page_size is None)
                    )
                    store_pages_pool = (
                        batcher
                        if lane is not None and batcher.page_size is not None
                        else None
                    )
                    if self.prefix_cache.worth_storing(
                        pc_keys, pc_hits, seg_bytes,
                        device_capable=device_capable,
                        pages_pool=store_pages_pool,
                    ):
                        # store off the reply path; the loop awaits this
                        # before any LATER step of this session
                        pending_store = asyncio.create_task(
                            self._store_prefix_async(
                                pc_keys, pc_hits, len(pc_keys) * SEGMENT_TOKENS,
                                lane, handles, np.asarray(out), end - start,
                                batcher=batcher, tenant=peer_str,
                            )
                        )
                        pending_store.add_done_callback(
                            log_exception_callback(logger, "prefix store")
                        )
                position += seq
                gen_token_list = None
                gen_n = step.get("gen_tokens")
                if gen_n:
                    # clamp to a power of two <= 32: each distinct length is
                    # its own compiled program, and arbitrary client-chosen
                    # lengths would be a compile-cache DoS; clients loop on
                    # the returned count
                    gen_n = max(1, min(int(gen_n), 32))
                    gen_n = 1 << (gen_n.bit_length() - 1)
                    # on-device sampling params (None -> greedy); malformed
                    # dicts become protocol errors before touching the device
                    gen_sampling = validate_gen_sampling(step.get("gen_sampling"))
                    # device-side generation loop (backend.generate_tokens /
                    # batching.generate_lane): single-HOST sessions (plain or
                    # TP/SP mesh — GSPMD partitions the whole scan) on a
                    # full-span server holding the client leaves; clients
                    # gate on the server_gen / server_gen_sampling info
                    # flags, so a violation here is a protocol error, not a
                    # fallback path
                    if not (
                        self.server_gen_params is not None
                        # the SESSION must cover the whole model: a sub-span
                        # session would apply the LM head to mid-stack hidden
                        # states and feed embeddings into the middle of the
                        # stack — syntactically valid, semantically garbage
                        and start == 0
                        and end == self.backend.n_blocks
                        and not getattr(backend, "is_lockstep", False)
                        and batch_size == 1
                        and prompts is None
                        and hypo_ids is None
                    ):
                        raise ValueError(
                            "server-side generation is not available for this "
                            "session (requires a whole-model session on a "
                            "full-span single-host server with client "
                            "leaves loaded; check the server_gen info flag)"
                        )
                    # the SESSION's negotiated budget caps generation just
                    # like a regular step: the lane/cache buffer may be
                    # larger than what this session negotiated at open
                    if position + gen_n - 1 > max_length:
                        raise ValueError(
                            f"Generating {gen_n} tokens at position {position} "
                            f"exceeds max_length {max_length}"
                        )

                    gen_timing = None
                    if lane is not None:
                        # pooled session: the gen loop runs INSIDE the flush
                        # loop — each of the <=32 decode steps batches this
                        # lane with every other generating lane and ordinary
                        # decode traffic into one compiled program (no more
                        # exclusive-checkout monopoly)
                        gen_arr = await asyncio.wait_for(
                            batcher.generate_lane(
                                # slice BEFORE np.asarray: out may be a
                                # device array holding the whole prefill
                                lane, np.asarray(out[:, -1:]), position,
                                gen_n, sampling=gen_sampling,
                            ),
                            self.step_timeout,
                        )
                        gen_timing = batcher.pop_step_timing(lane)
                        # token replies carry no hidden state for the client
                        # to re-digest: drop the gen loop's stale fingerprint
                        # so it cannot ride a LATER step's meta
                        batcher.pop_step_fp(lane)
                        step_fp = None
                    else:
                        def run_gen(kv=kv, out=out, gen_n=gen_n,
                                    gen_sampling=gen_sampling):
                            with device_annotation("server_gen"):
                                tokens, new_kv = backend.generate_tokens(
                                    self.server_gen_params, np.asarray(out[:, -1:]),
                                    kv, position, gen_n,
                                    active_adapter=active_adapter,
                                    sampling=gen_sampling,
                                )
                            return np.asarray(tokens), new_kv

                        t_gen = time.perf_counter()
                        gen_arr, kv = await asyncio.wait_for(
                            self.queue.submit(
                                run_gen, priority=PRIORITY_INFERENCE, size=gen_n
                            ),
                            self.step_timeout,
                        )
                        fallback_compute_s += time.perf_counter() - t_gen
                        self.memory_cache.update_cache(handles[0], kv[0])
                        self.memory_cache.update_cache(handles[1], kv[1])
                    if gen_timing is not None:
                        # a content op preceded the gen loop on this lane:
                        # the two device phases sum into one step attribution
                        if step_timing is None:
                            step_timing = gen_timing
                        else:
                            merged = {
                                "queue_s": step_timing["queue_s"] + gen_timing["queue_s"],
                                "compute_s": step_timing["compute_s"] + gen_timing["compute_s"],
                                "variant": step_timing["variant"] + "+gen",
                            }
                            # speculative evidence survives the merge
                            for k in ("spec_proposed", "spec_accepted", "acceptance_rate"):
                                if k in gen_timing:
                                    merged[k] = gen_timing[k]
                            step_timing = merged
                    position += gen_n - 1  # the last token is never fed
                    gen_token_list = [int(t) for t in gen_arr[0]]
                if reg is not None:
                    reg["position"] = position
                if not ttft_observed:
                    # first content-bearing reply of the session: open ->
                    # first token out, queue wait and prefill included
                    ttft_observed = True
                    tm.TTFT.observe(time.perf_counter() - t_open)
                # per-hop span piggyback: a compact attribution dict rides
                # every content reply, keyed by the session's trace id on the
                # client side (telemetry/spans.py). Dict-protocol replies, so
                # old clients simply ignore the unknown key.
                if step_timing is not None:
                    meta_q = step_timing["queue_s"]
                    meta_c = step_timing["compute_s"]
                    step_variant = step_timing.get("variant", step_variant)
                else:
                    meta_q, meta_c = 0.0, fallback_compute_s
                step_meta = {
                    "queue_s": round(meta_q, 6),
                    "compute_s": round(meta_c, 6),
                    "variant": step_variant,
                }
                if step_timing is not None:
                    # speculative-decoding evidence for streams that ever
                    # speculated: lifetime draft counts + acceptance rate
                    for k in ("spec_proposed", "spec_accepted", "acceptance_rate"):
                        if k in step_timing:
                            step_meta[k] = step_timing[k]
                if step_fp is not None:
                    # fused activation fingerprint of the reply's last token
                    # row (ops/fingerprint.py): the client re-derives it from
                    # the hidden state it receives and cross-checks — unknown
                    # key, so old clients ignore it
                    step_meta["fp"] = step_fp
                if lane is not None:
                    step_meta.update(batcher.occupancy_hint())
                    # the tenant's own bill since the last reply (resource
                    # ledger delta: page-seconds, compute split, tokens, swap
                    # bytes) — InferenceSession.usage_report() sums these
                    usage = batcher.pop_usage_delta(lane)
                    if usage:
                        step_meta["usage"] = usage
                if gen_token_list is not None:
                    # the client computes everything it needs from the token
                    # ids; skipping the hidden reply saves the prefill-sized
                    # upload on the wire
                    step_meta["serialize_s"] = 0.0
                    step_meta["total_s"] = round(time.perf_counter() - t_step_recv, 6)
                    yield {
                        "tokens": gen_token_list, "position": position,
                        "step_meta": step_meta,
                    }
                    continue
                if chaos.ENABLED and chaos.fire(
                    chaos.SITE_INTEGRITY_CORRUPT,
                    detail=f"{self._peer_str}:{session_id or 'anon'}",
                ) == "corrupt":
                    # seeded activation corruption AT the reply boundary: the
                    # wire output now diverges from the fused fingerprint in
                    # its own step_meta — the exact plausible-but-wrong
                    # failure the client cross-check exists to catch
                    out = chaos.corrupt_array(
                        out, site_seed=self._corrupt_seed, position=position
                    )
                t_ser = time.perf_counter()
                wire_out = serialize_array(out, reply_comp)
                ser_s = time.perf_counter() - t_ser
                tm.REPLY_SERIALIZE.observe(ser_s)
                step_meta["serialize_s"] = round(ser_s, 6)
                if push_to is not None and prompts is None:
                    # can_push = no deep prompts (reference block_functions.py:233).
                    # Fire-and-forget: the client's relay of this output remains
                    # authoritative (dedup by step_id), so a slow/dead next peer
                    # must never delay our own reply.
                    wire_hypo = (step.get("tensors") or {}).get("hypo_ids")
                    task = asyncio.create_task(
                        self._push_outputs(push_to, wire_out, step_id, start_from, wire_hypo)
                    )
                    self._push_tasks.add(task)
                    task.add_done_callback(self._push_tasks.discard)
                    task.add_done_callback(
                        log_exception_callback(logger, "output push")
                    )
                step_meta["total_s"] = round(time.perf_counter() - t_step_recv, 6)
                yield {
                    "tensors": {"hidden": wire_out}, "position": position,
                    "step_meta": step_meta,
                }
            finally:
                if pending_store is not None and not pending_store.done():
                    import sys as _sys

                    if _sys.exc_info()[1] is not None:
                        # error/cancellation teardown: drop the store NOW —
                        # holding the lane 30s on an abrupt disconnect would
                        # stall new-session admission
                        pending_store.cancel()
                    else:
                        # graceful stream end: finish the store BEFORE the
                        # lane/buffers are released (a session that ends right
                        # after its prefill — every hop of a chain does — must
                        # still populate the cache); bounded, and a
                        # re-tenanted lane is never snapshotted
                        try:
                            await asyncio.wait_for(asyncio.shield(pending_store), 30.0)
                        except asyncio.CancelledError:
                            pending_store.cancel()
                            raise
                        except Exception as e:
                            # incl. TimeoutError and store-internal failures:
                            # storing is best-effort — an otherwise-successful
                            # stream must not error over a cache hiccup
                            logger.debug("Prefix store abandoned at stream end: %r", e)
                            pending_store.cancel()
                        except BaseException:
                            # GeneratorExit (transport aclose), KeyboardInterrupt:
                            # never leak the store task holding the lane
                            pending_store.cancel()
                            raise
                await cleanup_steps()
                if session_id:
                    self._push_queues.pop(session_id, None)
                    self._session_registry.pop(session_id, None)
                # drop the ambient trace id (reset_trace_id tolerates the
                # generator resuming under a different Context at teardown)
                reset_trace_id(_trace_token)

    @staticmethod
    def _step_source(requests, push_queue, timeout):
        """Callable yielding the next step from either the client stream or the
        push queue. Pending getters persist across calls (no per-step task
        churn, no cancelled-task noise at teardown). Pulls straight from the
        request iterator — no intermediate buffer, so the transport's bounded
        inbound queue is the *only* buffer and its backpressure actually
        engages for flooding peers."""
        pending: Dict[str, asyncio.Task] = {}

        async def _next_client():
            try:
                return await anext(requests)
            except StopAsyncIteration:
                return None  # client half-closed
            except Exception as e:
                logger.debug("Client stream error (treating as half-close): %r", e)
                return None

        async def next_step():
            if "client" not in pending:
                pending["client"] = asyncio.create_task(_next_client())
            if push_queue is not None and "push" not in pending:
                pending["push"] = asyncio.create_task(push_queue.get())
            done, _ = await asyncio.wait(
                set(pending.values()), timeout=timeout, return_when=asyncio.FIRST_COMPLETED
            )
            if not done:
                await cleanup()
                raise asyncio.TimeoutError("No inference step within session_timeout")
            task = done.pop()
            for name, t in list(pending.items()):
                if t is task:
                    del pending[name]
            return task.result()

        async def cleanup():
            for task in pending.values():
                task.cancel()
                with contextlib.suppress(asyncio.CancelledError, Exception):
                    await task
            pending.clear()

        return next_step, cleanup

    async def _push_outputs(self, push_to: dict, wire_out, step_id, start_from, wire_hypo=None) -> None:
        """Forward our outputs straight to the next server in the chain
        (reference handler.py:320-350); push failures are non-fatal — the
        client's copy is authoritative. A rollback marker on the original step
        propagates so speculative rewinds stay coherent whichever copy wins."""
        try:
            from petals_tpu.dht.routing import PeerAddr

            payload = {
                "session_id": push_to["session_id"],
                "step_id": step_id,
                "tensors": {"hidden": wire_out},
            }
            if wire_hypo is not None:  # beam reorder must survive the push path
                payload["tensors"]["hypo_ids"] = wire_hypo
            if start_from is not None:
                payload["start_from_position"] = int(start_from)
            addr = PeerAddr.from_string(push_to["addr"])
            client = await self._push_pool.get_addr(addr)
            await asyncio.wait_for(client.call("ptu.push", payload), 10.0)
        except Exception as e:
            logger.debug(f"Push to next server failed (client copy still flows): {e}")

    def _sub_backend(self, start: int, end: int) -> TransformerBackend:
        if start == 0 and end == self.backend.n_blocks:
            return self.backend
        # Partial chains get their own backend over a sliced param stack —
        # cached so each (start, end) compiles its programs exactly once.
        key = (start, end)
        if key not in self._sub_backends:
            sliced = self.backend._slice_params(start, end)
            sub = TransformerBackend(
                self.backend.family,
                self.backend.cfg,
                sliced,
                first_block=self.backend.first_block + start,
                n_blocks=end - start,
                memory_cache=self.memory_cache,
                compute_dtype=self.backend.compute_dtype,
                cache_dtype=self.backend.cache_dtype,
                max_chunk_size_bytes=self.backend.max_chunk_size_bytes,
                use_flash=self.backend.use_flash,
                mesh=self.backend.mesh,
            )
            import jax

            sub.adapters = {
                name: (jax.tree_util.tree_map(lambda x: x[start:end], stacked), scaling)
                for name, (stacked, scaling) in self.backend.adapters.items()
            }
            if getattr(self.backend, "is_lockstep", False):
                # multi-host serving: the sliced chain must broadcast its span
                # so workers execute the same sub-backend in lockstep
                sub = self.backend.sub_view(sub, start, end)
            self._sub_backends[key] = sub
        return self._sub_backends[key]

