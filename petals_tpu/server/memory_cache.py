"""Server-wide KV-cache budget allocator, HBM edition
(counterpart of reference src/petals/server/memory_cache.py:26-225).

The reference spreads this across processes (shared-memory counters, mp.Pipe
handler->runtime protocol) because torch servers fork one process per
connection handler. A JAX/TPU server is one process that owns the device, so
the same contract collapses to asyncio:

- ``allocate_cache(*descriptors, timeout=...)`` — async context manager that
  reserves budget and yields integer handles; oversubscribed requests QUEUE
  (FIFO) until space frees or the timeout elapses (AllocationFailed).
- ``get_buffers(*handles)`` — compute-side access to the device buffers;
  buffers are created lazily (zeros in HBM) on first use and replaced
  functionally after each step via ``update_cache`` (XLA donation makes this
  in-place at the buffer level).

Handles survive across RPC calls so an inference session touches its KV by
integer id only — exactly the reference's cross-process contract, minus the
processes.
"""

from __future__ import annotations

import asyncio
import collections
import contextlib
import dataclasses
import time
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from petals_tpu import chaos
from petals_tpu.analysis.sanitizer import make_async_lock
from petals_tpu.data_structures import Handle
from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class AllocationFailed(Exception):
    pass


@dataclasses.dataclass(frozen=True)
class TensorDescriptor:
    shape: Tuple[int, ...]
    dtype: jnp.dtype
    sharding: object = None  # optional jax.sharding.Sharding (TP: heads split)

    @property
    def nbytes(self) -> int:
        return int(np.prod(self.shape)) * jnp.dtype(self.dtype).itemsize

    def make_zeros(self, device: Optional[jax.Device] = None) -> jax.Array:
        arr = jnp.zeros(self.shape, self.dtype)
        if self.sharding is not None:
            return jax.device_put(arr, self.sharding)
        return jax.device_put(arr, device) if device is not None else arr


class PageAllocator:
    """Page-grain free list + refcounts over ONE preallocated page pool.

    The paged KV cache (server/batching.py) budgets its whole page pool
    through MemoryCache ONCE at open; this allocator then hands out page
    INDICES on demand — admission costs one page, not max_length tokens, and
    lanes grow page-by-page. Refcounts make pages shareable: a block-table
    reference and a prefix-cache pin each count one, and a page with
    ``refs > 1`` must be forked (copy-on-write) before any write.

    Synchronous core, asyncio signalling: every mutation happens on the
    event loop (the batcher's table/refcount bookkeeping is loop-side, like
    its lane lists), and ``freed_event`` wakes allocation waiters when a
    page returns — the MemoryCache backpressure contract, at page grain.
    """

    def __init__(self, n_pages: int):
        assert n_pages > 0
        self.n_pages = int(n_pages)
        self._free = collections.deque(range(self.n_pages))
        self._free_set = set(range(self.n_pages))
        self.refs = np.zeros((self.n_pages,), np.int32)
        self.freed_event = asyncio.Event()
        self.stats = {"allocated": 0, "forked": 0, "freed": 0}

    @property
    def n_free(self) -> int:
        return len(self._free)

    def try_alloc(self, preferred: Optional[int] = None) -> Optional[int]:
        """Take a free page (refs=1) or None when the pool is exhausted.
        ``preferred`` is taken when free — the batcher asks for the identity
        page so pages read in sequential HBM order and the tables_contiguous
        debug flag stays meaningful (the fused paged-attention kernel serves
        identity and permuted tables through the same program)."""
        if not self._free:
            return None
        if preferred is not None and preferred in self._free_set:
            self._free.remove(preferred)
            page = preferred
        else:
            page = self._free.popleft()
        self._free_set.discard(page)
        self.refs[page] = 1
        self.stats["allocated"] += 1
        return page

    def free_runs(self) -> list:
        """Lengths of the contiguous free-page runs, ascending page order.
        Contiguity matters because the batcher prefers identity pages: a
        shattered free list means new lanes land on scattered pages and the
        dense-table fast path degrades to gathers."""
        runs = []
        current = 0
        prev = -2
        for page in sorted(self._free_set):
            if page == prev + 1:
                current += 1
            else:
                if current:
                    runs.append(current)
                current = 1
            prev = page
        if current:
            runs.append(current)
        return runs

    def fragmentation_info(self) -> dict:
        """Free-space economics snapshot: run-length histogram (static
        buckets — these become metric labels), largest run, and a scalar
        fragmentation ratio (1 - largest_run/free; 0 = one hole)."""
        runs = self.free_runs()
        free = len(self._free_set)
        largest = max(runs) if runs else 0
        hist = {"1": 0, "2_3": 0, "4_7": 0, "8_15": 0, "16_plus": 0}
        for r in runs:
            if r == 1:
                hist["1"] += 1
            elif r <= 3:
                hist["2_3"] += 1
            elif r <= 7:
                hist["4_7"] += 1
            elif r <= 15:
                hist["8_15"] += 1
            else:
                hist["16_plus"] += 1
        return {
            "free": free,
            "runs": len(runs),
            "largest_run": largest,
            "frag": round(1.0 - largest / free, 4) if free else 0.0,
            "run_hist": hist,
        }

    def fractional_shares(self, tables: np.ndarray) -> np.ndarray:
        """Fractional page ownership per block-table row: a page with
        refcount R contributes 1/R to each row referencing it, so summing a
        row's shares (plus the prefix cache's pin remainder) reconstructs
        exactly the allocated page count — the resource ledger's COW
        attribution rule (telemetry.ledger page-seconds conservation).
        ``tables`` is [n_rows, max_pages] int32 with -1 for empty slots."""
        mask = tables >= 0
        pages = np.where(mask, tables, 0)
        inv = np.where(mask, 1.0 / np.maximum(self.refs[pages], 1), 0.0)
        return inv.sum(axis=1)

    def incref(self, page: int) -> None:
        assert self.refs[page] > 0, f"incref of free page {page}"
        self.refs[page] += 1

    def decref(self, page: int) -> None:
        """Drop one reference; a page at zero returns to the free list (FIFO)
        and wakes allocation waiters."""
        assert self.refs[page] > 0, f"decref of free page {page}"
        self.refs[page] -= 1
        if self.refs[page] == 0 and page not in self._free_set:
            self._free.append(page)
            self._free_set.add(page)
            self.stats["freed"] += 1
            self.freed_event.set()


class HostSwapPool:
    """Byte-budgeted accounting for the host-RAM KV swap tier.

    When the page pool is exhausted, the session scheduler
    (server/scheduler.py) preempts a victim lane: its resident pages are
    gathered on device and copied to host RAM, its pool pages freed, and the
    content scattered back onto (possibly different) pages when the session
    next steps. This class only accounts the bytes — the arrays themselves
    ride inside the scheduler's swap entries, so the budget bounds how much
    host RAM preemption may pin. ``try_reserve`` is all-or-nothing: a victim
    whose KV does not fit is simply not preemptable, and the caller falls
    back to ordinary waiter backpressure.

    The radix prefix cache's swap tier shares THIS budget: demoted cache
    nodes reserve with ``kind="cache"``, tracked separately
    (``cache_bytes_in_use``) so the scheduler summary can show how the one
    budget splits between preempted sessions and demoted cache nodes. The
    cache self-limits to a fraction of the budget (prefix_cache.py
    CACHE_SWAP_FRAC) so session preemption always finds room.

    The copies land in ordinary (pageable) numpy memory; on TPU runtimes the
    device->host transfer is staged through the runtime's pinned buffers, and
    a future upgrade can place the pool in the ``pinned_host`` memory space
    once the jax version floor allows it.
    """

    def __init__(self, max_size_bytes: int):
        assert max_size_bytes >= 0
        self.max_size_bytes = int(max_size_bytes)
        self._bytes_in_use = 0
        self._cache_bytes_in_use = 0  # of which: demoted prefix-cache nodes
        self.stats = {
            "reserved": 0, "rejected": 0, "peak_bytes": 0,
            "cache_reserved": 0, "cache_rejected": 0,
        }

    @property
    def bytes_in_use(self) -> int:
        return self._bytes_in_use

    @property
    def cache_bytes_in_use(self) -> int:
        return self._cache_bytes_in_use

    @property
    def bytes_left(self) -> int:
        return self.max_size_bytes - self._bytes_in_use

    def try_reserve(self, nbytes: int, kind: str = "session") -> bool:
        """Reserve ``nbytes`` for one swap entry, or False when it would
        overflow the budget (the entry's victim stays resident).
        ``kind="cache"`` tags a prefix-cache node demotion — same budget,
        separate accounting."""
        nbytes = int(nbytes)
        assert nbytes >= 0
        if chaos.ENABLED and chaos.fire(chaos.SITE_SWAP_RESERVE) is not None:
            # injected pressure spike: behave exactly like a full budget
            self.stats["rejected" if kind == "session" else "cache_rejected"] += 1
            return False
        if nbytes > self.bytes_left:
            self.stats["rejected" if kind == "session" else "cache_rejected"] += 1
            return False
        self._bytes_in_use += nbytes
        if kind == "cache":
            self._cache_bytes_in_use += nbytes
            self.stats["cache_reserved"] += 1
        else:
            self.stats["reserved"] += 1
        self.stats["peak_bytes"] = max(self.stats["peak_bytes"], self._bytes_in_use)
        return True

    def free(self, nbytes: int, kind: str = "session") -> None:
        self._bytes_in_use -= int(nbytes)
        if kind == "cache":
            self._cache_bytes_in_use -= int(nbytes)
            assert self._cache_bytes_in_use >= 0, (
                "cache swap accounting went negative"
            )
        assert self._bytes_in_use >= 0, "swap-pool accounting went negative"


class MemoryCache:
    """Budgeted handle-based allocator for session KV buffers in HBM."""

    def __init__(self, max_size_bytes: Optional[int], max_alloc_timeout: Optional[float] = None):
        self.max_size_bytes = max_size_bytes if max_size_bytes is not None else 2**64
        self.max_alloc_timeout = max_alloc_timeout
        self._current_size_bytes = 0
        self._handle_counter = 0
        self._allocated: Dict[Handle, TensorDescriptor] = {}
        self._buffers: Dict[Handle, Optional[jax.Array]] = {}
        self._lock = make_async_lock("memory_cache._lock")
        self._freed_event = asyncio.Event()
        self._waiter_queue: list = []  # FIFO fairness for oversubscribed allocs

    @property
    def current_size_bytes(self) -> int:
        return self._current_size_bytes

    @property
    def bytes_left(self) -> int:
        return self.max_size_bytes - self._current_size_bytes

    @property
    def num_allocated(self) -> int:
        return len(self._allocated)

    @contextlib.asynccontextmanager
    async def allocate_cache(self, *descriptors: TensorDescriptor, timeout: Optional[float] = None):
        """Reserve budget for ``descriptors``; yield one handle per descriptor."""
        if self.max_alloc_timeout is not None:
            timeout = self.max_alloc_timeout if timeout is None else min(timeout, self.max_alloc_timeout)
        alloc_size = sum(d.nbytes for d in descriptors)
        if alloc_size > self.max_size_bytes:
            raise AllocationFailed(
                f"Cannot allocate {alloc_size} bytes: exceeds total cache size "
                f"{self.max_size_bytes} bytes"
            )

        alloc_task = asyncio.create_task(self._wait_and_reserve(descriptors, alloc_size, timeout))
        try:
            handles = await alloc_task
            yield handles
        finally:
            # Cancellation while *waiting* aborts cleanly (nothing reserved yet);
            # if the reservation raced to completion anyway, free it here.
            if not alloc_task.done():
                alloc_task.cancel()
                with contextlib.suppress(asyncio.CancelledError, AllocationFailed):
                    await alloc_task
            if alloc_task.done() and not alloc_task.cancelled() and alloc_task.exception() is None:
                self._free(alloc_task.result())

    async def _wait_and_reserve(
        self, descriptors: Sequence[TensorDescriptor], alloc_size: int, timeout: Optional[float]
    ) -> Tuple[Handle, ...]:
        start = time.monotonic()
        my_turn = asyncio.Event()
        self._waiter_queue.append(my_turn)
        if len(self._waiter_queue) == 1:
            my_turn.set()
        try:
            while True:
                if self._waiter_queue and self._waiter_queue[0] is my_turn:
                    my_turn.set()
                if my_turn.is_set():
                    async with self._lock:
                        # re-check under the lock: acquiring it may have yielded
                        if alloc_size <= self.bytes_left:
                            return self._reserve(descriptors, alloc_size)
                remaining = None if timeout is None else timeout - (time.monotonic() - start)
                if remaining is not None and remaining <= 0:
                    raise AllocationFailed(
                        f"Could not allocate {alloc_size} bytes within {timeout} s "
                        f"({self.bytes_left} of {self.max_size_bytes} bytes free, "
                        f"{len(self._waiter_queue) - 1} waiters ahead)"
                    )
                self._freed_event.clear()
                try:
                    await asyncio.wait_for(self._freed_event.wait(), timeout=remaining)
                except asyncio.TimeoutError:
                    pass  # loop once more to produce the AllocationFailed message
        finally:
            self._waiter_queue.remove(my_turn)
            self._freed_event.set()  # let the next waiter re-check its turn

    def _reserve(self, descriptors: Sequence[TensorDescriptor], alloc_size: int) -> Tuple[Handle, ...]:
        handles = []
        for descr in descriptors:
            handle = self._handle_counter
            self._handle_counter += 1
            self._allocated[handle] = descr
            self._buffers[handle] = None  # lazily materialized by use_cache
            handles.append(handle)
        self._current_size_bytes += alloc_size
        logger.debug(f"Allocated {alloc_size} bytes, handles={handles}; left={self.bytes_left}")
        return tuple(handles)

    def _free(self, handles: Sequence[Handle]) -> None:
        freed = 0
        for handle in handles:
            descr = self._allocated.pop(handle, None)
            if descr is not None:
                freed += descr.nbytes
            self._buffers.pop(handle, None)  # drops the HBM buffer reference
        self._current_size_bytes -= freed
        self._freed_event.set()
        logger.debug(f"Freed {freed} bytes, handles={list(handles)}; left={self.bytes_left}")

    @contextlib.contextmanager
    def use_cache(self, *handles: Handle, device: Optional[jax.Device] = None):
        """Deprecated contextmanager shim; use :meth:`get_buffers` (the
        single-process design never needed scoped access)."""
        yield self.get_buffers(*handles, device=device)

    def get_buffers(self, *handles: Handle, device: Optional[jax.Device] = None) -> list:
        """Compute-side access: the device buffers for ``handles``,
        materializing zeros on first touch."""
        buffers = []
        for handle in handles:
            if handle not in self._allocated:
                raise KeyError(f"Handle {handle} was not allocated (or already freed)")
            if self._buffers[handle] is None:
                self._buffers[handle] = self._allocated[handle].make_zeros(device)
            buffers.append(self._buffers[handle])
        return buffers

    def reset_buffer(self, handle: Handle) -> None:
        """Drop a handle's buffer so the next get_buffers rematerializes
        zeros (recovery path: a failed donating step consumed the buffer)."""
        if handle not in self._allocated:
            raise KeyError(f"Handle {handle} was not allocated (or already freed)")
        self._buffers[handle] = None

    def update_cache(self, handle: Handle, new_buffer: jax.Array) -> None:
        """Store the post-step buffer for ``handle`` (functional update; pair with
        XLA donation so the HBM allocation is reused)."""
        if handle not in self._allocated:
            raise KeyError(f"Handle {handle} was not allocated (or already freed)")
        descr = self._allocated[handle]
        assert tuple(new_buffer.shape) == tuple(descr.shape), (new_buffer.shape, descr.shape)
        self._buffers[handle] = new_buffer
