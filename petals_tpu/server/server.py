"""Server orchestrator: load a span of blocks, serve it, announce it
(counterpart of reference src/petals/server/server.py:46-775 — Server +
ModuleContainer + ModuleAnnouncerThread, collapsed into one asyncio process
since a JAX server has no per-connection forked handlers or separate runtime
process).
"""

from __future__ import annotations

import asyncio
import contextlib
import dataclasses
import math
import re
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

import petals_tpu
from petals_tpu import chaos
from petals_tpu.data_structures import ServerInfo, ServerState, make_uid, PeerID
from petals_tpu.dht.node import DHTNode, dht_time
from petals_tpu.rpc.server import RpcServer
from petals_tpu.server.backend import TransformerBackend
from petals_tpu.server.from_pretrained import get_block_config, load_block_params
from petals_tpu.server.handler import TransformerHandler
from petals_tpu.server.memory_cache import MemoryCache
from petals_tpu.utils.convert_block import QuantType, block_size_bytes, convert_block_params
from petals_tpu.utils.asyncio_utils import log_exception_callback
from petals_tpu.utils.dht_utils import declare_active_modules
from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)

DEFAULT_UPDATE_PERIOD = 30.0

# disaggregated serving tiers: generalists serve both phases; prefill-tier
# replicas soak FLOPs-bound prompt processing and hand the finished KV to a
# decode-tier replica over the page-push path (handler.rpc_session_handoff)
PHASE_TIERS = ("generalist", "prefill", "decode")


def default_dht_prefix(model_name: str) -> str:
    """Derive the swarm namespace from the model name (reference
    models/*/config.py dht_prefix logic: name minus org, '-hf' suffix)."""
    name = model_name.rstrip("/").split("/")[-1]
    name = re.sub(r"[^\w.-]", "-", name)
    return f"{name}-hf"


class Server:
    """Hosts blocks [first_block, first_block + num_blocks) of one model."""

    def __init__(
        self,
        model_path: str,
        *,
        first_block: Optional[int] = None,  # None: auto-place from swarm state
        num_blocks: Optional[int] = None,  # None: auto-size to device memory
        dht_prefix: Optional[str] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        initial_peers: Sequence = (),
        identity_seed: Optional[bytes] = None,
        compute_dtype=jnp.bfloat16,
        attn_cache_bytes: Optional[int] = None,
        max_chunk_size_bytes: int = 256 * 1024 * 1024,
        throughput="auto",  # float, or "auto" to self-measure (server/throughput.py)
        public_name: Optional[str] = None,
        update_period: float = DEFAULT_UPDATE_PERIOD,
        mean_balance_check_period: float = 0.0,  # >0: periodically rebalance span placement
        use_flash: Optional[bool] = None,
        max_alloc_timeout: float = 600.0,
        num_tp_devices: Optional[int] = None,  # >1: shard the span over this host's chips
        num_sp_devices: Optional[int] = None,  # >1: ring-attention seq parallelism (fwd/bwd path)
        quant_type: str = "none",  # "none" | "int8" | "nf4" | "nf4a" | "int4" (ops/quant.py)
        adapters: Sequence[str] = (),  # PEFT checkpoint dirs to host (utils/peft.py)
        compression: str = "none",  # default reply codec (clients may override per request)
        relay_via: Optional[str] = None,  # "host:port" of a relay peer: serve from behind NAT
        network_mbps: Optional[float] = None,  # known WAN budget; None = probe swarm peers
        inference_max_length: Optional[int] = None,  # None: 8192 for GQA/MQA, 2048 otherwise
        request_timeout: float = 3 * 60,
        session_timeout: float = 30 * 60,
        step_timeout: float = 5 * 60,
        balance_quality: float = 0.75,  # rebalance iff swarm quality < this (block_selection.py)
        revision: str = "main",  # Hub revision for weight streaming (utils/hub.py)
        cache_dir=None,  # Hub download cache (default PETALS_TPU_CACHE)
        quant_weight_cache: bool = True,  # persist quantized blocks across restarts
        coordinator_address: Optional[str] = None,  # multi-host: jax.distributed coordinator
        num_hosts: int = 1,  # multi-host: total processes (this leader + run_worker peers)
        batching: bool = True,  # continuous batching of concurrent decode sessions
        batch_lanes: Optional[int] = None,  # None: auto-size to the cache budget (<=8)
        batch_max_length: Optional[int] = None,  # pool lane length; None: min(inference_max_length, 1024)
        page_size: int = 64,  # paged KV: tokens per page; 0 = dense lane pool
        n_pages: Optional[int] = None,  # paged KV pool size; None = lanes * pages-per-lane
        kv_quant_type: str = "none",  # paged KV pool storage: "none" | "int8" | "nf4a"
        prefill_token_budget: int = 512,  # prefill tokens folded into each mixed batched step
        swap_host_bytes: int = 0,  # host-RAM KV swap tier (session preemption); 0 disables
        preemption_policy: str = "lru",  # victim choice on pool exhaustion: lru | largest | off
        prefix_cache_bytes: int = 256 * 2**20,  # host-RAM prompt-prefix cache; 0 disables
        prefix_share_scope: str = "swarm",  # "peer" isolates the prefix cache per client identity
        prefix_device_bytes: int = 256 * 2**20,  # HBM tier of the prefix cache; 0 disables
        prefix_cache_policy: str = "radix",  # "radix" tree with tiering | "lru" flat baseline
        server_side_generation: bool = True,  # device-side greedy loop on full-span servers
        draft_model: Optional[str] = None,  # small checkpoint for speculative decoding
        spec_k: int = 4,  # drafts verified per lane per tick when draft_model is set
        draft_window: Optional[int] = None,  # draft context window (tokens); None = default
        draft_quant_type: str = "nf4a",  # draft block quantization (4-bit serving default)
        metrics_port: Optional[int] = None,  # Prometheus /metrics HTTP port; None disables, 0 = ephemeral
        phase_tier: str = "generalist",  # disaggregated serving: "generalist" | "prefill" | "decode"
    ):
        self.num_hosts = num_hosts or 1
        self.coordinator_address = coordinator_address
        if self.num_hosts > 1:
            # MUST run before anything touches jax (even jax.devices());
            # everything below may initialize the XLA backend
            from petals_tpu.parallel.multihost import init_multihost

            if not coordinator_address:
                raise ValueError("num_hosts > 1 requires coordinator_address")
            init_multihost(coordinator_address, self.num_hosts, 0)
            if first_block is None or num_blocks is None:
                raise ValueError(
                    "multi-host serving needs an explicit --first_block/--num_blocks "
                    "(workers load the identical span; auto-placement would desync them)"
                )
        self.model_path = model_path
        self.revision = revision
        self.cache_dir = cache_dir
        # config must come from the SAME revision/cache the weights stream
        # from, or block splitting and shapes follow a different architecture
        self.family, self.cfg = get_block_config(
            model_path, revision=revision, cache_dir=cache_dir
        )
        total = self.cfg.num_hidden_layers
        self.auto_placement = first_block is None
        # PETALS_TPU_RADIX_DEVICE_FRAC retunes the radix cache's HBM/host
        # split as a fraction of prefix_cache_bytes without code edits
        # (revival step 10/10 silicon crossover)
        from petals_tpu.server.prefix_cache import resolve_device_bytes

        prefix_device_bytes = resolve_device_bytes(
            prefix_cache_bytes, prefix_device_bytes
        )
        if attn_cache_bytes is None:
            from petals_tpu.server.block_utils import device_memory_bytes

            memory = device_memory_bytes()
            # default KV budget: 15% of device memory (reference reserves an
            # attn-cache fraction before packing blocks, server.py:275-326)
            attn_cache_bytes = int(memory * 0.15) if memory else 2 << 30
            # the prefix cache's HBM tier lives OUTSIDE MemoryCache's budget
            # (pinned device slices, prefix_cache.py): carve it out of the
            # auto-sized KV budget or the default-on device tier tips an
            # auto-sized server into on-chip OOM; floored so a huge
            # prefix_device_bytes cannot starve serving entirely
            if prefix_device_bytes > 0:
                attn_cache_bytes = max(
                    attn_cache_bytes - prefix_device_bytes, attn_cache_bytes // 4
                )
        if num_blocks is None:
            if first_block is not None:
                num_blocks = total - first_block
            else:
                from petals_tpu.server.block_utils import choose_num_blocks

                num_blocks = choose_num_blocks(
                    self.family, self.cfg, quant_type=quant_type,
                    attn_cache_bytes=attn_cache_bytes or 0,
                )
        self.first_block = first_block if first_block is not None else 0
        self.num_blocks = num_blocks
        assert 0 <= self.first_block < self.first_block + self.num_blocks <= total
        self.dht_prefix = dht_prefix or default_dht_prefix(model_path)
        self.host, self.port = host, port
        self.initial_peers = list(initial_peers)
        self.identity_seed = identity_seed
        self.compute_dtype = compute_dtype
        self.attn_cache_bytes = attn_cache_bytes
        self.max_chunk_size_bytes = max_chunk_size_bytes
        if not isinstance(throughput, (int, float)) and throughput != "auto":
            raise ValueError(f'throughput must be a number or "auto", got {throughput!r}')
        self._throughput_spec = throughput
        self.throughput = throughput if isinstance(throughput, (int, float)) else 1.0
        self.public_name = public_name
        self.update_period = update_period
        self.mean_balance_check_period = mean_balance_check_period
        self.use_flash = use_flash
        self.max_alloc_timeout = max_alloc_timeout
        self.num_tp_devices = num_tp_devices
        self.num_sp_devices = num_sp_devices
        if (num_sp_devices or 1) > 1 and not self.family.supports_ring_attention:
            raise ValueError(
                f"num_sp_devices>1 needs ring attention, which {self.family.name} "
                f"does not support (plain causal only) — the sp devices would "
                f"sit idle holding replicated parameters"
            )
        self.quant_type = quant_type
        self.quant_weight_cache = quant_weight_cache
        self.adapter_paths = list(adapters)
        from petals_tpu.rpc.serialization import CompressionType

        self.compression = CompressionType(compression)
        if inference_max_length is None:
            # reference server.py:194-198: longer contexts for MQA/GQA models
            # (their KV is cheap), conservative cap otherwise
            heads = getattr(self.cfg, "num_attention_heads", 1)
            kv_heads = getattr(self.cfg, "num_key_value_heads", heads) or heads
            inference_max_length = 8192 if kv_heads < heads else 2048
        self.inference_max_length = inference_max_length
        self.batching = batching
        self.batch_lanes = batch_lanes
        self.batch_max_length = batch_max_length
        self.page_size = page_size
        self.n_pages = n_pages
        from petals_tpu.ops.paged_attention import KV_QUANT_KINDS

        if kv_quant_type not in KV_QUANT_KINDS:
            raise ValueError(
                f"kv_quant_type must be one of {KV_QUANT_KINDS}, got {kv_quant_type!r}"
            )
        if kv_quant_type != "none" and not page_size:
            raise ValueError(
                "kv_quant_type requires the paged KV pool (--page_size > 0): the "
                "dense lane pool has no quantized storage path"
            )
        self.kv_quant_type = kv_quant_type
        self.prefill_token_budget = prefill_token_budget
        self.swap_host_bytes = swap_host_bytes
        self.preemption_policy = preemption_policy
        self.prefix_cache_bytes = prefix_cache_bytes
        self.prefix_share_scope = prefix_share_scope
        self.prefix_device_bytes = prefix_device_bytes
        self.prefix_cache_policy = prefix_cache_policy
        self.server_side_generation = server_side_generation
        self.draft_model_path = draft_model
        self.spec_k = int(spec_k)
        self.draft_window = draft_window
        self.draft_quant_type = draft_quant_type
        self._draft_model = None  # loaded lazily by _make_handler
        self.request_timeout = request_timeout
        self.session_timeout = session_timeout
        self.step_timeout = step_timeout
        self.balance_quality = balance_quality
        self.module_uids = [
            make_uid(self.dht_prefix, i)
            for i in range(self.first_block, self.first_block + self.num_blocks)
        ]
        self._local_devices_only = False  # set by partial re-formation
        self.rpc_server: Optional[RpcServer] = None
        self.dht: Optional[DHTNode] = None
        self.handler: Optional[TransformerHandler] = None
        self.backend: Optional[TransformerBackend] = None
        self.memory_cache: Optional[MemoryCache] = None
        self._announcer_task: Optional[asyncio.Task] = None
        self._balancer_task: Optional[asyncio.Task] = None
        self._state = ServerState.JOINING  # what the announce loop broadcasts
        self._ready = asyncio.Event()
        # successor-server RTTs published with every announce so clients can
        # cost server->server hops (reference server.py:717-751)
        self._next_pings: dict = {}
        self._ping_aggregator = None
        self._trace_flush_task: Optional[asyncio.Task] = None
        self.relay_via = relay_via
        self.network_mbps = network_mbps
        self._relay_registrar = None
        self._contact_addr = None  # non-default announce addr (relay circuit)
        self.metrics_port = metrics_port
        self._metrics_server = None  # telemetry.exposition.MetricsServer when enabled
        if phase_tier not in PHASE_TIERS:
            raise ValueError(
                f"phase_tier must be one of {PHASE_TIERS}, got {phase_tier!r}"
            )
        self.phase_tier = phase_tier

    # ------------------------------------------------------------------ lifecycle

    @staticmethod
    def enable_compilation_cache() -> Optional[str]:
        """Point XLA's persistent compilation cache at our disk cache so a
        restarted server re-uses every compiled step executable instead of
        paying tens of seconds per shape bucket again (the TPU analogue of the
        reference warm-start concerns; disable with
        PETALS_TPU_NO_COMPILATION_CACHE=1)."""
        import os

        if os.environ.get("PETALS_TPU_NO_COMPILATION_CACHE"):
            return None
        from petals_tpu.utils.disk_cache import DEFAULT_CACHE_DIR

        cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR") or str(
            DEFAULT_CACHE_DIR / "xla_cache"
        )
        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            if not os.environ.get("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"):
                # operator's env setting wins; otherwise skip sub-second compiles
                jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        except Exception as e:  # older jax: feature-gate, never fail startup
            logger.debug(f"Compilation cache unavailable: {e}")
            return None
        return cache_dir

    async def start(self) -> None:
        self.enable_compilation_cache()
        from petals_tpu.dht.identity import Identity

        identity = (
            Identity.from_seed(self.identity_seed) if self.identity_seed else Identity.generate()
        )
        self._identity = identity  # re-used by partial re-formation
        peer_id = identity.peer_id
        self.rpc_server = RpcServer(identity=identity, host=self.host, port=self.port)
        if self.relay_via is not None:
            # NAT'd / firewalled server: no listener at all. The rpc surface is
            # served on REVERSE connections dialed out through the relay
            # (rpc/relay.py), the DHT runs query-only (reference client-mode
            # DHT, server.py:137-150), and the announced contact address is the
            # relay circuit.
            from petals_tpu.dht.routing import PeerAddr
            from petals_tpu.rpc.relay import RelayRegistrar

            relay_host, relay_port = self.relay_via.rsplit(":", 1)
            self.dht = await DHTNode.create(
                identity=identity,
                client_mode=True,
                initial_peers=self.initial_peers,
            )
            self._relay_registrar = RelayRegistrar(
                relay_host, int(relay_port), identity, self.rpc_server
            )
            await self._relay_registrar.start()
            await self._relay_registrar.wait_registered()
            self._contact_addr = PeerAddr(relay_host, int(relay_port), peer_id, relayed=True)
            # the client-mode DHT registers nothing on our serving RpcServer,
            # but peers still probe relayed servers (RTT for next_pings /
            # routing, bandwidth, health dial-backs) — serve those here
            from petals_tpu.utils.bandwidth import BandwidthProtocol

            async def _ping(_payload, _ctx):
                return {"peer_id": peer_id.to_string()}

            self.rpc_server.add_unary_handler("dht.ping", _ping)
            BandwidthProtocol().register(self.rpc_server)
            logger.info(f"Serving behind relay {self.relay_via} (no inbound listener)")
        else:
            # Start listening BEFORE the DHT bootstraps: the node advertises its
            # own (host, port) to peers during bootstrap.
            await self.rpc_server.start()
            self.dht = await DHTNode.create(
                identity=identity,
                rpc_server=self.rpc_server,
                initial_peers=self.initial_peers,
            )

        from petals_tpu.server.reachability import ReachabilityProtocol

        ReachabilityProtocol().register(self.rpc_server)

        # max_alloc_timeout caps client-requested allocation waits so one
        # unsatisfiable session can't park at the head of the FIFO forever
        self.memory_cache = MemoryCache(self.attn_cache_bytes, max_alloc_timeout=self.max_alloc_timeout)
        if self.num_hosts > 1:
            from petals_tpu.parallel.multihost import LockstepMemoryCache

            # reservation/free broadcast ALLOC/FREE so workers mirror the
            # session KV buffers by handle
            self.memory_cache = LockstepMemoryCache(self.memory_cache)

        if self._throughput_spec == "auto" and self.num_hosts == 1:
            from petals_tpu.server.throughput import get_server_throughput

            network_mbps = await self._resolve_network_mbps()
            info = await asyncio.get_running_loop().run_in_executor(
                None,
                lambda: get_server_throughput(
                    self.family, self.cfg, compute_dtype=self.compute_dtype,
                    num_blocks=self.num_blocks, quant_type=QuantType(self.quant_type).value,
                    num_devices=self.num_tp_devices or 1,
                    network_mbps=network_mbps,
                    using_relay=self.relay_via is not None,
                ),
            )
            self.throughput = info["throughput"]
            self._rps_info = info
        else:
            # multi-host "auto" probes the REAL lockstep backend after it is
            # built (workers mirror every op) — see below
            self._rps_info = None

        if self.auto_placement:
            self.first_block = await self._choose_start_block()
            self.module_uids = [
                make_uid(self.dht_prefix, i)
                for i in range(self.first_block, self.first_block + self.num_blocks)
            ]
            logger.info(f"Auto placement: serving blocks [{self.first_block}, {self.first_block + self.num_blocks})")

        # announce JOINING while blocks load (reference server.py:468-481)
        await self._announce(ServerState.JOINING)

        logger.info(
            f"Loading blocks [{self.first_block}, {self.first_block + self.num_blocks}) "
            f"of {self.model_path}"
        )
        t0 = time.perf_counter()
        # load off the event loop: the DHT node is already answering peers and
        # must not go dark for the (potentially minutes-long) weight load
        stacked = await asyncio.get_running_loop().run_in_executor(
            None, self._load_span_params, self.first_block, self.num_blocks
        )
        span_bytes = block_size_bytes(stacked)
        logger.info(
            f"Blocks loaded in {time.perf_counter() - t0:.1f}s "
            f"({span_bytes / 2**20:.0f} MiB for {self.num_blocks} blocks, quant={self.quant_type})"
        )

        self.backend = self._make_backend(stacked, self.first_block)
        self._install_adapters(self.backend)
        if self._throughput_spec == "auto" and self.num_hosts > 1:
            await self._measure_multihost_throughput()
        self.handler = self._make_handler()
        self.handler.register(self.rpc_server)

        from petals_tpu.utils.ping import PingAggregator

        # ride the DHT node's existing connection pool (same peer identity);
        # the first announce goes out WITHOUT next_pings — readiness must not
        # block on pinging possibly-dead successors, the announce loop fills
        # them in within one update_period
        self._ping_aggregator = PingAggregator(self.dht.pool)

        from petals_tpu.utils.tracing import (
            start_jax_trace,
            stop_jax_trace,
            trace_window_seconds,
        )

        if start_jax_trace() is not None:  # active only with PETALS_TPU_TRACE_DIR
            # bounded window: the profiler buffers until stop, so an open-ended
            # capture on a long-running server would grow host memory forever
            async def _flush_trace():
                await asyncio.sleep(trace_window_seconds())
                stop_jax_trace()

            # keep a strong ref: asyncio holds tasks weakly, and a collected
            # flush task would mean the capture never stops
            self._trace_flush_task = asyncio.create_task(_flush_trace())
            self._trace_flush_task.add_done_callback(
                log_exception_callback(logger, "trace flush")
            )

        if self.metrics_port is not None:
            from petals_tpu.telemetry.exposition import MetricsServer

            try:
                self._metrics_server = MetricsServer(port=self.metrics_port)
                logger.info(f"Prometheus /metrics on port {self._metrics_server.port}")
            except OSError as e:  # port taken: serve without scrape endpoint
                logger.warning(f"Could not bind metrics port {self.metrics_port}: {e}")
                self._metrics_server = None

        self._state = ServerState.ONLINE
        await self._announce(ServerState.ONLINE)
        self._announcer_task = asyncio.create_task(self._announce_loop())
        self._announcer_task.add_done_callback(
            log_exception_callback(logger, "announce loop")
        )
        if self.mean_balance_check_period > 0:
            self._balancer_task = asyncio.create_task(self._balance_loop())
            self._balancer_task.add_done_callback(
                log_exception_callback(logger, "balance loop")
            )
        self._ready.set()
        logger.info(f"Server ready: {self.contact_addr.to_string()} serving {self.module_uids}")

    @property
    def contact_addr(self):
        """The address this server announces: its relay circuit when hidden,
        otherwise the DHT node's own listen address."""
        return self._contact_addr or (self.dht.own_addr if self.dht is not None else None)

    async def wait_ready(self) -> None:
        await self._ready.wait()

    async def drain(self, park_ttl: float = 60.0, migrate: bool = True) -> int:
        """Graceful-shutdown prelude: stop accepting sessions, announce OFFLINE,
        and park every live session's KV in host RAM so clients can migrate
        their caches to replacement servers (``ptu.session_export``) instead of
        recomputing prefills. With ``migrate=True`` (drain-to-migrate) the
        parked KV is then proactively PUSHED to live replicas covering each
        session's span — the client's repair becomes a redirect + server-side
        ``kv_adopt``, moving zero KV bytes over the client's own link. The RPC
        server stays up — call :meth:`shutdown` after the drain window.
        Returns the number of parked sessions."""
        # a rebalance firing mid-drain would reload blocks and re-announce
        # ONLINE, overriding the OFFLINE below — stop considering moves first
        if self._balancer_task is not None:
            self._balancer_task.cancel()
            try:
                await self._balancer_task
            except asyncio.CancelledError:
                pass
            self._balancer_task = None
        parked = 0
        if self.handler is not None:
            # park BEFORE refusing steps: flipping `draining` first lets an
            # in-flight step raise and unregister its session while the park
            # snapshot awaits — the export would then find nothing. A step
            # that lands between the snapshot and the flip only makes the
            # parked copy stale, which clients top up by replaying the tail.
            parked = await self.handler.park_sessions(ttl=park_ttl)
            self.handler.draining = True
        self._state = ServerState.OFFLINE
        try:
            await self._announce(ServerState.OFFLINE, expiration=dht_time() + 60)
        except Exception as e:
            # best-effort: the DHT entry expires on its own if we cannot reach it
            logger.debug("OFFLINE announce during drain failed: %r", e)
        if parked:
            logger.info(f"Draining: parked {parked} session(s) for migration")
        if parked and migrate:
            pushed = await self._migrate_parked_sessions()
            if pushed:
                logger.info(f"Drain-to-migrate: pushed {pushed} session(s) to replicas")
        return parked

    async def _migrate_parked_sessions(self, deadline_s: float = 30.0) -> int:
        """Push every parked session's KV to a live replica covering its span
        (drain-to-migrate / rebalance path). Best-effort per session: a
        session with no covering replica, or whose push fails, simply stays
        parked — the client falls back to export-over-its-own-link or replay."""
        handler = self.handler
        if handler is None or not handler._parked or self.dht is None:
            return 0
        from petals_tpu.utils.dht_utils import get_remote_module_infos

        all_uids = [
            make_uid(self.dht_prefix, i) for i in range(self.cfg.num_hidden_layers)
        ]
        try:
            infos, addr_book = await get_remote_module_infos(self.dht, all_uids)
        except Exception as e:
            logger.warning(f"Drain-to-migrate skipped: swarm lookup failed ({e!r})")
            return 0
        migrated = 0
        for session_id, snap in list(handler._parked.items()):
            dest = self._pick_migration_target(
                infos, addr_book, snap["start"], snap["end"]
            )
            if dest is None:
                logger.info(
                    f"No live replica covers blocks [{snap['start']}, {snap['end']}): "
                    f"session {session_id!r} stays parked for client-side export"
                )
                continue
            peer_id, addr = dest
            if await handler.migrate_parked_to(
                session_id, snap, peer_id.to_string(), addr.to_string(),
                deadline_s=deadline_s,
            ):
                migrated += 1
        return migrated

    def _pick_migration_target(self, infos, addr_book, start: int, end: int):
        """Highest-throughput ONLINE peer (not us) serving every block of
        [start, end) with a known contact address, or None. Decode-tier
        replicas win ties-by-class: a migrated session is mid-generation, so
        its KV belongs on the tier shaped for token-by-token decoding."""
        candidates = None
        for i in range(start, end):
            info = infos[i] if i < len(infos) else None
            if info is None:
                return None
            here = {
                pid for pid, si in info.servers.items()
                if si.state == ServerState.ONLINE and pid in addr_book
                and pid != self.dht.peer_id
            }
            candidates = here if candidates is None else (candidates & here)
            if not candidates:
                return None
        best, best_key = None, (-1, -1.0)
        for pid in candidates:
            si = infos[start].servers[pid]
            tier = getattr(si, "phase_tier", None)
            key = (1 if tier == "decode" else 0, si.throughput or 0.0)
            if key > best_key:
                best, best_key = pid, key
        return (best, addr_book[best]) if best is not None else None

    async def shutdown(self) -> None:
        # a drain-to-migrate push racing shutdown must not hang teardown on a
        # slow (or chaos-delayed) destination peer — tell it to abort now;
        # aborted sessions stay parked and clients repair via export/replay
        if self.handler is not None:
            self.handler.abort_migrations()
        if self._balancer_task is not None:
            self._balancer_task.cancel()
            try:
                await self._balancer_task
            except asyncio.CancelledError:
                pass
        if self._announcer_task is not None:
            self._announcer_task.cancel()
            try:
                await self._announcer_task
            except asyncio.CancelledError:
                pass
        try:
            await self._announce(ServerState.OFFLINE, expiration=dht_time() + 60)
        except Exception as e:
            logger.debug("OFFLINE announce during shutdown failed: %r", e)
        from petals_tpu.utils.tracing import stop_jax_trace

        if self._trace_flush_task is not None:
            self._trace_flush_task.cancel()
        stop_jax_trace()
        if self._metrics_server is not None:
            self._metrics_server.close()
            self._metrics_server = None
        if self.num_hosts > 1 and self.backend is not None:
            # release the lockstep workers before the handler dies — they sit
            # in a blocking broadcast wait otherwise
            try:
                self.backend.shutdown_workers()
            except Exception as e:
                logger.warning(f"multihost worker shutdown broadcast failed: {e!r}")
        if self.handler is not None:
            self.handler.shutdown()
        # flush + close the journal's JSONL write-through sink AFTER the
        # handler stops emitting: the last scheduler decisions of this run
        # must reach disk even if the process dies right after shutdown.
        # The in-memory ring stays usable (close only detaches the sink).
        from petals_tpu.telemetry import get_journal

        get_journal().close()
        if self._relay_registrar is not None:
            await self._relay_registrar.stop()
        if self.dht is not None:
            await self.dht.shutdown()
        if self.rpc_server is not None:
            await self.rpc_server.stop()

    # ------------------------------------------------------------------ announcing

    def _server_info(self, state: ServerState) -> ServerInfo:
        cache_tokens_left = None
        if self.memory_cache is not None and self.backend is not None:
            per_token = self.backend.cache_bytes_per_token()
            if getattr(self, "kv_quant_type", "none") != "none":
                # quantized paged pool: a cached token costs wire bytes, so
                # the same budget advertises ~4x the remaining capacity
                per_token = self.backend.kv_bytes_per_token()
            cache_tokens_left = int(self.memory_cache.bytes_left // max(per_token, 1))
        rps = getattr(self, "_rps_info", None) or {}
        return ServerInfo(
            state=state,
            throughput=self.throughput,
            inference_rps=rps.get("inference_rps"),
            forward_rps=rps.get("forward_rps"),
            network_rps=rps.get("network_rps"),
            start_block=self.first_block,
            end_block=self.first_block + self.num_blocks,
            public_name=self.public_name,
            version=petals_tpu.__version__,
            compute_dtype=str(jnp.dtype(self.compute_dtype).name),
            quant_type=self.quant_type,
            adapters=tuple(
                sorted(self.backend.adapters) if self.backend is not None else ()
            ),
            cache_tokens_left=cache_tokens_left,
            next_pings=dict(self._next_pings) or None,
            server_gen=(
                self.handler.server_gen_params is not None
                if getattr(self, "handler", None) is not None else None
            ),
            # sampling rides the same device-gen machinery: any server that
            # can gen greedily can warp + sample on device too
            server_gen_sampling=(
                self.handler.server_gen_params is not None
                if getattr(self, "handler", None) is not None else None
            ),
            # speculative decoding capability: k drafts verified per tick
            # (informational — spec output is bit-identical to plain decode)
            spec_k=(
                self.spec_k
                if getattr(self, "handler", None) is not None
                and self.handler.draft_model is not None else None
            ),
            # lane-pool / scheduler occupancy for load-aware routing and the
            # health monitor; None on servers without continuous batching
            pool=(
                self.handler.batcher.occupancy_info()
                if getattr(self, "handler", None) is not None
                and self.handler.batcher is not None else None
            ),
            # per-server telemetry digest: the announce loop's cadence makes
            # the tok/s figure an update_period-window average
            telemetry=self._telemetry_digest(),
            compile_stats=self._compile_stats(),
            # integrity observatory: self-probe digest_hex + quarantine flag
            # (refreshed by the announce loop; None until the first refresh)
            integrity=getattr(self, "_integrity_info", None),
            # where /metrics and /journal live, so a breaching client can
            # fetch this server's journal excerpt for its trace_id
            metrics_port=(
                self._metrics_server.port
                if getattr(self, "_metrics_server", None) is not None else None
            ),
            # disaggregated serving tier; generalists announce it too so
            # run_health's tier column distinguishes "old server" from
            # "explicit generalist"
            phase_tier=self.phase_tier,
        )

    def _telemetry_digest(self) -> Optional[dict]:
        from petals_tpu.telemetry.exposition import telemetry_digest
        from petals_tpu.telemetry.integrity import cap_announce_payload

        try:
            # size-capped: the digest rides every widely-replicated DHT
            # announce, and the ledger sub-dict can grow with tenant count
            return cap_announce_payload(telemetry_digest())
        except Exception as e:  # an announce must never fail over metrics
            logger.debug("telemetry digest failed: %r", e)
            return None

    async def _refresh_integrity(self) -> None:
        """Refresh the announce-visible integrity digest: the span's
        self-probe fingerprint (the SAME ``ptu.probe`` path external canary
        probers hit, so an injected ``integrity.corrupt`` is visible in the
        announce too) plus this server's quarantine flag from the
        process-local registry. Announce-must-never-fail discipline: any
        error leaves the previous digest in place."""
        if getattr(self, "handler", None) is None or self.backend is None:
            return
        try:
            import numpy as np

            from petals_tpu.ops import fingerprint as fp_ops
            from petals_tpu.telemetry.integrity import (
                cap_announce_payload,
                get_quarantine,
            )

            reply = await self.handler.rpc_probe({"tokens": 4}, None)
            peer_str = ""
            if self._identity is not None:
                peer_str = self._identity.peer_id.to_string()
            self._integrity_info = cap_announce_payload({
                "self_digest": fp_ops.digest_hex(
                    np.asarray(reply["fp"], dtype=np.float32)
                ),
                "fp_seed": int(reply["fp_seed"]),
                "span": f"{reply['first_block']}:{reply['first_block'] + reply['n_blocks']}",
                "quarantined": bool(
                    peer_str and get_quarantine().is_quarantined(peer_str)
                ),
            })
        except Exception as e:
            logger.debug("integrity digest refresh failed: %r", e)

    def _compile_stats(self) -> Optional[dict]:
        from petals_tpu.telemetry.observatory import compile_stats_digest

        try:
            return compile_stats_digest()
        except Exception as e:  # an announce must never fail over metrics
            logger.debug("compile stats digest failed: %r", e)
            return None

    async def _announce(self, state: ServerState, expiration: Optional[float] = None) -> None:
        if chaos.ENABLED and chaos.fire(chaos.SITE_ANNOUNCE) is not None:
            # injected announce loss: the DHT record silently ages out, as if
            # the store never reached the network
            logger.warning("chaos: dropping DHT announce (%s)", state)
            return
        expiration = expiration or (dht_time() + max(2 * self.update_period, 60.0))
        if state != ServerState.OFFLINE:
            # refresh the announce-visible self-probe digest first, so the
            # ServerInfo built below carries this period's integrity view
            await self._refresh_integrity()
        await declare_active_modules(
            self.dht, self.module_uids, self._server_info(state), expiration,
            contact_addr=self._contact_addr,
        )
        if state != ServerState.OFFLINE:
            from petals_tpu.utils.dht_utils import declare_model

            await declare_model(
                self.dht, self.dht_prefix,
                num_blocks=self.cfg.num_hidden_layers,
                expiration_time=expiration,
                public_name=self.public_name,
                model_type=self.family.name,
            )

    def _load_span_params(self, first_block: int, num_blocks: int):
        # fused qkv/gate-up halves the Pallas call count at decode; off under
        # TP (per-leaf PartitionSpecs), with adapters (unfused leaf names),
        # and multi-host (mesh always present; workers load fuse=False)
        fuse = (
            (self.num_tp_devices or 1) <= 1
            and not self.adapter_paths
            and self.num_hosts == 1
        )
        per_block = [
            self._load_block_converted(i, fuse=fuse)
            for i in range(first_block, first_block + num_blocks)
        ]
        return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_block)

    def _load_block_converted(self, block_index: int, *, fuse: bool) -> dict:
        """One block, quantized per --quant_type. Quantized conversions are
        persisted in the disk cache (utils/quant_cache.py): the encode is a
        pure function of (checkpoint, kind, fuse), so restarts stream packed
        bytes instead of re-encoding (reference re-quantizes every start,
        convert_block.py:76-115 — acceptable on CUDA, minutes at 405B here)."""
        use_cache = self.quant_weight_cache and QuantType(self.quant_type) != QuantType.NONE
        if use_cache:
            from petals_tpu.utils import quant_cache

            path = quant_cache.cache_path(
                self.model_path, block_index, QuantType(self.quant_type).value,
                fuse=fuse, revision=self.revision, cache_dir=self.cache_dir,
                dtype_tag=jnp.dtype(self.compute_dtype).name,
            )
            cached = quant_cache.load_quantized_block(path)
            if cached is not None:
                return cached
        params = convert_block_params(
            load_block_params(
                self.model_path, block_index, dtype=self.compute_dtype,
                family=self.family, cfg=self.cfg, revision=self.revision,
                cache_dir=self.cache_dir,
            ),
            self.family.name,
            self.quant_type,
            fuse=fuse,
        )
        if use_cache:
            try:
                quant_cache.save_quantized_block(path, params)
            except OSError as e:
                logger.warning(f"Could not cache quantized block {block_index}: {e!r}")
        return params

    def _install_adapters(self, backend: TransformerBackend) -> None:
        if not self.adapter_paths:
            return
        from petals_tpu.utils.peft import load_adapter, stack_adapter

        block_range = range(self.first_block, self.first_block + self.num_blocks)
        for path in self.adapter_paths:
            adapter = load_adapter(path, self.family.name, block_range=block_range)
            stacked = stack_adapter(adapter, self.first_block, self.num_blocks, self.compute_dtype)
            backend.adapters[adapter.name] = (stacked, adapter.scaling)
        logger.info(f"Hosting adapters: {sorted(backend.adapters)}")

    def _make_handler(self) -> TransformerHandler:
        """Handler construction shared by start() and partial re-formation.
        Continuous-batching pool sizing: lanes cost HBM for their full lane
        length, so cap the pool at half the cache budget (private sessions
        and training still need room) and disable if fewer than 2 lanes fit."""
        batch_max_length = self.batch_max_length or min(self.inference_max_length, 1024)
        batch_lanes = self.batch_lanes
        if batch_lanes is None:
            lane_bytes = self.backend.cache_bytes_per_token() * batch_max_length
            if self.kv_quant_type != "none":
                # quantized pool pages cost wire bytes on device too (packed
                # codes + f32 scales), so the budget affords ~4x the lanes
                lane_bytes = self.backend.kv_bytes_per_token() * batch_max_length
            affordable = int(self.memory_cache.max_size_bytes // 2 // max(lane_bytes, 1))
            batch_lanes = max(min(8, affordable), 0)
        return TransformerHandler(
            self.backend,
            dht_prefix=self.dht_prefix,
            memory_cache=self.memory_cache,
            server_info_fn=lambda: dataclasses.asdict(self._server_info(ServerState.ONLINE)),
            identity=self._identity,
            compression=self.compression,
            inference_max_length=self.inference_max_length,
            request_timeout=self.request_timeout,
            session_timeout=self.session_timeout,
            step_timeout=self.step_timeout,
            batching=self.batching and batch_lanes >= 2,
            batch_lanes=batch_lanes,
            batch_max_length=batch_max_length,
            page_size=self.page_size or None,
            n_pages=self.n_pages,
            prefill_token_budget=self.prefill_token_budget,
            swap_host_bytes=self.swap_host_bytes,
            preemption_policy=self.preemption_policy,
            prefix_cache_bytes=self.prefix_cache_bytes,
            prefix_share_scope=self.prefix_share_scope,
            prefix_device_bytes=self.prefix_device_bytes,
            prefix_cache_policy=self.prefix_cache_policy,
            server_gen_params=self._load_server_gen_params(),
            draft_model=self._load_draft_model(),
            spec_k=self.spec_k if self.draft_model_path else None,
        )

    def _load_draft_model(self):
        """Speculative-decoding draft (server/spec_decode.py): a small full
        model loaded alongside the span. Same eligibility as server-side
        generation — the verify step embeds/samples with the client leaves —
        plus a paged pool (verification rides the chunk-scatter machinery).
        Any load failure degrades to plain decode, never a dead server."""
        if not self.draft_model_path or self.spec_k < 1:
            return None
        if (
            not self.server_side_generation
            or self.num_blocks != self.cfg.num_hidden_layers
            or self.first_block != 0
            or self.num_hosts > 1
            or not self.page_size
        ):
            logger.warning(
                "Speculative decoding disabled: --draft_model needs a "
                "full-span single-host server with server-side generation "
                "and a paged lane pool"
            )
            return None
        if self._draft_model is not None:
            return self._draft_model
        try:
            from petals_tpu.server.spec_decode import DEFAULT_WINDOW, DraftModel

            self._draft_model = DraftModel.from_pretrained(
                self.draft_model_path,
                spec_k=self.spec_k,
                window=int(self.draft_window or DEFAULT_WINDOW),
                quant_type=self.draft_quant_type,
                revision=self.revision,
                cache_dir=self.cache_dir,
            )
        except Exception as e:
            logger.warning(f"Speculative decoding disabled (draft load failed): {e}")
            self._draft_model = None
        return self._draft_model

    def _load_server_gen_params(self):
        """Client leaves (embed/norm/head) for the device-side greedy
        generation loop — full-span servers, single-host (TP meshes
        included: the loop reuses the span step fn, GSPMD partitions the
        whole scan, and the replicated head/embed ride along; lockstep
        groups stay excluded — the loop would need broadcast ops). Loaded
        in f32 so logits match the client's own lm_logits bit-for-bit."""
        if not self.server_side_generation:
            return None
        if (
            self.num_blocks != self.cfg.num_hidden_layers
            or self.first_block != 0
            or self.num_hosts > 1
        ):
            return None
        try:
            from petals_tpu.client.from_pretrained import load_client_params

            params = load_client_params(
                self.model_path, dtype=jnp.float32,
                family=self.family, cfg=self.cfg,
            )
            logger.info("Server-side generation enabled (client leaves loaded)")
            return params
        except Exception as e:
            logger.warning(f"Server-side generation disabled: {e}")
            return None

    def _make_raw_backend(self, stacked, first_block: int) -> TransformerBackend:
        """Backend construction WITHOUT the lockstep wrap (the live span move
        rebuilds raw backends under the broadcast lock and re-wraps itself)."""
        mesh = None
        tp = self.num_tp_devices or 1
        sp = self.num_sp_devices or 1
        # after partial re-formation, jax.devices() STILL lists the dead
        # members' chips (jax.distributed stays initialized); meshes must be
        # built from this host's devices only
        devices = jax.local_devices() if self._local_devices_only else None
        if self.num_hosts > 1:
            from petals_tpu.parallel.multihost import multihost_mesh

            # tp (x sp) over the GLOBAL device set (all hosts' chips);
            # num_tp_devices None means every device in the group divided by sp
            mesh = multihost_mesh(self.num_tp_devices, sp)
        elif sp > 1:
            from petals_tpu.parallel.mesh import serving_mesh

            mesh = serving_mesh(tp, sp, devices=devices)
        elif tp > 1:
            from petals_tpu.parallel.mesh import tp_mesh

            mesh = tp_mesh(tp, devices=devices)
        return TransformerBackend(
            self.family,
            self.cfg,
            stacked,
            first_block=first_block,
            n_blocks=self.num_blocks,
            memory_cache=self.memory_cache,
            compute_dtype=self.compute_dtype,
            max_chunk_size_bytes=self.max_chunk_size_bytes,
            use_flash=self.use_flash,
            mesh=mesh,
            kv_quant_type=self.kv_quant_type,
        )

    def _make_backend(self, stacked, first_block: int) -> TransformerBackend:
        backend = self._make_raw_backend(stacked, first_block)
        if self.num_hosts > 1:
            from petals_tpu.parallel.multihost import LockstepBackend

            backend = LockstepBackend(backend)
        return backend

    async def _choose_start_block(self, throughputs=None) -> int:
        """Pick the span covering the swarm's weakest blocks (reference
        server.py:403-418 via block_selection)."""
        import numpy as np

        from petals_tpu.data_structures import make_uid as _mk
        from petals_tpu.server.block_selection import choose_best_start, compute_throughputs
        from petals_tpu.utils.dht_utils import get_remote_module_infos

        if throughputs is None:
            all_uids = [_mk(self.dht_prefix, i) for i in range(self.cfg.num_hidden_layers)]
            infos, _ = await get_remote_module_infos(self.dht, all_uids)
            throughputs = compute_throughputs(infos, exclude_peer=self.dht.peer_id)
        return choose_best_start(np.asarray(throughputs), self.num_blocks)

    async def _balance_loop(self) -> None:
        """Periodically re-evaluate placement and move if the swarm would gain
        (reference server.py:369-384 rebalance loop)."""
        import random as _random

        from petals_tpu.data_structures import make_uid as _mk
        from petals_tpu.server.block_selection import should_choose_other_blocks
        from petals_tpu.utils.dht_utils import get_remote_module_infos

        while True:
            await asyncio.sleep(self.mean_balance_check_period * (0.5 + _random.random()))
            try:
                all_uids = [_mk(self.dht_prefix, i) for i in range(self.cfg.num_hidden_layers)]
                infos, _ = await get_remote_module_infos(self.dht, all_uids)
                if should_choose_other_blocks(
                    self.dht.peer_id, infos, self.num_blocks,
                    balance_quality=self.balance_quality,
                ):
                    from petals_tpu.server.block_selection import compute_throughputs

                    throughputs = compute_throughputs(infos, exclude_peer=self.dht.peer_id)
                    new_start = await self._choose_start_block(throughputs)
                    if new_start != self.first_block:
                        logger.info(f"Rebalancing: moving span to start at block {new_start}")
                        await self._reload_span(new_start)
            except Exception as e:
                logger.warning(f"Balance check failed: {e}")

    async def resize(self, new_first_block: int) -> bool:
        """Autoscaler actuator: move this server's span to start at
        ``new_first_block`` (same span length), migrating live sessions to
        replicas first. A no-op (returns False) when already there; raises
        ValueError on an out-of-range target so a bad policy decision fails
        loudly instead of announcing blocks that do not exist."""
        if not 0 <= new_first_block <= self.cfg.num_hidden_layers - self.num_blocks:
            raise ValueError(
                f"resize target {new_first_block} outside "
                f"[0, {self.cfg.num_hidden_layers - self.num_blocks}]"
            )
        if new_first_block == self.first_block:
            return False
        logger.info(f"Resize: moving span to start at block {new_first_block}")
        await self._reload_span(new_first_block)
        return True

    async def _reload_span(self, new_first_block: int) -> None:
        """Move to a new span: announce OFFLINE on the old blocks, reload, and
        re-register (reference ModuleContainer restart, server.py:369-384)."""
        old_uids = self.module_uids
        try:
            await declare_active_modules(
                self.dht, old_uids, self._server_info(ServerState.OFFLINE), dht_time() + 60
            )
        except Exception as e:
            # best-effort: stale entries expire; the reload must not abort here
            logger.debug("OFFLINE announce before span reload failed: %r", e)
        self.first_block = new_first_block
        self.module_uids = [
            make_uid(self.dht_prefix, i)
            for i in range(self.first_block, self.first_block + self.num_blocks)
        ]
        self._state = ServerState.JOINING  # the announce loop must NOT say ONLINE yet
        await self._announce(ServerState.JOINING)

        if self.num_hosts > 1:
            # LIVE SPAN MOVE for a lockstep group (round 5; previously moves
            # required restarting every member). Quiesce first: park live
            # sessions (their owners migrate via ptu.session_export — the
            # parked copies are host RAM, they survive the move), refuse new
            # compute, and barrier the priority queue so every in-flight op's
            # broadcasts are done. Then one OP_RELOAD_SPAN broadcast rebuilds
            # leader + workers from the checkpoint SIMULTANEOUSLY — the
            # sharded-param device_puts are collectives that pair exactly
            # like at startup, and the broadcast lock (held around the whole
            # rebuild) keeps any other collective from interleaving.
            from petals_tpu.server.task_queue import PRIORITY_BARRIER

            if self.handler is None:
                raise RuntimeError("live span move before the server started serving")
            try:
                await self.handler.park_sessions(ttl=60.0)
                # rebalance-migrate: the new span can't serve the old span's
                # KV, so hand it to replicas that can (best-effort; failures
                # leave the parked copy for client-side export)
                await self._migrate_parked_sessions()
                self.handler.draining = True
                await self.handler.queue.submit(
                    lambda: None, priority=PRIORITY_BARRIER, size=0
                )

                def build_raw():
                    stacked = self._load_span_params(self.first_block, self.num_blocks)
                    return self._make_raw_backend(stacked, self.first_block)

                old_backend = self.backend
                self.backend = await asyncio.get_running_loop().run_in_executor(
                    None, lambda: old_backend.reload_span(self.first_block, build_raw)
                )
                self._install_adapters(self.backend)
                await self.handler.swap_backend(self.backend)
            finally:
                # NEVER leave the server permanently refusing sessions: if the
                # move failed post-broadcast the group is degraded and ops
                # fail through _check_group with a clear error anyway
                self.handler.draining = False
        else:
            if self.handler is not None:
                # park + migrate BEFORE the batcher rebuild kills pooled
                # sessions: rebalance used to be a session-killer (clients
                # replayed their whole prefix); now their KV moves to a
                # replica and the repair is a redirect + kv_adopt
                try:
                    if await self.handler.park_sessions(ttl=60.0):
                        await self._migrate_parked_sessions()
                except Exception as e:
                    logger.warning(f"Rebalance-migrate failed (sessions will replay): {e!r}")
            stacked = await asyncio.get_running_loop().run_in_executor(
                None, self._load_span_params, self.first_block, self.num_blocks
            )
            # Build a FRESH backend: open PRIVATE sessions keep their reference
            # to the old one (consistent old-span compute until they close);
            # pooled sessions are invalidated by the batcher rebuild inside
            # swap_backend (the shared lane pool cannot serve two spans). The
            # constructor also re-applies TP sharding for mesh servers.
            self.backend = self._make_backend(stacked, self.first_block)
            self._install_adapters(self.backend)
            await self.handler.swap_backend(self.backend)
        # stale by construction: measured for the OLD span's successor block;
        # the announce loop re-measures for the new span within one period
        self._next_pings = {}
        self._state = ServerState.ONLINE
        await self._announce(ServerState.ONLINE)

    async def _announce_loop(self) -> None:
        while True:
            await asyncio.sleep(self.update_period)
            try:
                if self.num_hosts > 1 and await self._check_group_health():
                    return  # degraded: final OFFLINE announce already sent
                await self._measure_next_pings()
                await self._announce(self._state)
            except Exception as e:
                logger.warning(f"Announce failed: {e}")

    async def _check_group_health(self) -> bool:
        """Multi-host worker-death detection: when a lockstep op has degraded
        the group (a member died mid-collective), stop accepting sessions and
        go OFFLINE so clients fail over NOW — in-flight sessions already got
        clean MultihostDegraded errors from their steps. Then PARTIALLY
        RE-FORM (round 5): the surviving leader falls back to single-host
        serving — possibly a shorter span — with no process restarted; only
        the dead worker needs a replacement (which joins a future group).
        Returns True once degraded (the announce loop then stops; a
        successful re-formation starts a fresh one)."""
        from petals_tpu.parallel.multihost import group_degraded

        err = group_degraded()
        if err is None:
            return False
        logger.error(
            f"multihost group degraded ({err!r}): draining, going OFFLINE, "
            f"then re-forming single-host from the checkpoint"
        )
        if self.handler is not None:
            self.handler.draining = True
        self._state = ServerState.OFFLINE
        await self._announce(ServerState.OFFLINE)
        try:
            await self._reform_single_host()
        except Exception as e:
            logger.exception(
                f"single-host re-formation failed ({e!r}); staying OFFLINE — "
                f"restart the leader and workers to re-form the group"
            )
            # the reform may have died after its JOINING announce: the
            # swarm's final view of this peer must be OFFLINE, not 'coming
            # online soon'
            self._state = ServerState.OFFLINE
            with contextlib.suppress(Exception):
                await self._announce(ServerState.OFFLINE, expiration=dht_time() + 60)
            return True  # the announce loop stops; operator intervention needed
        # re-formed: num_hosts is now 1, so this health check disarms itself
        # and the announce loop keeps running for the single-host server
        return False

    async def _reform_single_host(self) -> None:
        """Partial re-formation after losing a lockstep group member
        (VERDICT r4 #4, elasticity spirit of reference server.py:369-384,
        which restarts only the module container — not the swarm's other
        members). XLA bakes the group mesh into every compiled program and
        shards params across member processes, so the OLD backend is
        unrecoverable by construction; what survives is this process, its
        DHT identity, its listening address, and the swarm's view of it.
        The leader therefore rebuilds a LOCAL backend from the checkpoint
        (shrinking the span if this host alone cannot hold it), swaps in a
        fresh memory cache + handler on the SAME RpcServer, and re-announces.
        Clients of the old group failover through the normal banned-peer
        path and find the re-formed server at the same address."""
        # the dead member can never join jax's exit-time shutdown barrier;
        # without this the interpreter-exit hook aborts the process (FATAL)
        import atexit

        try:
            import jax as _jax

            atexit.unregister(_jax.distributed.shutdown)
        except Exception:  # swarmlint: disable=no-silent-except — probing a version-dependent private hook: absence means there is nothing to unregister
            pass

        # local compute shape: the sp axis spanned the group, so locally it
        # re-forms as plain tp over this host's chips (a future replacement
        # group re-enables sp); tp=1 retry below if the local width doesn't
        # divide the model (kv-head divisibility was only checked for the
        # group width)
        n_local = len(jax.local_devices())
        group_devices = max(jax.device_count(), 1)
        local_tp = n_local if n_local > 1 else 1
        self.num_sp_devices = None
        self.num_tp_devices = local_tp if local_tp > 1 else None

        # shrink the span if one host cannot hold what the group held;
        # choose_num_blocks sizes ONE chip, and local tp shards params over
        # local_tp chips, so capacity scales with the width actually used
        from petals_tpu.server.block_utils import choose_num_blocks

        old_num = self.num_blocks
        try:
            max_local = choose_num_blocks(
                self.family, self.cfg, quant_type=self.quant_type,
                attn_cache_bytes=self.attn_cache_bytes or 0,
            ) * local_tp
        except Exception as e:
            logger.warning("Local capacity estimate failed, keeping span size: %r", e)
            max_local = old_num
        self.num_blocks = max(1, min(old_num, max_local))
        self.module_uids = [
            make_uid(self.dht_prefix, i)
            for i in range(self.first_block, self.first_block + self.num_blocks)
        ]
        if self.num_blocks != old_num:
            logger.warning(
                f"re-formation shrinks the span to [{self.first_block}, "
                f"{self.first_block + self.num_blocks}) — one host cannot "
                f"hold the group's {old_num} blocks"
            )
        self._state = ServerState.JOINING
        await self._announce(ServerState.JOINING)

        self.num_hosts = 1  # _make_backend now builds a local (non-lockstep) backend
        self._local_devices_only = True  # jax.devices() still lists dead members
        stacked = await asyncio.get_running_loop().run_in_executor(
            None, self._load_span_params, self.first_block, self.num_blocks
        )
        try:
            self.backend = self._make_backend(stacked, self.first_block)
        except Exception as e:
            if (self.num_tp_devices or 1) > 1:
                logger.warning(f"local tp={self.num_tp_devices} mesh failed ({e!r}); re-forming tp=1")
                self.num_tp_devices = None
                self.backend = self._make_backend(stacked, self.first_block)
            else:
                raise
        self._install_adapters(self.backend)
        # fresh budget: the old (Lockstep-wrapped) cache's mirrors died with
        # the workers; old sessions already got their clean errors
        old_handler = self.handler
        self.memory_cache = MemoryCache(
            self.attn_cache_bytes, max_alloc_timeout=self.max_alloc_timeout
        )
        self.handler = self._make_handler()
        self.handler.register(self.rpc_server)  # replaces the old registrations
        if old_handler is not None:
            with contextlib.suppress(Exception):
                old_handler.shutdown()
        self._next_pings = {}
        # the announced throughput was measured for the GROUP's devices;
        # rescale conservatively by the width this host keeps so routing
        # doesn't over-prefer the degraded server (a fresh probe would be
        # more precise — the rescale is honest enough until the operator's
        # replacement group re-measures)
        used = min(local_tp, n_local)
        if group_devices > used:
            self.throughput = self.throughput * used / group_devices
            logger.info(
                f"throughput rescaled {group_devices}->{used} devices: "
                f"{self.throughput:.2f}"
            )
        self._state = ServerState.ONLINE
        # everything destructive already succeeded: a transient announce
        # failure must NOT mark the healthy re-formed server failed — the
        # announce loop retries every update_period
        try:
            await self._announce(ServerState.ONLINE)
        except Exception as e:
            logger.warning(f"post-reform ONLINE announce failed ({e!r}); the announce loop will retry")
        logger.info(
            f"re-formed single-host: serving {self.module_uids} at "
            f"{self.contact_addr.to_string()}"
        )

    async def _resolve_network_mbps(self):
        network_mbps = self.network_mbps
        if network_mbps is None and self.initial_peers:
            # measure the real path to swarm peers (utils/bandwidth.py) —
            # the speedtest-cli role; falls back to the loopback stack probe
            from petals_tpu.dht.routing import PeerAddr
            from petals_tpu.utils.bandwidth import probe_swarm_bandwidth_mbps

            peer_addrs = [
                p if isinstance(p, PeerAddr) else PeerAddr.from_string(p)
                for p in self.initial_peers
            ]
            network_mbps = await probe_swarm_bandwidth_mbps(self.dht.pool, peer_addrs)
        return network_mbps

    async def _measure_multihost_throughput(self) -> None:
        """Auto-throughput for multi-host spans (v2): probe the REAL lockstep
        backend — every op broadcasts, so the workers mirror the probe exactly
        like serving traffic. Measures the whole span (already 'per num_blocks'),
        never disk-cached (the number belongs to this group composition)."""
        import time as _time

        from petals_tpu.server.throughput import RELAY_PENALTY, measure_network_rps

        cfg = self.cfg
        rng = np.random.RandomState(0)
        step_h = rng.randn(1, 1, cfg.hidden_size).astype(np.float32) * 0.01
        # 1024-token forwards: the SAME basis as the single-host probe
        # (throughput.py measure_compute_rps) — announced numbers must be
        # comparable across servers or routing deprioritizes multi-host spans
        fwd_h = rng.randn(1, 1024, cfg.hidden_size).astype(np.float32) * 0.01

        descriptors = self.backend.cache_descriptors(1, 64, 0, self.num_blocks)
        async with self.memory_cache.allocate_cache(*descriptors) as handles:
            kv = tuple(self.memory_cache.get_buffers(*handles))

            def probe():
                nonlocal kv
                out, kv2 = self.backend.inference_step(step_h, kv, 0, handles=handles)
                np.asarray(out)
                pos, n = 1, 20
                t0 = _time.perf_counter()
                for _ in range(n):
                    out, kv2 = self.backend.inference_step(step_h, kv2, pos, handles=handles)
                    pos += 1
                np.asarray(out)
                inference_rps = n / (_time.perf_counter() - t0)
                np.asarray(self.backend.forward(fwd_h))  # compile
                t0 = _time.perf_counter()
                for _ in range(3):
                    np.asarray(self.backend.forward(fwd_h))
                forward_rps = 3 * fwd_h.shape[1] / (_time.perf_counter() - t0)
                return inference_rps, forward_rps

            # lockstep ops block on collectives: keep the event loop free
            inference_rps, forward_rps = await asyncio.get_running_loop().run_in_executor(
                None, probe
            )
        network_mbps = await self._resolve_network_mbps()
        network_rps = measure_network_rps(cfg.hidden_size, network_mbps=network_mbps)
        if self.relay_via is not None:
            network_rps *= RELAY_PENALTY
        # the span probe already spreads compute over num_blocks blocks
        self.throughput = min(forward_rps, network_rps)
        self._rps_info = {
            "throughput": self.throughput,
            "inference_rps": inference_rps,
            "forward_rps": forward_rps,
            "network_rps": network_rps,
        }
        logger.info(f"multihost auto-throughput: {self._rps_info}")

    async def _measure_next_pings(self) -> None:
        """Ping the servers that could follow us in an inference chain — those
        serving our end block — and stage their RTTs for the next announce
        (reference server.py:717-751: min-latency routing is half-blind to
        multi-hop chains without these inter-server edges)."""
        if self._ping_aggregator is None:
            return
        next_block = self.first_block + self.num_blocks
        if next_block >= self.cfg.num_hidden_layers:
            self._next_pings = {}
            return
        try:
            from petals_tpu.utils.dht_utils import get_remote_module_infos
            from petals_tpu.utils.random_utils import sample_up_to

            infos, addr_book = await get_remote_module_infos(
                self.dht, [make_uid(self.dht_prefix, next_block)]
            )
            if not infos or infos[0] is None:
                self._next_pings = {}
                return
            own = self.dht.peer_id
            candidates = [
                addr_book[pid]
                for pid, si in infos[0].servers.items()
                # OFFLINE/JOINING announcements linger until expiry; pinging
                # them would crowd live successors out of the sample
                if pid != own and pid in addr_book and si.state == ServerState.ONLINE
            ]
            candidates = sample_up_to(candidates, 10)
            if candidates:
                await asyncio.wait_for(self._ping_aggregator.ping(candidates), 10.0)
            candidate_ids = {addr.peer_id for addr in candidates}
            self._next_pings = {
                pid.to_string(): rtt
                for pid, rtt in self._ping_aggregator.to_dict().items()
                if pid in candidate_ids and math.isfinite(rtt)
            }
        except Exception as e:
            logger.debug(f"next_pings round failed: {e}")
