"""Continuous batching: coalesce concurrent decode sessions into one step.

The reference explicitly never batches across requests — its task pools note
"there is no batching" (reference src/petals/server/task_pool.py:35-36), so a
server's aggregate decode throughput equals single-stream throughput. On TPU
that wastes the hardware: decode is weight-bandwidth-bound, so stepping 8
sessions in one program costs barely more than stepping one (the measured
batch-8 step is ~1.4x the batch-1 step for 8x the tokens).

TPU-first design — a LANE pool, not a page table:

- One shared KV pool [n_blocks, n_lanes, max_len, kv_heads, head_dim] x2,
  budgeted through MemoryCache like any session cache. Each session borrows a
  LANE for its lifetime; sessions at different decode depths coexist via a
  per-lane position vector (models/common.py absolute_positions).
- Every batched step runs the SAME compiled program over the whole pool —
  static shapes, so sessions joining/leaving NEVER recompile (XLA's one-trace
  model makes vLLM-style dynamic page tables recompile-hostile; decode reads
  the whole masked buffer either way, so lane-granularity loses no bandwidth,
  it only rounds memory up to max_len per active session).
- Idle lanes ride along with position = max_len (the out-of-range sentinel):
  their KV writes are dropped by the scatter, their outputs ignored.
- Non-batchable work on a pooled session (chunked prefill, kv import/export)
  extracts the lane into session-shaped buffers, runs the normal path, and
  inserts it back — all under the server's priority queue, so it serializes
  with batched steps.

Scheduling: greedy coalescing, no timers. Step requests accumulate while the
current device step runs; the flush loop drains whatever is pending into the
next step. Single-stream latency is untouched (a lone request flushes
immediately); concurrent sessions batch automatically.
"""

from __future__ import annotations

import asyncio
import contextlib
import dataclasses
import itertools
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from petals_tpu.analysis.sanitizer import (
    lock_try_acquire_nowait,
    make_async_lock,
    make_thread_lock,
)
from petals_tpu.utils.locks import AsyncTryLock
from petals_tpu.data_structures import SESSION_PRIORITY_NORMAL
from petals_tpu.ops.sampling import sampling_vectors
from petals_tpu.server.memory_cache import (
    AllocationFailed,
    HostSwapPool,
    MemoryCache,
    PageAllocator,
)
from petals_tpu.server.scheduler import SessionScheduler, SwapEntry
from petals_tpu.server.spec_decode import min_accept_floor
from petals_tpu.server.task_queue import PRIORITY_INFERENCE, PriorityTaskQueue
from petals_tpu.telemetry import get_journal
from petals_tpu.telemetry import instruments as tm
from petals_tpu.utils.asyncio_utils import log_exception_callback
from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclasses.dataclass
class _LaneGenState:
    """Host-side bookkeeping for one lane mid server-side generation: the
    flush loop advances every registered lane by one token per batched step
    (feeding ``token`` at ``position``) until ``remaining`` hits zero, then
    resolves ``future`` with the collected stream."""

    future: asyncio.Future
    generation: int
    token: int  # last sampled token — fed on the next step
    position: int  # cache write position for that next step
    remaining: int  # decode steps left (n_tokens - 1 at start)
    collected: List[int]
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    repetition_penalty: float = 1.0
    seed: int = 0
    draw_idx: int = 0
    seen: Optional[np.ndarray] = None  # [vocab] bool; only when penalty active
    # per-hop latency attribution (handler step_meta): admission time, first
    # queue wait, and cumulative compiled-step time across the stream
    enqueued: float = 0.0  # time.perf_counter() at registration
    started: bool = False  # first batched step already recorded the wait
    queue_s: float = 0.0
    compute_s: float = 0.0
    # speculative decoding (server/spec_decode.py): prompt context for the
    # draft's window, the per-lane acceptance-rate EMA driving auto-disable,
    # the cooldown (plain-decode ticks left after a disable), and lifetime
    # proposed/accepted counts for the stream's step_meta
    context: Optional[List[int]] = None
    spec_ema: float = 1.0  # optimistic start: new lanes get to speculate
    spec_cooldown: int = 0
    spec_proposed: int = 0
    spec_accepted: int = 0


@dataclasses.dataclass
class _LanePrefillState:
    """Host-side bookkeeping for one lane's admitted prefill: the flush loop
    feeds one bucketed chunk per mixed step (round-robin across admitted
    prefills, bounded by the per-tick token budget) until ``offset`` reaches
    the full length, then resolves ``future`` with the concatenated span
    outputs. Pages for the WHOLE range were prepared at admission, so the
    flush loop never blocks on allocation mid-prefill."""

    future: asyncio.Future
    generation: int
    lane: int
    hidden: np.ndarray  # [1, total, hidden] host-side
    position: int  # absolute position of the next unfed token
    offset: int  # tokens already fed
    cap: int  # per-step chunk cap (chunk_plan byte sizing)
    n_total: int  # final sequence length (longrope factor selection)
    outs: List[np.ndarray]
    enqueued: float = 0.0  # time.perf_counter() at admission (queue-wait metric)
    wait_observed: bool = False  # first chunk already recorded the queue wait
    queue_s: float = 0.0  # admission -> first chunk (handler step_meta)
    compute_s: float = 0.0  # cumulative mixed-step wall across chunks


@dataclasses.dataclass
class _LaneWaiter:
    """One parked acquire_lane caller. Admission order is a POLICY decision
    (scheduler.pick_waiter): priority class first, then per-peer fair share,
    then ``seq`` — which alone reproduces the old FIFO at default priority."""

    fut: asyncio.Future
    priority: int
    peer_id: Optional[str]
    seq: int
    # request trace id (telemetry.trace): pre-admission, so the scheduler
    # slot doesn't exist yet — the waiter carries it for journal events
    trace_id: Optional[str] = None


class DecodeBatcher:
    """Shared-pool continuous batcher for one backend (one span of blocks)."""

    def __init__(
        self,
        backend,
        memory_cache: MemoryCache,
        queue: PriorityTaskQueue,
        *,
        n_lanes: int = 8,
        max_length: int = 1024,
        alloc_timeout: Optional[float] = None,
        gen_params=None,  # full-model client leaves: enables pooled server-gen
        page_size: Optional[int] = None,  # None/0 -> dense lane pool (legacy)
        n_pages: Optional[int] = None,  # default: n_lanes * max_pages (no oversub)
        prefill_token_budget: int = 512,  # max prefill-chunk tokens per mixed step
        swap_host_bytes: int = 0,  # host-RAM KV swap tier; 0 -> no preemption
        preemption_policy: str = "lru",  # lru | largest | off
        ledger=None,  # telemetry.ledger.ResourceLedger; None -> process singleton
        draft_model=None,  # server.spec_decode.DraftModel; enables spec decode
        spec_k: Optional[int] = None,  # drafts per lane per tick; None -> draft's k
    ):
        self.backend = backend
        self.memory_cache = memory_cache
        self.queue = queue
        self.n_lanes = n_lanes
        self.max_length = max_length
        self.alloc_timeout = alloc_timeout
        self.gen_params = gen_params
        # paged KV mode: the pool becomes [n_blocks, n_pages, page_size, ...]
        # and lanes address it through per-lane block tables. Gated off under
        # lockstep (the paged programs are single-host) and TP meshes (the
        # page axis is unsharded); those keep the dense lane pool.
        lockstep = bool(getattr(backend, "is_lockstep", False))
        if page_size and not lockstep and getattr(backend, "mesh", None) is None:
            self.page_size: Optional[int] = int(page_size)
            # round the lane capacity UP to whole pages so tables tile exactly
            self.max_length = -(-int(max_length) // self.page_size) * self.page_size
            self.max_pages = self.max_length // self.page_size
            self.n_pages = int(n_pages) if n_pages else self.n_lanes * self.max_pages
            if self.n_pages < self.max_pages:
                raise ValueError(
                    f"n_pages={self.n_pages} cannot hold even one full lane "
                    f"({self.max_pages} pages of {self.page_size} tokens)"
                )
        else:
            self.page_size = None
            self.max_pages = 0
            self.n_pages = 0
        self._pages: Optional[PageAllocator] = None
        self._tables: Optional[np.ndarray] = None  # [n_lanes, max_pages] int32, -1 = unallocated
        # cached tables_are_contiguous result for the stats/debug surface
        # (paged_summary); None = recompute on next read. The STEP path no
        # longer consults it — the fused kernel serves identity and permuted
        # tables alike — so the O(n_lanes*max_pages) scan runs only when the
        # tables actually changed AND someone asks (rpc_info), not per tick.
        self._tables_contig: Optional[bool] = None
        # bumped on every pool reset: prefix-cache page pins carry the epoch
        # they were taken under so stale pins never decref a rebuilt allocator
        self._page_epoch = 0
        # lanes currently running server-side generation: advanced one token
        # per flush-loop iteration alongside (and batched WITH) ordinary
        # per-token decode traffic
        self._gen_states: Dict[int, _LaneGenState] = {}
        # paged-lane prefills admitted into the MIXED step (prefill_lane):
        # one bucketed chunk rides each flush tick, round-robin, so decode
        # lanes keep stepping while prefills stream in
        self._prefill_queue: List[_LanePrefillState] = []
        self.prefill_token_budget = max(int(prefill_token_budget), 1)
        # speculative decoding (server/spec_decode.py): with a draft model
        # loaded, eligible gen lanes move onto the draft-verify path — k
        # drafts verified in ONE paged step per tick, up to k+1 tokens
        # committed. Paged pool only (verification rides the chunk-scatter
        # machinery); requires gen_params (the verify program embeds/samples
        # with the client leaves). spec_k must match the draft's compiled k.
        self.draft = draft_model
        self.spec_k = int(spec_k if spec_k is not None
                          else getattr(draft_model, "spec_k", 0) or 0)
        if draft_model is not None:
            draft_k = int(getattr(draft_model, "spec_k", self.spec_k))
            if self.spec_k != draft_k:
                raise ValueError(
                    f"spec_k={self.spec_k} does not match the draft model's "
                    f"compiled k={draft_k}"
                )
            if gen_params is None:
                raise ValueError(
                    "Speculative decoding needs the client leaves loaded "
                    "(gen_params): the verify step embeds and samples on device"
                )
        # the draft instance whose bucket shapes have been pre-compiled via
        # DraftModel.warmup (first spec tick, on the compute thread); keyed
        # on the object so a swapped-in draft re-warms
        self._draft_warmed = None
        # per-lane acceptance EMA auto-disable: a lane whose EMA drops below
        # the floor falls back to plain decode for a cooldown window (both
        # journaled as 'spec_disabled' with the EMA evidence)
        self._spec_min_accept = min_accept_floor()
        self._spec_ema_alpha = 0.2
        try:
            self._spec_cooldown_ticks = max(
                int(os.environ.get("PETALS_TPU_SPEC_COOLDOWN", 64)), 1
            )
        except ValueError:
            self._spec_cooldown_ticks = 64

        self._pool_stack: Optional[contextlib.AsyncExitStack] = None
        self._handles = None
        # a failed donating step can consume the pool buffers; recovery zeros
        # the pool and bumps the generation so every OUTSTANDING lane is
        # invalidated (its KV is gone — silently serving zeros would corrupt
        # every tenant token-by-token)
        self._generation = 0
        # makes the compute thread's post-step generation-check + buffer swap
        # atomic w.r.t. the event loop's reset (check-then-update alone is a
        # TOCTOU: a reset landing between them would be overwritten)
        self._reset_lock = make_thread_lock("batching._reset_lock")
        self._lane_generation: Dict[int, int] = {}
        self._free_lanes: List[int] = []
        self._lane_waiters: List[_LaneWaiter] = []
        self._waiter_seq = itertools.count()
        self._pending: List[tuple] = []  # (lane, hidden, position, future, generation)
        # per-hop latency attribution (handler step_meta): admission time of
        # the in-flight step per lane, and the finished step's queue/compute
        # split for the handler to pop after the future resolves. Plain dict
        # ops (GIL-atomic) — one step in flight per lane (_lane_busy), so the
        # event loop and compute thread never race on the same key.
        self._enq_t: Dict[int, float] = {}
        self._step_timing: Dict[int, dict] = {}
        # integrity fingerprints of the in-flight step per lane (ops/
        # fingerprint.py, fused into the batched programs) — same
        # single-writer discipline as _step_timing
        self._step_fp: Dict[int, list] = {}
        # session scheduler: priority + per-peer fair-share admission, and (in
        # paged mode with swap_host_bytes > 0) preemption of idle victim lanes
        # to the host-RAM swap tier on pool exhaustion. With the default
        # swap_host_bytes=0 no lane ever suspends and a full pool keeps the
        # exact waiter-backpressure/AllocationFailed behavior of PR 2.
        self.swap_pool = HostSwapPool(int(swap_host_bytes or 0))
        # per-tenant resource ledger (telemetry.ledger): page-seconds with
        # fractional COW attribution, compute-seconds, tokens, swap bytes —
        # settled at the same boundaries where _note_occupancy runs. Its
        # dominant-resource share feeds the scheduler's fair-share admission
        # and victim tie-breaks in place of the raw lanes-held count.
        if ledger is None:
            from petals_tpu.telemetry.ledger import get_ledger

            ledger = get_ledger()
        self._ledger = ledger
        # price the pool for /ledger readers: wire bytes per cached token
        # (quantized pools cost ~4x less) and the storage kind. Guarded by
        # hasattr because unit-test stub backends/ledgers lack the accessors.
        if hasattr(backend, "kv_bytes_per_token") and hasattr(ledger, "set_kv_cost"):
            ledger.set_kv_cost(
                getattr(backend, "kv_quant_type", "none"),
                backend.kv_bytes_per_token(),
            )
        self._ledger_keys: Dict[int, str] = {}  # lane -> ledger session key
        self._scheduler = SessionScheduler(
            self.swap_pool, policy=preemption_policy, pages_fn=self._lane_pages,
            usage_fn=ledger.peer_dominant_share,
        )
        # per-lane asyncio locks serializing swap-out against swap-in, and an
        # in-flight op counter making lanes with ANY active work unpreemptable
        self._lane_locks: Dict[int, AsyncTryLock] = {}
        self._inflight: Dict[int, int] = {}
        # swap-ins serialize through this fair (FIFO-wakeup) lock: N resumers
        # racing _alloc_pages would each grab pages the others need and an
        # unlucky one could starve past its timeout; one-at-a-time, the head
        # gets every freed page and provably drains the queue
        self._swap_in_turnstile = make_async_lock("batching._swap_in_turnstile")
        self._flush_task: Optional[asyncio.Task] = None
        self._open_lock = make_async_lock("batching._open_lock")
        self._closed = False
        # multi-host lockstep (parallel/multihost.py): lane ops broadcast so
        # every process mirrors the pool; extracted lanes live on workers as
        # synthetic NEGATIVE-handle mirrors minted here (never colliding with
        # MemoryCache's non-negative handles)
        self._lockstep = bool(getattr(backend, "is_lockstep", False))
        self._temp_ids = itertools.count(-2, -1)
        # observability + tests: how many device steps served how many tokens.
        # EVERY key is pre-initialized — rpc_info spreads this dict into the
        # health summary, and lazily created keys made the schema depend on
        # which code paths had run
        self.stats = {
            "batched_steps": 0, "batched_tokens": 0, "max_batch": 0,
            "gen_steps": 0, "gen_lane_tokens": 0, "max_gen_lanes": 0,
            "exclusive_chunks": 0, "prefill_tokens": 0, "mixed_steps": 0,
            "max_prefill_tokens_per_step": 0,
            "spec_steps": 0, "spec_proposed": 0, "spec_accepted": 0,
            "spec_disabled": 0, "max_spec_lanes": 0,
        }
        # swarm telemetry plane: every admission / victim-selection / swap
        # decision is journaled WITH the occupancy snapshot that justified it
        # (telemetry.journal), and the pool gauges/counters feed the /metrics
        # endpoint + the announce digest
        self._journal = get_journal()

    # ------------------------------------------------------------------ pool

    @property
    def is_open(self) -> bool:
        return self._handles is not None

    async def ensure_open(self, timeout: Optional[float] = None) -> None:
        """Allocate the pool on first use (budgeted through MemoryCache).
        ``timeout`` bounds the budget wait — callers on the session-open path
        must be able to fall back to a private cache promptly instead of
        hanging on a full cache."""
        async with self._open_lock:
            if self._handles is not None or self._closed:
                return
            # descriptors come from the backend so the pool carries the same
            # sharding as session caches (kv-head axis over the tp mesh) —
            # under lockstep the workers mirror the alloc with the identical
            # sharded descriptors, and materialization is a collective every
            # process must enter with the SAME specs (an unsharded leader
            # pool would deadlock the group at open)
            if self.page_size is not None:
                # 2 descriptors (k, v) unquantized; 4 (k/v codes, k/v scales)
                # when the backend stores the pool quantized
                descs = self.backend.paged_cache_descriptors(
                    self.n_pages, self.page_size, 0, self.backend.n_blocks
                )
            else:
                descs = self.backend.cache_descriptors(
                    self.n_lanes, self.max_length, 0, self.backend.n_blocks
                )
            stack = contextlib.AsyncExitStack()
            try:
                handles = await stack.enter_async_context(
                    self.memory_cache.allocate_cache(
                        *descs,
                        timeout=self.alloc_timeout if timeout is None else timeout,
                    )
                )
            except BaseException:
                await stack.aclose()
                raise
            self._pool_stack = stack
            self._handles = handles
            self._free_lanes = list(range(self.n_lanes))
            if self.page_size is not None:
                self._pages = PageAllocator(self.n_pages)
                self._tables = np.full((self.n_lanes, self.max_pages), -1, np.int32)
                self._tables_mutated()
                logger.info(
                    f"Paged-batching pool open: {self.n_pages} pages x "
                    f"{self.page_size} tokens ({self.n_lanes} lanes x "
                    f"{self.max_pages} table slots) for blocks "
                    f"[{self.backend.first_block}, {self.backend.first_block + self.backend.n_blocks})"
                )
            else:
                logger.info(
                    f"Continuous-batching pool open: {self.n_lanes} lanes x "
                    f"{self.max_length} tokens for blocks "
                    f"[{self.backend.first_block}, {self.backend.first_block + self.backend.n_blocks})"
                )

    async def close(self) -> None:
        self._closed = True
        for w in self._lane_waiters:
            if not w.fut.done():
                w.fut.set_exception(AllocationFailed("Batcher is shutting down"))
        self._lane_waiters.clear()
        self._scheduler.reset()  # drop swap entries, release their host bytes
        for st in self._gen_states.values():
            if not st.future.done():
                st.future.set_exception(AllocationFailed("Batcher is shutting down"))
        self._gen_states.clear()
        for pst in self._prefill_queue:
            if not pst.future.done():
                pst.future.set_exception(AllocationFailed("Batcher is shutting down"))
        self._prefill_queue.clear()
        if self._pool_stack is not None:
            await self._pool_stack.aclose()
            self._pool_stack = None
            self._handles = None

    def _buffers(self):
        """The (k_pool, v_pool) pair every step/compute path consumes. A
        quantized pool rides as 4 MemoryCache buffers (codes x2, scales x2)
        and is re-wrapped into PagedPool pytrees HERE, so every caller —
        step bodies, swap, COW, snapshots — keeps the 2-tuple shape."""
        bufs = self.memory_cache.get_buffers(*self._handles)
        if len(bufs) == 4:
            from petals_tpu.ops.paged_attention import PagedPool

            return PagedPool(bufs[0], bufs[2]), PagedPool(bufs[1], bufs[3])
        return bufs

    def _update(self, k_pool, v_pool) -> None:
        from petals_tpu.ops.paged_attention import PagedPool

        if isinstance(k_pool, PagedPool):
            self.memory_cache.update_cache(self._handles[0], k_pool.codes)
            self.memory_cache.update_cache(self._handles[1], v_pool.codes)
            self.memory_cache.update_cache(self._handles[2], k_pool.scales)
            self.memory_cache.update_cache(self._handles[3], v_pool.scales)
            return
        self.memory_cache.update_cache(self._handles[0], k_pool)
        self.memory_cache.update_cache(self._handles[1], v_pool)

    # ------------------------------------------------------------------ lanes

    async def acquire_lane(
        self,
        timeout: Optional[float] = None,
        *,
        priority: int = SESSION_PRIORITY_NORMAL,
        peer_id: Optional[str] = None,
        trace_id: Optional[str] = None,
    ) -> int:
        """Borrow a lane; queues when all lanes are taken — the allocation-
        pressure behavior of MemoryCache, at lane granularity. Parked callers
        are admitted by priority class, then per-peer fair share, then FIFO
        (scheduler.pick_waiter); at default priority that is exactly the old
        FIFO. ``timeout`` bounds the WHOLE acquisition including first-use
        pool allocation, so session opens can fall back to a private cache.

        Paged mode: admission additionally claims ONE page (not max_length
        tokens) — the lane grows page-by-page via prepare_write, and a full
        page pool exerts the same waiter backpressure as a full lane list
        (preempting an idle victim first when the swap tier is enabled)."""
        t_wait = time.perf_counter()
        lane = await self._acquire_lane(
            timeout=timeout, priority=priority, peer_id=peer_id, trace_id=trace_id
        )
        self._scheduler.register(lane, peer_id, int(priority), trace_id=trace_id)
        # ledger session opens at admission, before the first page claim, so
        # every page-second of this lane's residency lands on its bill
        self._ledger_keys[lane] = self._ledger.open_session(peer_id, trace_id)
        if self.page_size is not None:
            try:
                await self.prepare_write(lane, 0, 1, timeout=timeout)
            except BaseException:
                self.release_lane(lane)
                raise
        self._journal.event(
            "admission", trace_id=trace_id, lane=lane,
            occupancy=self.occupancy_info(),
            priority=int(priority),
            wait_s=round(time.perf_counter() - t_wait, 6),
        )
        self._note_occupancy()
        return lane

    async def _acquire_lane(
        self,
        timeout: Optional[float] = None,
        priority: int = SESSION_PRIORITY_NORMAL,
        peer_id: Optional[str] = None,
        trace_id: Optional[str] = None,
    ) -> int:
        await self.ensure_open(timeout=timeout)
        if self._closed:
            raise AllocationFailed("Batcher is closed")
        if self._free_lanes:
            # FIFO like the waiter queue: least-recently-released lane first,
            # so reuse is fair and page-table churn stays predictable
            lane = self._free_lanes.pop(0)
            self._lane_generation[lane] = self._generation
            return lane
        waiter = _LaneWaiter(
            fut=asyncio.get_running_loop().create_future(),
            priority=int(priority),
            peer_id=peer_id,
            seq=next(self._waiter_seq),
            trace_id=trace_id,
        )
        fut = waiter.fut
        self._lane_waiters.append(waiter)
        try:
            lane = await asyncio.wait_for(fut, timeout)
            self._lane_generation[lane] = self._generation
            return lane
        except asyncio.TimeoutError:
            if fut.done() and not fut.cancelled() and fut.exception() is None:
                lane = fut.result()  # resolved in the cancellation race window
                self._lane_generation[lane] = self._generation
                return lane
            tm.ALLOC_FAILED.inc()
            raise AllocationFailed(
                f"No free decode lane within {timeout} s ({self._occupancy()})"
            )
        except BaseException:
            # cancelled after release_lane already handed us the lane: put it
            # back, or pool capacity shrinks forever
            if fut.done() and not fut.cancelled() and fut.exception() is None:
                self.release_lane(fut.result())
            raise
        finally:
            if waiter in self._lane_waiters:
                self._lane_waiters.remove(waiter)

    def release_lane(self, lane: int) -> None:
        # drop stale latency attributions — they belong to the departing
        # tenant, not whoever acquires this lane next
        self._enq_t.pop(lane, None)
        self._step_timing.pop(lane, None)
        self._step_fp.pop(lane, None)
        # a timed-out/cancelled session may have left a step queued: purge it,
        # or its stale KV write could land in the next tenant's history
        kept = []
        for entry in self._pending:
            if entry[0] == lane:
                fut = entry[3]
                if not fut.done():
                    fut.set_exception(AllocationFailed("Lane released mid-step"))
            else:
                kept.append(entry)
        self._pending = kept
        # likewise a mid-generation release: fail the stream so the handler
        # never resolves it against a lane now owned by someone else
        st = self._gen_states.pop(lane, None)
        if st is not None and not st.future.done():
            st.future.set_exception(AllocationFailed("Lane released mid-step"))
        # ...and a mid-prefill release: the remaining chunks must never run
        # against a lane now owned by someone else
        for pst in [p for p in self._prefill_queue if p.lane == lane]:
            self._prefill_queue.remove(pst)
            if not pst.future.done():
                pst.future.set_exception(AllocationFailed("Lane released mid-step"))
        self._lane_generation.pop(lane, None)
        # drop the scheduler slot: a suspended lane's host swap bytes free
        # here, and a swap-out racing this release aborts on its post-gather
        # validation (the slot object it captured is no longer registered)
        self._scheduler.unregister(lane)
        # settle and close the tenant's bill; totals fold into the peer rollup
        key = self._ledger_keys.pop(lane, None)
        if key is not None:
            self._ledger.close_session(key)
        # paged mode: drop this lane's table references — pages whose refcount
        # hits zero (no prefix-cache pin) return to the pool and wake any
        # prepare_write waiters blocked on an exhausted pool
        if self.page_size is not None and self._tables is not None:
            row = self._tables[lane]
            for slot in range(self.max_pages):
                if row[slot] >= 0:
                    self._pages.decref(int(row[slot]))
            row[:] = -1
            self._tables_mutated()
        # hand straight to the best-placed waiter (priority class, then
        # per-peer fair share, then FIFO), else back to the free list; the
        # new session overwrites the lane from position 0, so no zeroing
        while self._lane_waiters:
            w = self._scheduler.pick_waiter(self._lane_waiters)
            if w is None:
                self._lane_waiters.clear()  # every parked future already dead
                break
            self._lane_waiters.remove(w)
            if not w.fut.done():
                # the pick_waiter POLICY decision, with its justification:
                # who was chosen (priority / fair share) over how many others
                self._journal.event(
                    "waiter_picked", trace_id=w.trace_id, lane=lane,
                    occupancy=self.occupancy_info(),
                    priority=w.priority,
                    waiters=len(self._lane_waiters) + 1,
                )
                w.fut.set_result(lane)
                self._note_occupancy()
                return
        self._free_lanes.append(lane)
        self._note_occupancy()

    # ------------------------------------------------------------------ pages

    async def prepare_write(
        self, lane: int, t0: int, t1: int, timeout: Optional[float] = None
    ) -> None:
        """Make token range [t0, t1) of ``lane`` writable: allocate missing
        pages on demand and copy-on-write-fork any page shared with the
        prefix cache (refs > 1). Blocks on an exhausted pool until a page
        frees (release_lane / prefix-cache eviction), raising
        AllocationFailed at ``timeout`` — MemoryCache's backpressure
        contract at page grain. No-op in dense mode."""
        if self.page_size is None or t1 <= t0:
            return
        self._check_lane(lane)
        if t1 > self.max_length:
            raise ValueError(
                f"Write range [{t0}, {t1}) overflows the lane buffer "
                f"({self.max_length} tokens)"
            )
        alloc = self._pages
        # identity preference keeps tables contiguous at the default pool
        # size: the fused kernel serves any layout, but identity tables read
        # pages in sequential HBM order (and keep the tables_contiguous
        # debug flag meaningful)
        identity_base = (
            lane * self.max_pages
            if self.n_pages == self.n_lanes * self.max_pages else None
        )
        deadline = None if timeout is None else time.monotonic() + timeout
        pages_changed = False
        for slot in range(t0 // self.page_size, (t1 - 1) // self.page_size + 1):
            cur = int(self._tables[lane, slot])
            if cur >= 0 and alloc.refs[cur] == 1:
                continue  # already exclusively owned
            preferred = None if identity_base is None else identity_base + slot
            while True:
                page = alloc.try_alloc(preferred=preferred)
                if page is not None:
                    break
                # pool exhausted: before parking on freed_event, try to swap
                # an idle victim lane out to host RAM (no-op when the swap
                # tier is disabled — the PR2 backpressure path is unchanged)
                if await self._try_preempt(exclude=lane):
                    if self._pages is not alloc:
                        raise AllocationFailed(
                            "Lane pool was reset while waiting for a free page"
                        )
                    self._check_lane(lane)
                    continue
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    tm.ALLOC_FAILED.inc()
                    raise AllocationFailed(
                        f"No free KV page within {timeout} s ({self._occupancy()})"
                    )
                alloc.freed_event.clear()
                wait = remaining
                if self.swap_pool.max_size_bytes > 0 and self._scheduler.policy != "off":
                    # a victim can become IDLE without any page freeing, so
                    # freed_event alone would never retry preemption: poll
                    wait = 0.05 if wait is None else min(wait, 0.05)
                try:
                    await asyncio.wait_for(alloc.freed_event.wait(), timeout=wait)
                except asyncio.TimeoutError:
                    pass  # loop once more to produce the AllocationFailed message
                if self._pages is not alloc:
                    raise AllocationFailed(
                        "Lane pool was reset while waiting for a free page"
                    )
                self._check_lane(lane)
            try:
                if cur >= 0:
                    # shared page: fork it on the compute thread (serialized
                    # with batched steps by the queue), then drop our shared ref
                    await self.queue.submit(
                        self._copy_page, cur, page,
                        priority=PRIORITY_INFERENCE, size=0,
                    )
                    alloc.stats["forked"] += 1
                    self._check_lane(lane)
                    alloc.decref(cur)
            except BaseException:
                if self._pages is alloc:
                    alloc.decref(page)  # never reached the table: hand it back
                raise
            self._tables[lane, slot] = page
            self._tables_mutated()
            pages_changed = True
        if pages_changed:
            # attribution rates changed (a grow or a COW fork): settle the
            # ledger here, not on the next admission boundary — page-seconds
            # accrued under the old rates up to this instant
            self._ledger_sync()

    def _copy_page(self, src: int, dst: int) -> None:
        """Compute-thread body: device copy of one page (all blocks) — the
        copy-on-write fork. Donating, so swapped under the reset lock like
        every other pool-touching op."""
        with self._reset_lock:
            k_pool, v_pool = self._buffers()
            k_pool, v_pool = self.backend._copy_page_fn(
                k_pool, v_pool, np.int32(src), np.int32(dst)
            )
            self._update(k_pool, v_pool)

    @property
    def page_epoch(self) -> int:
        return self._page_epoch

    @property
    def page_nbytes(self) -> int:
        """Wire bytes of one KV page across this span (0 for dense pools) —
        how the radix prefix cache prices its pinned page runs when billing
        HBM residency to tenants through the ledger."""
        if self.page_size is None:
            return 0
        return self._page_nbytes()

    def pin_lane_pages(self, lane: int, t0: int, t1: int) -> Optional[List[int]]:
        """Take a reference on the pages backing token range [t0, t1) of
        ``lane`` (page-aligned) so the prefix cache can share them after the
        lane is released. Returns the page list, or None when the range is
        not fully resident (or not paged). Pair with unpin_pages."""
        if self.page_size is None or self._tables is None:
            return None
        assert t0 % self.page_size == 0 and t1 % self.page_size == 0, (t0, t1)
        row = self._tables[lane]
        pages = []
        for slot in range(t0 // self.page_size, t1 // self.page_size):
            page = int(row[slot])
            if page < 0:
                return None
            pages.append(page)
        for page in pages:
            # swarmlint: disable=paired-refcount — ownership transfer: the refs belong to the caller (prefix cache), released via unpin_pages; no code below this loop can raise
            self._pages.incref(page)
        self._ledger_sync()  # refcounts moved: the lane's fractional share shrank
        return pages

    def unpin_pages(self, pages: Sequence[int], epoch: int) -> None:
        """Drop prefix-cache references taken by pin_lane_pages. Ignores pins
        from a previous epoch: the reset rebuilt the allocator, so those
        pages no longer exist to decref."""
        if self.page_size is None or self._pages is None or epoch != self._page_epoch:
            return
        for page in pages:
            self._pages.decref(int(page))
        self._ledger_sync()  # pins released: surviving holders' shares grew

    def adopt_pages(self, lane: int, pages: Sequence[int]) -> None:
        """Point ``lane``'s first len(pages) table slots at already-resident
        (prefix-cache-pinned) pages — a cache hit that copies ZERO bytes.
        The lane holds them read-shared; its first write past the prefix
        forks via prepare_write."""
        assert self.page_size is not None and self._tables is not None
        assert len(pages) <= self.max_pages
        row = self._tables[lane]
        for slot, page in enumerate(pages):
            cur = int(row[slot])
            self._pages.incref(int(page))
            if cur >= 0:
                self._pages.decref(cur)
            row[slot] = int(page)
        if pages:
            self._tables_mutated()
            tm.PREFIX_ADOPT.inc()
            self._ledger_sync()  # the lane now shares the prefix pages' refcounts

    def _tables_mutated(self) -> None:
        """Invalidate the cached contiguity flag — call after ANY table write
        (alloc, adopt, release, swap, reset)."""
        self._tables_contig = None

    def tables_contiguous(self) -> Optional[bool]:
        """Stats/debug surface ONLY: are the block tables currently the
        identity layout? The step path no longer branches on this (one fused
        attention path serves both); the flag is kept for observability —
        identity tables mean page reads stream sequentially through HBM.
        Cached; recomputed lazily after a table mutation."""
        if self.page_size is None or self._tables is None:
            return None
        if self._tables_contig is None:
            from petals_tpu.ops.paged_attention import tables_are_contiguous

            self._tables_contig = tables_are_contiguous(self._tables, self.n_pages)
        return self._tables_contig

    def paged_summary(self) -> Optional[dict]:
        """Observability: pool occupancy + allocator counters (rpc_info)."""
        if self.page_size is None:
            return None
        alloc = self._pages
        return {
            "page_size": self.page_size,
            "n_pages": self.n_pages,
            "page_epoch": self._page_epoch,
            "pages_free": alloc.n_free if alloc is not None else self.n_pages,
            "tables_contiguous": self.tables_contiguous(),
            **({f"pages_{k}": v for k, v in alloc.stats.items()} if alloc else {}),
        }

    # -------------------------------------------------------- preemption / swap

    def _lane_pages(self, lane: int) -> int:
        """Resident page count of a lane (scheduler pages_fn: victim sizing
        and fair-share accounting)."""
        if self._tables is None:
            return 0
        return int((self._tables[lane] >= 0).sum())

    def _page_nbytes(self) -> int:
        # WIRE bytes per page: quantized pools swap/reserve packed bytes, so
        # the host-swap budget, ledger swap meters, and victim sizing all
        # bill what actually moves (kv_bytes_per_token == cache_bytes_per_token
        # for unquantized backends)
        return self.backend.kv_bytes_per_token() * self.page_size

    def _lane_lock(self, lane: int) -> AsyncTryLock:
        lock = self._lane_locks.get(lane)
        if lock is None:
            # one shared sanitizer name: lane locks are an equivalence class
            # (never nested within each other except via trylock, below)
            lock = self._lane_locks[lane] = make_async_lock("batching.lane_lock")
        return lock

    @contextlib.asynccontextmanager
    async def _lane_busy(self, lane: int):
        """Guard every lane-touching op: a suspended lane transparently swaps
        back in first, then the in-flight counter marks the lane unpreemptable
        for the op's duration. No await between the resident check returning
        and the increment, so the pair is atomic on the event loop."""
        await self._ensure_resident(lane)
        self._inflight[lane] = self._inflight.get(lane, 0) + 1
        self._scheduler.touch(lane)
        try:
            yield
        finally:
            self._inflight[lane] -= 1
            # a step boundary IS the preemption opportunity: when decode is
            # compute-bound, lanes are idle only in the sliver between ops,
            # which timer polls almost always miss — wake page waiters now
            # so they re-attempt victim selection while this lane is idle
            if (
                self._inflight[lane] == 0
                and self._pages is not None
                and self.swap_pool.max_size_bytes > 0
            ):
                self._pages.freed_event.set()

    def _lane_idle(self, lane: int, *, ignore_lock: bool = False) -> bool:
        """A lane is preemptable only while NOTHING is touching it: no step
        pending or in flight, no server-gen or prefill stream, no exclusive
        op, no swap already in progress — and some pages actually resident
        to reclaim. ``ignore_lock`` is for the re-check inside
        _swap_out_lane, which holds the lane lock itself."""
        if self._lane_generation.get(lane) != self._generation:
            return False
        if self._inflight.get(lane, 0) > 0:
            return False
        if lane in self._gen_states:
            return False
        if any(p.lane == lane for p in self._prefill_queue):
            return False
        if any(e[0] == lane for e in self._pending):
            return False
        if not ignore_lock:
            lock = self._lane_locks.get(lane)
            if lock is not None and lock.locked():
                return False
        return self._lane_pages(lane) > 0

    async def _try_preempt(self, exclude: int) -> bool:
        """Pool exhausted: try to swap ONE idle victim lane out to host RAM.
        Returns True when a victim's pages were freed (the caller retries
        allocation immediately); False means no preemptable victim — fall
        back to waiting on freed_event, the old backpressure path. Victims
        must be of equal-or-lower priority than the requester."""
        sched = self._scheduler
        if (
            self.page_size is None
            or sched.policy == "off"
            or self.swap_pool.max_size_bytes <= 0
        ):
            return False
        req = sched.lanes.get(exclude)
        max_priority = req.priority if req is not None else None
        candidates = [
            l for l in list(self._lane_generation)
            if l != exclude and self._lane_idle(l)
        ]
        victim = sched.pick_victim(candidates, max_priority=max_priority)
        if victim is None:
            return False
        # journal the DECISION (outcome shows as a following swap_out event
        # or its absence): who was evicted, for whom, under what occupancy
        self._journal.event(
            "victim_selected",
            trace_id=sched.trace_id_of(victim),
            lane=victim,
            occupancy=self.occupancy_info(),
            requester_lane=exclude,
            requester_trace_id=sched.trace_id_of(exclude),
            policy=sched.policy,
            candidates=list(candidates),
        )
        return await self._swap_out_lane(victim)

    async def _swap_out_lane(self, lane: int) -> bool:
        """Suspend ``lane``: gather its resident pages on device, copy them to
        the host swap pool, then free the pages (waking allocation waiters).
        The block-table row is cleared; swap-in may later land the content on
        entirely different physical pages. Aborts harmlessly (False) if the
        lane's state moved while the gather ran — release_lane, a pool reset,
        or a racing op all invalidate the snapshot."""
        sched = self._scheduler
        slot = sched.lanes.get(lane)
        if slot is None or slot.swap is not None or slot.suspending:
            return False
        lock = self._lane_lock(lane)
        # non-blocking trylock (records no sanitizer order edge): a held lane
        # lock means the lane is busy, i.e. not preemptable — and a blocking
        # acquire would invert the lane-lock -> turnstile order, since
        # _try_preempt can run with the swap-in turnstile held (_swap_in)
        if not lock_try_acquire_nowait(lock):
            return False
        try:
            if not self._lane_idle(lane, ignore_lock=True):
                return False
            if sched.lanes.get(lane) is not slot or slot.swap is not None:
                return False
            alloc = self._pages
            gen = self._lane_generation.get(lane)
            row = self._tables[lane]
            slots = np.flatnonzero(row >= 0).astype(np.int32)
            if slots.size == 0:
                return False
            pages = row[slots].astype(np.int32).copy()
            nbytes = int(slots.size) * self._page_nbytes()
            if not self.swap_pool.try_reserve(nbytes):
                return False  # swap tier full: this victim is not preemptable
            slot.suspending = True
            try:
                k_host, v_host = await self.queue.submit(
                    self._swap_out_device, pages,
                    priority=PRIORITY_INFERENCE, size=0,
                )
            except asyncio.CancelledError:
                self.swap_pool.free(nbytes)
                slot.suspending = False
                sched.stats["swap_aborted"] += 1
                raise
            except Exception as e:
                # the gather is non-donating, so the pool is intact; degrade
                # to the plain backpressure path rather than failing the
                # REQUESTER for the victim's trouble
                logger.warning("Swap-out gather for lane %d failed: %r", lane, e)
                self.swap_pool.free(nbytes)
                slot.suspending = False
                sched.stats["swap_aborted"] += 1
                return False
            # validate nothing moved while the gather ran; only now (host
            # copy landed, snapshot still true) do the pages actually free
            if (
                sched.lanes.get(lane) is not slot
                or self._pages is not alloc
                or self._lane_generation.get(lane) != gen
                or gen != self._generation
                or not np.array_equal(self._tables[lane][slots], pages)
            ):
                self.swap_pool.free(nbytes)
                slot.suspending = False
                sched.stats["swap_aborted"] += 1
                return False
            for page in pages:
                alloc.decref(int(page))
            self._tables[lane, slots] = -1
            self._tables_mutated()
            slot.swap = SwapEntry(
                k=k_host, v=v_host, slots=slots, nbytes=nbytes, generation=gen,
                suspended_at=time.monotonic(),
            )
            slot.suspending = False
            sched.stats["preemptions"] += 1
            sched.stats["swap_outs"] += 1
            tm.PREEMPTIONS.inc()
            tm.SWAP_OUT_BYTES.inc(nbytes)
            key = self._ledger_keys.get(lane)
            if key is not None:
                self._ledger.note_swap(key, out_bytes=nbytes)
            self._journal.event(
                "swap_out", trace_id=slot.trace_id, lane=lane,
                occupancy=self.occupancy_info(),
                pages=int(slots.size), nbytes=nbytes,
            )
            self._note_occupancy()
            logger.debug(
                f"Preempted lane {lane}: {slots.size} pages -> host swap "
                f"({self.swap_pool.bytes_in_use}/{self.swap_pool.max_size_bytes} B used)"
            )
            return True
        finally:
            lock.release()

    def _swap_out_device(self, pages: np.ndarray):
        """Compute-thread body: gather the victim's pages and land them in
        host RAM. Non-donating — the pool stays live; the pages only free
        once the event loop validates and commits the suspend."""
        with self._reset_lock:
            k_pool, v_pool = self._buffers()
            k, v = self.backend._swap_out_pages_fn(k_pool, v_pool, pages)
            # per-leaf host copy: a quantized pool's SwapEntry holds a
            # PagedPool of numpy arrays — packed wire bytes, never fp pages
            to_host = lambda t: jax.tree_util.tree_map(np.asarray, t)
            return to_host(k), to_host(v)

    async def _ensure_resident(self, lane: int) -> None:
        """Transparent resume: if ``lane`` is suspended (or a suspend is in
        flight — the lock serializes us behind it), swap its KV back in
        before the caller's op proceeds."""
        sched = self._scheduler
        slot = sched.lanes.get(lane)
        if slot is None or (slot.swap is None and not slot.suspending):
            return
        async with self._lane_lock(lane):
            slot = sched.lanes.get(lane)
            if slot is None or slot.swap is None:
                return  # suspend aborted, or lane released meanwhile
            await self._swap_in(lane, slot)

    async def _swap_in(self, lane: int, slot) -> None:
        """Resume a suspended lane (lane lock held): allocate fresh pages
        (all-or-nothing, preempting others if needed), scatter the host copy
        back into the pool, and restore the block-table row — onto possibly
        different physical pages than before."""
        sched = self._scheduler
        entry = slot.swap
        self._check_lane(lane)
        # only the ALLOCATION is serialized: once this resumer holds its
        # pages the next one can start negotiating for pages while our
        # scatter runs on the compute queue — the turnstile exists to stop
        # concurrent allocators hoarding partial page sets, not to make
        # swap-ins take turns at the device
        async with self._swap_in_turnstile:
            pages = await self._alloc_pages(lane, entry.slots)
        alloc = self._pages
        pages_arr = np.asarray(pages, np.int32)
        try:
            await self.queue.submit(
                self._swap_in_device, lane, entry, pages_arr,
                priority=PRIORITY_INFERENCE, size=0,
            )
        except BaseException:
            if self._pages is alloc:
                for page in pages:
                    alloc.decref(int(page))
            self._maybe_reset_pool()  # the scatter donates the pool buffers
            raise
        self._tables[lane, entry.slots] = pages_arr
        self._tables_mutated()
        slot.swap = None
        slot.resumed_at = time.monotonic()
        self.swap_pool.free(entry.nbytes)
        sched.stats["swap_ins"] += 1
        tm.SWAP_IN_BYTES.inc(entry.nbytes)
        key = self._ledger_keys.get(lane)
        if key is not None:
            self._ledger.note_swap(key, in_bytes=entry.nbytes)
        self._journal.event(
            "swap_in", trace_id=slot.trace_id, lane=lane,
            occupancy=self.occupancy_info(),
            pages=int(entry.slots.size), nbytes=entry.nbytes,
        )
        self._note_occupancy()
        logger.debug(f"Resumed lane {lane}: {entry.slots.size} pages swapped in")

    def _swap_in_device(self, lane: int, entry, pages: np.ndarray) -> None:
        """Compute-thread body: scatter a swap entry's KV onto fresh pages.
        Donating, so the generation check rides INSIDE the reset lock — the
        same TOCTOU rule as _insert_lane."""
        with self._reset_lock:
            self._check_lane(lane)
            if entry.generation != self._generation:
                raise AllocationFailed(
                    "Lane pool was reset while this session was swapped out"
                )
            k_pool, v_pool = self._buffers()
            k_pool, v_pool = self.backend._swap_in_pages_fn(
                k_pool, v_pool, entry.k, entry.v, pages
            )
            self._update(k_pool, v_pool)

    async def _alloc_pages(self, lane: int, slots: np.ndarray) -> List[int]:
        """All-or-nothing page allocation for a swap-in: take len(slots)
        pages only once that many are simultaneously free — two resuming
        lanes each holding a partial set would deadlock — preempting other
        lanes when the pool is short. Identity slots are preferred so a
        resumed lane can regain the contiguous fast path when its old pages
        happen to be free."""
        alloc = self._pages
        n = int(len(slots))
        identity_base = (
            lane * self.max_pages
            if self.n_pages == self.n_lanes * self.max_pages else None
        )
        timeout = 30.0 if self.alloc_timeout is None else self.alloc_timeout
        deadline = time.monotonic() + timeout
        while True:
            if self._pages is not alloc:
                raise AllocationFailed("Lane pool was reset while waiting for a free page")
            self._check_lane(lane)
            if alloc.n_free >= n:
                pages = []
                for slot in slots:
                    preferred = None if identity_base is None else identity_base + int(slot)
                    page = alloc.try_alloc(preferred=preferred)
                    assert page is not None, "n_free lied: allocator invariant broken"
                    pages.append(page)
                return pages
            if await self._try_preempt(exclude=lane):
                continue
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                tm.ALLOC_FAILED.inc()
                raise AllocationFailed(
                    f"No free KV page for swap-in within {timeout} s ({self._occupancy()})"
                )
            alloc.freed_event.clear()
            try:
                # bounded wait (not remaining): see prepare_write — preemption
                # must re-attempt when a victim merely becomes idle
                await asyncio.wait_for(
                    alloc.freed_event.wait(), timeout=min(remaining, 0.05)
                )
            except asyncio.TimeoutError:
                pass  # loop once more to produce the AllocationFailed message

    # -------------------------------------------------------- observability

    def _note_occupancy(self) -> None:
        """Refresh the pool gauges. Called at admission/release/swap
        boundaries — occupancy only changes there, so the decode tick path
        pays nothing for these."""
        busy = (self.n_lanes - len(self._free_lanes)) if self.is_open else 0
        tm.LANES_BUSY.set(busy)
        if self.page_size is not None:
            tm.PAGES_TOTAL.set(self.n_pages)
            tm.PAGES_FREE.set(
                self._pages.n_free if self._pages is not None else self.n_pages
            )
            if self._pages is not None:
                # page-pool economics: free-run histogram + fragmentation.
                # O(free pages) with a sort, but only at admission/release/
                # swap boundaries — never on the decode tick.
                info = self._pages.fragmentation_info()
                tm.PAGE_FRAGMENTATION.set(info["frag"])
                tm.PAGE_LARGEST_RUN.set(info["largest_run"])
                for bucket, child in tm.PAGE_FREE_RUN_CHILDREN.items():
                    child.set(info["run_hist"][bucket])
        mc = self.memory_cache
        if mc is not None and mc.max_size_bytes < 2**60:
            # only meaningful under a real HBM budget (the default cache is
            # effectively unbounded and would read as 2**64 headroom)
            tm.HBM_HEADROOM.set(mc.bytes_left)
        tm.SWAP_RESIDENCY_OLDEST.set(self._scheduler.oldest_swap_age())
        # the same boundaries are the ledger's settlement points: push a
        # fresh attribution-rate snapshot, then give the noisy-neighbor
        # detector a look while the admission queue state is current
        self._ledger_sync()
        if self._lane_waiters:
            self._ledger_check_noisy()

    def _ledger_sync(self) -> None:
        """Settle the resource ledger and install the new piecewise-constant
        rates: each session's fractional page holding (1/refcount per
        referenced page — prefix-cache pins absorb the remainder) plus the
        pool occupancy whose integral the per-session split must sum to.
        Called wherever block tables or refcounts change; O(lanes x
        max_pages) vectorized, never on the per-token decode path."""
        weights: Dict[str, float] = {}
        occupied = 0.0
        if (
            self.page_size is not None
            and self._pages is not None
            and self._tables is not None
        ):
            occupied = float(self.n_pages - self._pages.n_free)
            if self._ledger_keys:
                lanes = list(self._ledger_keys)
                shares = self._pages.fractional_shares(self._tables[lanes])
                weights = {
                    self._ledger_keys[lane]: float(s)
                    for lane, s in zip(lanes, shares)
                }
        self._ledger.set_rates(weights, occupied)

    def _ledger_check_noisy(self) -> None:
        """Ask the DRF detector whether one peer's dominant-resource share
        is starving the admission queue; journal the evidence when it fires
        (the counter bump + flight-recorder entry happen inside the ledger)."""
        evidence = self._ledger.check_noisy(
            [w.peer_id for w in self._lane_waiters if not w.fut.done()]
        )
        if evidence is not None:
            self._journal.event(
                "noisy_neighbor", occupancy=self.occupancy_info(), **evidence
            )

    def pop_usage_delta(self, lane: int) -> Optional[dict]:
        """Per-session resource usage since the last call — the tenant's own
        bill, piggybacked on step_meta so InferenceSession.usage_report()
        can aggregate it client-side. None for unmetered (dense/private)
        lanes or an empty delta."""
        key = self._ledger_keys.get(lane)
        if key is None:
            return None
        delta = self._ledger.usage_delta(key)
        if delta and delta.get("spec_proposed"):
            # per-reply speculative efficiency rides the bill (acceptance
            # rate and tokens per compute-second over this delta window)
            from petals_tpu.telemetry.ledger import derive_efficiency

            delta.update(derive_efficiency(delta))
        return delta or None

    def _occupancy(self) -> str:
        """Human-readable pool occupancy for AllocationFailed messages: lane
        and page counts, per-lane page holdings, and swap-tier usage — so a
        rejected client (and the operator reading its logs) can see WHY."""
        busy = (self.n_lanes - len(self._free_lanes)) if self.is_open else 0
        parts = [
            f"{busy}/{self.n_lanes} lanes busy",
            f"{len(self._lane_waiters)} waiters",
        ]
        if self.page_size is not None and self._pages is not None:
            parts.append(f"{self._pages.n_free}/{self.n_pages} pages free")
            if self._tables is not None and self._lane_generation:
                held = ", ".join(
                    f"lane {l}: {self._lane_pages(l)}"
                    for l in sorted(self._lane_generation)
                )
                parts.append(f"pages held: [{held}]")
        if self.swap_pool.max_size_bytes > 0:
            parts.append(
                f"{self._scheduler.suspended_count} suspended, swap "
                f"{self.swap_pool.bytes_in_use}/{self.swap_pool.max_size_bytes} B"
            )
        return "; ".join(parts)

    def occupancy_info(self) -> dict:
        """Machine-readable pool/scheduler occupancy (ServerInfo.pool,
        rpc_info, run_health): enough for a client to route around a loaded
        server — busy lanes, free pages, suspended sessions, swap bytes,
        preemption count."""
        info = {
            "lanes": self.n_lanes,
            "busy_lanes": (self.n_lanes - len(self._free_lanes)) if self.is_open else 0,
            "lane_waiters": len(self._lane_waiters),
        }
        if self.page_size is not None:
            info["n_pages"] = self.n_pages
            info["pages_free"] = (
                self._pages.n_free if self._pages is not None else self.n_pages
            )
            if self._pages is not None:
                frag = self._pages.fragmentation_info()
                info["frag"] = frag["frag"]
                info["largest_free_run"] = frag["largest_run"]
            # honest capacity math for clients: the pool's encoding and its
            # WIRE bytes/token (what a page actually costs under kv quant)
            info["kv_quant"] = getattr(self.backend, "kv_quant_type", "none")
            info["kv_bytes_per_token"] = int(self.backend.kv_bytes_per_token())
        info.update(self._scheduler.summary())
        return info

    def occupancy_hint(self) -> dict:
        """Two-field load hint riding every step_meta reply (cheaper than the
        full occupancy_info dict, and small enough for every token)."""
        return {
            "busy_lanes": (self.n_lanes - len(self._free_lanes)) if self.is_open else 0,
            "lane_waiters": len(self._lane_waiters),
        }

    def pop_step_timing(self, lane: int) -> Optional[dict]:
        """Consume the finished step's queue/compute attribution for ``lane``
        (written by the compute thread / flush loop just before the step
        future resolved). None when no timed step completed — e.g. a
        cached-prefix fast path that never touched the device."""
        return self._step_timing.pop(lane, None)

    def pop_step_fp(self, lane: int) -> Optional[list]:
        """Consume the finished step's fused activation fingerprint for
        ``lane`` (FP_DIM floats; ops/fingerprint.py) — the handler
        piggybacks it on step_meta next to the timing attribution. None
        when fingerprinting is disabled or no batched step ran."""
        return self._step_fp.pop(lane, None)

    def _capture_step_fp(self, lanes, chunk_lane: Optional[int] = None) -> None:
        """Stash the backend's fused per-lane fingerprints (compute thread,
        right after the step's host sync — same discipline as
        _record_decode_timing). ``chunk_lane`` takes the mixed step's
        prefill-chunk digest: its LAST chunk's digest is what the client
        re-derives from the assembled prefill reply."""
        pop = getattr(self.backend, "pop_step_fp", None)
        if pop is None:
            return  # wrapper backend without the fingerprint plane
        fp, chunk_fp = pop()
        if fp is not None:
            host = np.asarray(fp)
            for lane in lanes:
                self._step_fp[lane] = [float(x) for x in host[lane]]
        if chunk_fp is not None and chunk_lane is not None:
            self._step_fp[chunk_lane] = [
                float(x) for x in np.asarray(chunk_fp).reshape(-1)
            ]

    # ------------------------------------------------------------------ stepping

    def _check_lane(self, lane: int) -> None:
        if self._lane_generation.get(lane) != self._generation:
            raise AllocationFailed(
                "Lane pool was reset after a failed device step: this "
                "session's KV is gone; the client must re-open the session"
            )

    async def step(self, lane: int, hidden: np.ndarray, position: int) -> np.ndarray:
        """One decode token for ``lane`` (hidden [1, 1, hidden]); coalesced
        with whatever other lanes are pending by the time the device is free.
        A preempted (swapped-out) lane transparently swaps back in first."""
        t_enq = time.perf_counter()  # before _lane_busy: lock + alloc waits count as queue
        async with self._lane_busy(lane):
            self._check_lane(lane)
            if self.page_size is not None:
                # grow the lane to cover this token BEFORE the device step —
                # allocation can await a freed page; the step itself never
                # blocks. alloc_timeout bounds the wait: without it, N
                # sessions each needing one more page from an exhausted pool
                # (and none willing to release) deadlock forever
                await self.prepare_write(
                    lane, int(position), int(position) + 1,
                    timeout=self.alloc_timeout,
                )
            fut = asyncio.get_running_loop().create_future()
            self._enq_t[lane] = t_enq  # written under _lane_busy: no overwrite race
            self._pending.append((lane, hidden, int(position), fut, self._generation))
            self._spawn_flush_loop()
            return await fut

    def _spawn_flush_loop(self) -> None:
        """(Re)start the flush loop if it is not already draining. The strong
        reference in ``self._flush_task`` keeps the loop alive (asyncio holds
        tasks weakly) and the done-callback surfaces a crashed drain — a
        silently dead flush loop would hang every pending step future."""
        if self._flush_task is None or self._flush_task.done():
            self._flush_task = asyncio.create_task(self._flush_loop())
            self._flush_task.add_done_callback(
                log_exception_callback(logger, "decode flush loop")
            )

    async def _flush_loop(self) -> None:
        while self._pending or self._gen_states or self._prefill_queue:
            batch, self._pending = self._pending, []
            # entries enqueued before a pool reset must fail loudly — running
            # them against the rematerialized (zeroed) pool would be the
            # silent corruption the generation machinery exists to prevent
            stale = [e for e in batch if e[4] != self._generation]
            batch = [e for e in batch if e[4] == self._generation]
            for *_, fut, _gen in stale:
                if not fut.done():
                    fut.set_exception(AllocationFailed(
                        "Lane pool was reset while this step was pending"
                    ))
            # same staleness rule for mid-generation lanes
            for lane, st in list(self._gen_states.items()):
                if st.generation != self._generation:
                    del self._gen_states[lane]
                    if not st.future.done():
                        st.future.set_exception(AllocationFailed(
                            "Lane pool was reset while this step was pending"
                        ))
            # ...and for admitted prefills
            for pst in [p for p in self._prefill_queue if p.generation != self._generation]:
                self._prefill_queue.remove(pst)
                if not pst.future.done():
                    pst.future.set_exception(AllocationFailed(
                        "Lane pool was reset while this step was pending"
                    ))
            gen_states = dict(self._gen_states)
            # speculating lanes leave the plain gen dict for this tick and
            # ride their own draft-verify step; their verify rows share the
            # prefill fairness budget (they are chunk writes, like prefill)
            spec_states = self._pick_spec_lanes(gen_states)
            pf = self._next_prefill_chunk(
                len(batch) + len(gen_states) + len(spec_states),
                spec_tokens=len(spec_states) * (self.spec_k + 1),
            )
            if not batch and not gen_states and not spec_states and pf is None:
                continue
            try:
                toks = chunk_out = spec_res = None
                if spec_states:
                    spec_res = await self.queue.submit(
                        self._run_batch_spec, spec_states,
                        priority=PRIORITY_INFERENCE,
                        size=len(spec_states) * (self.spec_k + 1),
                    )
                if gen_states:
                    out, toks = await self.queue.submit(
                        self._run_batch_gen, batch, gen_states,
                        priority=PRIORITY_INFERENCE,
                        size=len(batch) + len(gen_states),
                    )
                    if pf is not None:
                        # the gen program has no prefill half: the chunk rides
                        # its own mixed step this tick (decode entries already
                        # ran above, so neither side starves the other)
                        _, chunk_out = await self.queue.submit(
                            self._run_batch_mixed, [], pf,
                            priority=PRIORITY_INFERENCE, size=pf[1],
                        )
                elif pf is not None:
                    out, chunk_out = await self.queue.submit(
                        self._run_batch_mixed, batch, pf,
                        priority=PRIORITY_INFERENCE, size=len(batch) + pf[1],
                    )
                elif batch:
                    out = await self.queue.submit(
                        self._run_batch, batch, priority=PRIORITY_INFERENCE,
                        size=len(batch),
                    )
            except BaseException as e:  # noqa: BLE001 — deliver to every waiter
                for *_, fut, _gen in batch:
                    if not fut.done():
                        fut.set_exception(e)
                for lane, st in itertools.chain(
                    gen_states.items(), spec_states.items()
                ):
                    if self._gen_states.get(lane) is st:
                        del self._gen_states[lane]
                    if not st.future.done():
                        st.future.set_exception(e)
                if pf is not None:
                    pst = pf[0]
                    if pst in self._prefill_queue:
                        self._prefill_queue.remove(pst)
                    if not pst.future.done():
                        pst.future.set_exception(e)
                self._maybe_reset_pool()
                continue
            for lane, _, _, fut, _gen in batch:
                if not fut.done():
                    fut.set_result(out[lane : lane + 1])
            if pf is not None and chunk_out is not None:
                self._advance_prefill(pf[0], pf[1], chunk_out)
            if spec_res is not None:
                self._commit_spec_results(spec_states, *spec_res)
            if toks is None:
                continue
            # per-lane post-step bookkeeping (event-loop side, no races with
            # the compute thread): collect the sampled token, advance the
            # feed/draw cursors, and resolve finished streams
            for lane, st in gen_states.items():
                if self._gen_states.get(lane) is not st:
                    continue  # released/cancelled while the step ran
                tok = int(toks[lane])
                st.collected.append(tok)
                st.token = tok
                st.position += 1
                st.draw_idx += 1
                if st.seen is not None and 0 <= tok < st.seen.shape[0]:
                    st.seen[tok] = True
                st.remaining -= 1
                if st.remaining <= 0:
                    del self._gen_states[lane]
                    self._step_timing[lane] = self._gen_step_timing(st, "gen")
                    if not st.future.done():
                        st.future.set_result(
                            np.asarray([st.collected], np.int32)
                        )

    def _pick_spec_lanes(self, gen_states) -> Dict[int, _LaneGenState]:
        """Partition this tick's generating lanes: lanes eligible to
        speculate move into the returned dict (and OUT of ``gen_states``);
        the rest take the plain one-token path. Eligibility: a draft model
        is loaded, the pool is paged, the lane's auto-disable cooldown has
        expired, and the lane has room for the best case — the verify step
        writes spec_k + 1 KV rows at positions p..p+spec_k, which must stay
        inside generate_lane's up-front page reservation (remaining rows
        starting at the current position)."""
        if self.draft is None or self.spec_k < 1 or self.page_size is None:
            return {}
        spec: Dict[int, _LaneGenState] = {}
        for lane, st in list(gen_states.items()):
            if st.spec_cooldown > 0:
                st.spec_cooldown -= 1
                continue
            if st.remaining < self.spec_k + 1:
                continue
            spec[lane] = st
            del gen_states[lane]
        return spec

    def _gen_step_timing(self, st: _LaneGenState, variant: str) -> dict:
        """The finished stream's step_meta timing dict. Streams that ever
        speculated also report their lifetime acceptance evidence."""
        timing = {
            "queue_s": st.queue_s, "compute_s": st.compute_s, "variant": variant,
        }
        if st.spec_proposed:
            timing["spec_proposed"] = st.spec_proposed
            timing["spec_accepted"] = st.spec_accepted
            timing["acceptance_rate"] = round(
                st.spec_accepted / st.spec_proposed, 4
            )
        return timing

    def _commit_spec_results(self, spec_states, g_hat, n_emit) -> None:
        """Post-step bookkeeping for a spec tick (event-loop side): commit
        each lane's emitted prefix g_hat[lane, :n_emit[lane]] — by the
        deterministic-stream acceptance rule those are the target's OWN
        sampled tokens, bit-identical to what plain decode would have
        emitted — then advance position/draw cursors by the emitted count.
        Rollback of the rejected suffix is pure position truncation: the
        stale KV rows past the new position stay in the pages (masked out
        of every future step by kv_length) and are overwritten in place by
        the next tick. No pages move, no refcounts change.

        Also the acceptance-EMA auto-disable: a lane whose EMA falls below
        the PETALS_TPU_SPEC_MIN_ACCEPT floor stops speculating for a
        cooldown window (draft compute on a hostile stream costs more than
        it saves), journaled with the EMA evidence."""
        for lane, st in spec_states.items():
            if self._gen_states.get(lane) is not st:
                continue  # released/cancelled while the step ran
            m = int(n_emit[lane])  # in [1, spec_k + 1] <= st.remaining
            emitted = [int(t) for t in g_hat[lane, :m]]
            for tok in emitted:
                st.collected.append(tok)
                if st.seen is not None and 0 <= tok < st.seen.shape[0]:
                    st.seen[tok] = True
            st.token = emitted[-1]
            st.position += m
            st.draw_idx += m
            st.remaining -= m
            accepted = m - 1  # of spec_k proposed drafts
            st.spec_proposed += self.spec_k
            st.spec_accepted += accepted
            alpha = self._spec_ema_alpha
            st.spec_ema = (
                (1.0 - alpha) * st.spec_ema + alpha * (accepted / self.spec_k)
            )
            if st.spec_ema < self._spec_min_accept and st.remaining > 0:
                ema = st.spec_ema
                st.spec_cooldown = self._spec_cooldown_ticks
                st.spec_ema = 1.0  # optimistic restart after the cooldown
                self.stats["spec_disabled"] += 1
                tm.SPEC_DISABLED.inc()
                self._journal.event(
                    "spec_disabled", lane=lane, ema=round(ema, 4),
                    floor=self._spec_min_accept,
                    cooldown_ticks=self._spec_cooldown_ticks,
                    proposed=st.spec_proposed, accepted=st.spec_accepted,
                )
            if st.remaining <= 0:
                del self._gen_states[lane]
                self._step_timing[lane] = self._gen_step_timing(st, "spec")
                if not st.future.done():
                    st.future.set_result(np.asarray([st.collected], np.int32))

    def _prefill_budget(self, n_decode: int, spec_tokens: int = 0) -> int:
        """Per-tick fairness: the prefill token budget shrinks under decode
        pressure (more than half the lanes actively stepping), but never
        below one page — prefills always make progress, and decode lanes
        never wait on more than one bounded chunk per tick. Spec-verify rows
        spend from the same budget (they are chunk writes riding the tick,
        exactly like prefill tokens), with the same one-page floor."""
        budget = self.prefill_token_budget
        if n_decode > max(1, self.n_lanes // 2):
            budget = max(self.page_size or 1, budget // 2)
        if spec_tokens:
            budget = max(self.page_size or 1, budget - int(spec_tokens))
        return budget

    def _next_prefill_chunk(
        self, n_decode: int, spec_tokens: int = 0
    ) -> Optional[tuple]:
        """Pick the chunk riding this tick: the queue head's next ``take``
        tokens, capped by the byte-sized chunk cap and the fairness budget,
        with the chunk END aligned to an absolute page boundary unless it is
        the prefill's final chunk (whole-page scatters — satellite of
        backend.chunk_plan's page alignment). Returns (state, take) or None."""
        if not self._prefill_queue:
            return None
        st = self._prefill_queue[0]
        remaining = st.hidden.shape[1] - st.offset
        take = min(remaining, st.cap, self._prefill_budget(n_decode, spec_tokens))
        if self.page_size and take < remaining:
            end = st.position + take
            aligned = end - end % self.page_size
            if aligned > st.position:
                take = aligned - st.position
        if not st.wait_observed:
            # first chunk entering a step: the admission -> first-compute gap
            st.wait_observed = True
            if st.enqueued:
                st.queue_s = max(time.perf_counter() - st.enqueued, 0.0)
                tm.PREFILL_QUEUE_WAIT.observe(st.queue_s)
        return st, max(int(take), 1)

    def _advance_prefill(self, st: _LanePrefillState, take: int, chunk_out) -> None:
        """Post-step bookkeeping (event-loop side): collect the chunk's span
        output, advance the cursor, resolve finished prefills, and rotate the
        queue so concurrent prefills share the budget round-robin."""
        if st not in self._prefill_queue:
            return  # released/cancelled while the step ran
        st.outs.append(np.asarray(chunk_out))
        st.offset += take
        st.position += take
        if st.offset >= st.hidden.shape[1]:
            self._prefill_queue.remove(st)
            self._step_timing[st.lane] = {
                "queue_s": st.queue_s, "compute_s": st.compute_s, "variant": "mixed",
            }
            if not st.future.done():
                out = (
                    st.outs[0] if len(st.outs) == 1
                    else np.concatenate(st.outs, axis=1)
                )
                st.future.set_result(out)
        elif len(self._prefill_queue) > 1:
            self._prefill_queue.append(self._prefill_queue.pop(0))

    async def prefill_lane(
        self, lane: int, hidden: np.ndarray, position: int
    ) -> np.ndarray:
        """Admit a multi-token prefill (hidden [1, seq, hidden]) for a PAGED
        lane into the mixed-step queue: pages for the whole range are
        allocated up front (this await is the only blocking point), then the
        flush loop feeds one bucketed, page-aligned chunk per tick alongside
        every pending decode lane — one jitted program per tick, no lane
        extract/insert, no stop-the-world chunks (contrast
        run_exclusive_chunks, which remains the dense-pool fallback).
        Returns the span output for the whole range, token-identical to the
        exclusive path."""
        if self.page_size is None:
            raise RuntimeError("prefill_lane requires the paged lane pool")
        async with self._lane_busy(lane):
            self._check_lane(lane)
            total = int(hidden.shape[1])
            position = int(position)
            if position + total > self.max_length:
                raise ValueError(
                    f"Prefill of {total} tokens at position {position} overflows "
                    f"the lane buffer ({self.max_length} tokens)"
                )
            await self.prepare_write(
                lane, position, position + total, timeout=self.alloc_timeout
            )
            plan = self.backend.chunk_plan(
                1, total, kv_buf_len=self.max_length,
                page_size=self.page_size, start=position,
            )
            st = _LanePrefillState(
                future=asyncio.get_running_loop().create_future(),
                generation=self._lane_generation[lane],
                lane=lane,
                hidden=np.ascontiguousarray(np.asarray(hidden, np.float32)),
                position=position,
                offset=0,
                cap=int(max(plan)),
                n_total=position + total,
                outs=[],
                enqueued=time.perf_counter(),
            )
            self._prefill_queue.append(st)
            self._spawn_flush_loop()
            try:
                return await st.future
            finally:
                if st in self._prefill_queue:
                    self._prefill_queue.remove(st)

    async def generate_lane(
        self, lane: int, last_hidden: np.ndarray, position: int,
        n_tokens: int, sampling: Optional[dict] = None,
    ) -> np.ndarray:
        """Server-side generation ON the pooled lane: sample ``n_tokens``
        starting from ``last_hidden`` (the span output of the last fed
        token), feeding n_tokens - 1 of them into the lane's KV starting at
        ``position`` (the final token stays unfed — the session resume
        convention shared with backend.generate_tokens). Unlike the old
        run_exclusive monopoly, the per-token loop lives in the flush loop:
        every step batches THIS lane with every other generating lane and any
        ordinary decode traffic into one compiled program.

        ``sampling`` is a validated rpc/protocol.validate_gen_sampling dict
        (None -> greedy). Returns tokens [1, n_tokens] int32."""
        if self.gen_params is None:
            raise RuntimeError("This batcher has no client leaves loaded for server-gen")
        async with self._lane_busy(lane):
            self._check_lane(lane)
            if position + n_tokens - 1 > self.max_length:
                raise ValueError(
                    f"Generating {n_tokens} tokens at position {position} overflows "
                    f"the lane buffer ({self.max_length} tokens)"
                )
            if self.page_size is not None and n_tokens > 1:
                # reserve the whole stream's pages up front: the flush loop can't
                # await page allocation mid-generation
                await self.prepare_write(lane, int(position), int(position) + int(n_tokens) - 1)

            # bootstrap: t0 comes from the caller's hidden, not a pool step —
            # submitted through the queue so it serializes with batched steps
            def boot():
                self._check_lane(lane)
                return self.backend.sample_from_hidden(
                    self.gen_params, last_hidden, sampling
                )

            t0 = int((await self.queue.submit(
                boot, priority=PRIORITY_INFERENCE, size=1
            ))[0])
            if n_tokens <= 1:
                return np.asarray([[t0]], np.int32)

            st = _LaneGenState(
                future=asyncio.get_running_loop().create_future(),
                generation=self._lane_generation[lane],
                token=t0, position=int(position), remaining=int(n_tokens) - 1,
                collected=[t0], enqueued=time.perf_counter(),
            )
            if sampling is not None:
                st.do_sample = bool(sampling.get("do_sample", False))
                st.temperature = float(sampling.get("temperature", 1.0))
                st.top_k = int(sampling.get("top_k", 0) or 0)
                st.top_p = float(sampling.get("top_p", 1.0) or 1.0)
                st.repetition_penalty = float(
                    sampling.get("repetition_penalty", 1.0) or 1.0
                )
                st.seed = int(sampling.get("seed", 0))
                st.draw_idx = int(sampling.get("offset", 0)) + 1
                # the draft model conditions on (context + collected); a
                # missing context only costs acceptance rate, never parity
                ctx = sampling.get("context")
                if ctx:
                    st.context = [int(t) for t in ctx]
                if st.repetition_penalty != 1.0:
                    vocab = self.backend.cfg.vocab_size
                    seen = np.zeros((vocab,), bool)
                    for t in sampling.get("context") or ():
                        if 0 <= int(t) < vocab:
                            seen[int(t)] = True
                    if 0 <= t0 < vocab:
                        seen[t0] = True
                    st.seen = seen
            self._gen_states[lane] = st
            self._spawn_flush_loop()
            try:
                return await st.future
            finally:
                if self._gen_states.get(lane) is st:
                    del self._gen_states[lane]

    def _maybe_reset_pool(self) -> None:
        """A failed batched step may have CONSUMED the donated pool buffers.
        Zero the pool and invalidate every outstanding lane (generation bump)
        — their KV is unrecoverable, and letting tenants silently decode
        against zeros would corrupt outputs; their next step errors instead,
        so clients re-open through the normal failover path."""
        if self._handles is None:
            return
        try:
            k_pool, v_pool = self._buffers()
            broken = k_pool.is_deleted() or v_pool.is_deleted()
        except Exception as e:
            logger.debug("Pool liveness probe raised (treating as consumed): %r", e)
            broken = True
        if not broken:
            return  # routine failures (cancellation, rejects) leave the pool intact
        if self._lockstep:
            # a consumed pool under lockstep means a device op died mid-
            # collective: the GROUP is degraded (multihost._degrade_on_failure)
            # and every subsequent op fails loudly through _check_group. A
            # leader-local reset would both desync the workers' mirrors and
            # hang (rematerializing a cross-process-sharded buffer is itself
            # a collective the workers aren't entering).
            with self._reset_lock:
                self._generation += 1
            logger.warning(
                "Pool-consuming lockstep op failed: invalidating outstanding "
                "pooled sessions (group degradation handles the rest)"
            )
            return
        logger.warning(
            "Pool-touching step failed with the donated buffers consumed: "
            "resetting the lane pool; outstanding pooled sessions are invalidated"
        )
        with self._reset_lock:
            self._generation += 1
            if self.page_size is not None:
                # every table reference died with the lanes; rebuild the
                # allocator and bump the epoch so prefix-cache pins taken
                # against the old pool become no-op unpins. Swap entries
                # target the dead generation too: drop them, freeing their
                # host bytes — suspended sessions fail loudly via _check_lane
                self._scheduler.reset()
                self._page_epoch += 1
                if self._pages is not None:
                    # wake prepare_write waiters parked on the dead allocator
                    # so they observe the swap and fail loudly
                    self._pages.freed_event.set()
                self._pages = PageAllocator(self.n_pages)
                if self._tables is not None:
                    self._tables[:] = -1
                    self._tables_mutated()
            for handle in self._handles or ():
                try:
                    self.memory_cache.reset_buffer(handle)
                except KeyError:
                    pass  # racing close(): handles already freed

    def _run_batch(self, batch) -> np.ndarray:
        """Compute-thread body: ONE jitted step for every pending lane."""
        # generation guards on BOTH sides of the device step: an exclusive
        # op's failure can reset the pool from the event loop while this
        # task is queued or mid-flight, and decoding against the
        # rematerialized zeros must fail loudly, never resolve futures
        if batch and batch[0][4] != self._generation:
            raise AllocationFailed("Lane pool was reset before this batched step ran")
        t_step = time.perf_counter()
        hsz = self.backend.hidden_size
        hidden = np.zeros((self.n_lanes, 1, hsz), np.float32)
        positions = np.full((self.n_lanes,), self.max_length, np.int32)  # idle sentinel
        for lane, h, pos, _fut, _gen in batch:
            hidden[lane] = np.asarray(h, np.float32).reshape(1, hsz)
            positions[lane] = pos
        k_pool, v_pool = self._buffers()
        if self.page_size is not None:
            # snapshot the tables: the event loop may grow OTHER lanes while
            # this step runs, but never slots this step reads unmasked or
            # writes (prepare_write ran before each entry was enqueued)
            out, (k_pool, v_pool) = self.backend.paged_decode_step(
                hidden, (k_pool, v_pool), positions, self._tables.copy(),
                handles=self._handles,
            )
        else:
            out, (k_pool, v_pool) = self.backend.batched_decode_step(
                hidden, (k_pool, v_pool), positions, handles=self._handles
            )
        host_out = np.asarray(out)  # device sync: the step has fully executed
        with self._reset_lock:
            if batch and batch[0][4] != self._generation:
                # the reset landed while this step executed: the buffers it
                # read were either consumed (we would have raised) or already
                # zeroed. Checked atomically with the swap (under the reset
                # lock) so the freshly reset pool stays zeroed — swapping in
                # the stale stepped buffers would silently break the 'reset
                # leaves a zeroed pool' recovery invariant.
                raise AllocationFailed("Lane pool was reset while this batched step ran")
            self._update(k_pool, v_pool)
        self.stats["batched_steps"] += 1
        self.stats["batched_tokens"] += len(batch)
        self.stats["max_batch"] = max(self.stats["max_batch"], len(batch))
        duration = time.perf_counter() - t_step
        if self.page_size is not None:
            tm.STEP_PAGED.observe(duration)
            tm.STEPS_PAGED.inc()
        else:
            tm.STEP_DENSE.observe(duration)
            tm.STEPS_DENSE.inc()
        tm.DECODE_TOKENS.inc(len(batch))
        self._record_decode_timing(batch, t_step, duration)
        self._capture_step_fp([entry[0] for entry in batch])
        self._ledger_account_step(
            duration, decode_lanes=[entry[0] for entry in batch]
        )
        return host_out

    def _record_decode_timing(self, batch, t_step: float, duration: float) -> None:
        """Per-lane queue/compute split for the handler's step_meta: queue is
        enqueue -> compute start, compute is the shared batched-step wall (the
        lane rode the whole program). Runs on the compute thread; see _enq_t."""
        variant = "paged" if self.page_size is not None else "dense"
        for lane, _h, _pos, _fut, _gen in batch:
            enq = self._enq_t.pop(lane, None)
            self._step_timing[lane] = {
                "queue_s": max(t_step - enq, 0.0) if enq is not None else 0.0,
                "compute_s": duration,
                "variant": variant,
            }

    def _ledger_account_step(
        self, duration: float, *, decode_lanes=(), gen_lanes=(), prefill=None
    ) -> None:
        """Ledger attribution of one batched tick (compute thread): the
        step's wall time splits EQUALLY across the lanes that rode it — the
        whole-step wall that step_meta reports per lane would multiply-count
        shared compute — plus one decode token per decode/gen lane and the
        prefill chunk's token count. ``prefill`` is (lane, take)."""
        keys = []
        for lane in decode_lanes:
            key = self._ledger_keys.get(lane)
            if key is not None:
                keys.append(key)
                self._ledger.note_tokens(key, decode=1)
        for lane in gen_lanes:
            key = self._ledger_keys.get(lane)
            if key is not None:
                keys.append(key)
                self._ledger.note_tokens(key, decode=1)
        if prefill is not None:
            lane, take = prefill
            key = self._ledger_keys.get(lane)
            if key is not None:
                keys.append(key)
                self._ledger.note_tokens(key, prefill=int(take))
        self._ledger.note_compute(keys, duration)

    def _run_batch_mixed(self, batch, pf) -> Tuple[np.ndarray, np.ndarray]:
        """Compute-thread body: ONE jitted step advancing every pending
        decode lane AND one prefill chunk together (backend.paged_mixed_step).
        The prefill lane rides the decode half at the idle sentinel, so its
        decode-side write drops; its tokens ride the prefill half."""
        st, take = pf
        expected = batch[0][4] if batch else st.generation
        if expected != self._generation or st.generation != self._generation:
            raise AllocationFailed("Lane pool was reset before this batched step ran")
        t_step = time.perf_counter()
        hsz = self.backend.hidden_size
        hidden = np.zeros((self.n_lanes, 1, hsz), np.float32)
        positions = np.full((self.n_lanes,), self.max_length, np.int32)  # idle sentinel
        for lane, h, pos, _fut, _gen in batch:
            hidden[lane] = np.asarray(h, np.float32).reshape(1, hsz)
            positions[lane] = pos
        chunk = st.hidden[:, st.offset : st.offset + take]
        k_pool, v_pool = self._buffers()
        out, chunk_out, (k_pool, v_pool) = self.backend.paged_mixed_step(
            hidden, (k_pool, v_pool), positions, self._tables.copy(),
            chunk, st.lane, st.position, n_total=st.n_total,
            handles=self._handles,
        )
        host_out = np.asarray(out)  # device sync: the step has fully executed
        host_chunk = np.asarray(chunk_out)
        with self._reset_lock:
            if expected != self._generation:
                # see _run_batch: checked atomically with the swap so a reset
                # landing mid-step leaves the freshly zeroed pool in place
                raise AllocationFailed("Lane pool was reset while this batched step ran")
            self._update(k_pool, v_pool)
        self.stats["batched_steps"] += 1
        self.stats["batched_tokens"] += len(batch)
        self.stats["max_batch"] = max(self.stats["max_batch"], len(batch))
        self.stats["mixed_steps"] += 1
        self.stats["prefill_tokens"] += take
        self.stats["max_prefill_tokens_per_step"] = max(
            self.stats["max_prefill_tokens_per_step"], take
        )
        duration = time.perf_counter() - t_step
        tm.STEP_MIXED.observe(duration)
        tm.STEPS_MIXED.inc()
        tm.DECODE_TOKENS.inc(len(batch))
        self._record_decode_timing(batch, t_step, duration)
        self._capture_step_fp(
            [entry[0] for entry in batch], chunk_lane=st.lane
        )
        self._ledger_account_step(
            duration,
            decode_lanes=[entry[0] for entry in batch],
            prefill=(st.lane, take),
        )
        st.compute_s += duration  # whole-prefill compute accumulates per chunk
        return host_out, host_chunk

    def _run_batch_gen(self, batch, gen_states) -> Tuple[np.ndarray, np.ndarray]:
        """Compute-thread body: one jitted step advancing every pending decode
        lane AND every generating lane together (the client leaves embed the
        gen lanes' tokens and sample their next ones on device)."""
        expected = (
            batch[0][4] if batch
            else next(iter(gen_states.values())).generation
        )
        if expected != self._generation or any(
            st.generation != self._generation for st in gen_states.values()
        ):
            raise AllocationFailed("Lane pool was reset before this batched step ran")
        t_step = time.perf_counter()
        hsz = self.backend.hidden_size
        hidden = np.zeros((self.n_lanes, 1, hsz), np.float32)
        positions = np.full((self.n_lanes,), self.max_length, np.int32)  # idle sentinel
        tokens = np.zeros((self.n_lanes,), np.int32)
        use_token = np.zeros((self.n_lanes,), bool)
        vecs = sampling_vectors(self.n_lanes, self.backend.cfg.vocab_size)
        for lane, h, pos, _fut, _gen in batch:
            hidden[lane] = np.asarray(h, np.float32).reshape(1, hsz)
            positions[lane] = pos
        for lane, st in gen_states.items():
            tokens[lane] = st.token
            use_token[lane] = True
            positions[lane] = st.position
            vecs["do_sample"][lane] = st.do_sample
            vecs["temperature"][lane] = st.temperature
            vecs["top_k"][lane] = st.top_k
            vecs["top_p"][lane] = st.top_p
            vecs["repetition_penalty"][lane] = st.repetition_penalty
            vecs["seeds"][lane] = st.seed
            vecs["draw_idx"][lane] = st.draw_idx
            if st.seen is not None:
                vecs["seen_mask"][lane] = st.seen
        k_pool, v_pool = self._buffers()
        if self.page_size is not None:
            out, toks, (k_pool, v_pool) = self.backend.paged_gen_decode_step(
                self.gen_params, hidden, tokens, use_token, (k_pool, v_pool),
                positions, self._tables.copy(), sampling_vecs=vecs,
                handles=self._handles,
            )
        else:
            out, toks, (k_pool, v_pool) = self.backend.batched_gen_decode_step(
                self.gen_params, hidden, tokens, use_token, (k_pool, v_pool),
                positions, sampling_vecs=vecs, handles=self._handles,
            )
        host_out = np.asarray(out)  # device sync: the step has fully executed
        host_toks = np.asarray(toks)
        with self._reset_lock:
            if expected != self._generation:
                # see _run_batch: checked atomically with the swap so a reset
                # landing mid-step leaves the freshly zeroed pool in place
                raise AllocationFailed("Lane pool was reset while this batched step ran")
            self._update(k_pool, v_pool)
        self.stats["batched_steps"] += 1
        self.stats["batched_tokens"] += len(batch) + len(gen_states)
        self.stats["max_batch"] = max(
            self.stats["max_batch"], len(batch) + len(gen_states)
        )
        self.stats["gen_steps"] += 1
        self.stats["gen_lane_tokens"] += len(gen_states)
        self.stats["max_gen_lanes"] = max(
            self.stats["max_gen_lanes"], len(gen_states)
        )
        duration = time.perf_counter() - t_step
        tm.STEP_GEN.observe(duration)
        tm.STEPS_GEN.inc()
        tm.DECODE_TOKENS.inc(len(batch) + len(gen_states))
        self._record_decode_timing(batch, t_step, duration)
        self._capture_step_fp([entry[0] for entry in batch] + list(gen_states))
        self._ledger_account_step(
            duration,
            decode_lanes=[entry[0] for entry in batch],
            gen_lanes=list(gen_states),
        )
        for st in gen_states.values():
            if not st.started:
                st.started = True
                st.queue_s = max(t_step - st.enqueued, 0.0) if st.enqueued else 0.0
            st.compute_s += duration
        return host_out, host_toks

    def _run_batch_spec(self, spec_states) -> Tuple[np.ndarray, np.ndarray]:
        """Compute-thread body for one speculative tick: the draft proposes
        k tokens per speculating lane, then ONE verify step (backend.
        paged_spec_verify_step) feeds [last committed token, k drafts] at
        positions p..p+k, samples the target's own token for every row from
        the lane's seed+offset PRNG stream, and returns the emitted prefix
        per lane. Non-speculating lanes ride at the idle sentinel. Returns
        (g_hat [n_lanes, spec_k+1], n_emit [n_lanes]); the event loop
        commits g_hat[lane, :n_emit[lane]] (_commit_spec_results).

        Ledger honesty: the WHOLE tick wall (draft + verify, both on this
        thread) splits equally across the speculating lanes via the normal
        note_compute path — conservation holds unchanged — and the draft's
        share is additionally recorded per lane as the draft_seconds
        'of which' annotation, with proposed/accepted counts feeding the
        per-peer acceptance_rate (/ledger, step_meta usage)."""
        expected = next(iter(spec_states.values())).generation
        if expected != self._generation or any(
            st.generation != self._generation for st in spec_states.values()
        ):
            raise AllocationFailed("Lane pool was reset before this batched step ran")
        if self._draft_warmed is not self.draft:
            # compile every propose bucket before the first measured tick so
            # later lane-count mixes never compile (spec_decode.DraftModel)
            self.draft.warmup(self.n_lanes)
            self._draft_warmed = self.draft
        t_step = time.perf_counter()
        S = self.spec_k + 1
        contexts: List[Optional[List[int]]] = [None] * self.n_lanes
        for lane, st in spec_states.items():
            contexts[lane] = (st.context or []) + st.collected
        drafts = self.draft.propose(contexts)  # [n_lanes, spec_k] greedy
        draft_s = time.perf_counter() - t_step
        tokens = np.zeros((self.n_lanes, S), np.int32)
        positions = np.full((self.n_lanes,), self.max_length, np.int32)  # idle sentinel
        vecs = sampling_vectors(self.n_lanes, self.backend.cfg.vocab_size)
        for lane, st in spec_states.items():
            tokens[lane, 0] = st.token
            tokens[lane, 1:] = drafts[lane]
            positions[lane] = st.position
            vecs["do_sample"][lane] = st.do_sample
            vecs["temperature"][lane] = st.temperature
            vecs["top_k"][lane] = st.top_k
            vecs["top_p"][lane] = st.top_p
            vecs["repetition_penalty"][lane] = st.repetition_penalty
            vecs["seeds"][lane] = st.seed
            vecs["draw_idx"][lane] = st.draw_idx
            if st.seen is not None:
                vecs["seen_mask"][lane] = st.seen
        k_pool, v_pool = self._buffers()
        g_hat, n_emit, (k_pool, v_pool) = self.backend.paged_spec_verify_step(
            self.gen_params, tokens, (k_pool, v_pool), positions,
            self._tables.copy(), sampling_vecs=vecs, handles=self._handles,
        )
        host_g = np.asarray(g_hat)  # device sync: the step has fully executed
        host_m = np.asarray(n_emit)
        with self._reset_lock:
            if expected != self._generation:
                # see _run_batch: checked atomically with the swap so a reset
                # landing mid-step leaves the freshly zeroed pool in place
                raise AllocationFailed("Lane pool was reset while this batched step ran")
            self._update(k_pool, v_pool)
        n_spec = len(spec_states)
        emitted_total = int(sum(int(host_m[lane]) for lane in spec_states))
        accepted_total = emitted_total - n_spec  # one bonus token per lane
        proposed_total = n_spec * self.spec_k
        self.stats["batched_steps"] += 1
        self.stats["batched_tokens"] += emitted_total
        self.stats["spec_steps"] += 1
        self.stats["spec_proposed"] += proposed_total
        self.stats["spec_accepted"] += accepted_total
        self.stats["max_spec_lanes"] = max(self.stats["max_spec_lanes"], n_spec)
        duration = time.perf_counter() - t_step
        tm.STEP_SPEC.observe(duration)
        tm.STEPS_SPEC.inc()
        tm.DECODE_TOKENS.inc(emitted_total)
        tm.SPEC_PROPOSED.inc(proposed_total)
        tm.SPEC_ACCEPTED.inc(accepted_total)
        self._capture_step_fp(list(spec_states))
        keys = []
        per_lane_draft = draft_s / n_spec
        for lane, st in spec_states.items():
            key = self._ledger_keys.get(lane)
            if key is not None:
                keys.append(key)
                self._ledger.note_tokens(key, decode=int(host_m[lane]))
                self._ledger.note_spec(
                    key, draft_seconds=per_lane_draft,
                    proposed=self.spec_k, accepted=int(host_m[lane]) - 1,
                )
        self._ledger.note_compute(keys, duration)
        for st in spec_states.values():
            if not st.started:
                st.started = True
                st.queue_s = max(t_step - st.enqueued, 0.0) if st.enqueued else 0.0
            st.compute_s += duration
        return host_g, host_m

    # ------------------------------------------------------- non-batchable ops

    def _new_temp(self) -> Optional[tuple]:
        """Synthetic mirror handles for an extracted lane under lockstep
        (None otherwise): exclusive-op fns pass these to the backend so
        workers address their copy of the checked-out lane."""
        if not self._lockstep:
            return None
        t = next(self._temp_ids)
        return (t, t)

    def _extract_lane(self, lane: int, temp: Optional[tuple] = None):
        """Compute-thread body: lane checked OUT of the pool as session-shaped
        [n_blocks, 1, max_len, hkv, d] buffers (broadcast under lockstep so
        workers mirror the copy under ``temp``)."""
        k_pool, v_pool = self._buffers()
        if temp is not None:
            return self.backend.lane_extract(
                k_pool, v_pool, lane,
                pool_handle=self._handles[0], temp_handle=temp[0],
            )
        if self.page_size is not None:
            # gather the lane's pages into the session-shaped dense view the
            # exclusive fns (prefill, kv import) expect — same layout as the
            # dense pool's lane, so those fns are mode-oblivious
            return self.backend._paged_lane_gather_fn(
                k_pool, v_pool, self._tables[lane].copy()
            )
        return self.backend._lane_extract_fn(k_pool, v_pool, np.int32(lane))

    def _insert_lane(self, lane: int, kv_lane, temp: Optional[tuple] = None) -> None:
        """Compute-thread body: lane checked back IN. The whole read-insert-
        swap runs under the reset lock: a reset landing mid-way would
        otherwise let the insert donate the freshly zeroed pool's buffers (or
        swap stale pre-reset buffers back in), breaking the 'reset leaves a
        zeroed pool' invariant — the same TOCTOU _run_batch guards against.
        The lane check raises BEFORE any buffer is donated, so a failed
        insert leaves the new pool untouched."""
        k2, v2 = kv_lane
        with self._reset_lock:
            self._check_lane(lane)
            k_pool, v_pool = self._buffers()
            if temp is not None:
                k_pool, v_pool = self.backend.lane_insert(
                    k_pool, v_pool, (k2, v2), lane,
                    pool_handle=self._handles[0], temp_handle=temp[0],
                )
            elif self.page_size is not None:
                # scatter the dense lane view back through the block table;
                # unallocated (-1) slots drop, so content past the session's
                # resident pages never lands anywhere
                k_pool, v_pool = self.backend._paged_lane_scatter_fn(
                    k_pool, v_pool, k2, v2, self._tables[lane].copy()
                )
            else:
                k_pool, v_pool = self.backend._lane_insert_fn(
                    k_pool, v_pool, k2, v2, np.int32(lane)
                )
            self._update(k_pool, v_pool)

    def _release_temp(self, temp: Optional[tuple]) -> None:
        """Best-effort drop of a synthetic lockstep mirror that will NOT be
        inserted back (a failed/cancelled exclusive op): without the OP_FREE
        broadcast every worker would retain a full lane-sized KV copy per
        failure — an unbounded leak under repeated client disconnects."""
        if temp is None:
            return
        try:
            self.backend.release_temp(temp[0])
        except Exception:  # swarmlint: disable=no-silent-except — best-effort by contract: a degraded lockstep group already dropped the mirrors with its workers
            pass

    async def run_exclusive(
        self, lane: int, fn, *, size: int = 0, extract: bool = True,
        write_range: Optional[Tuple[int, int]] = None,
    ):
        """Run ``fn(kv_lane, lane_handles) -> (result, kv_lane')`` with the
        lane extracted into session-shaped buffers, then insert the updated
        lane back — all in ONE atomic queue task. Used for KV import and any
        step the batched program doesn't cover. Serialized with batched steps
        by the queue. ``lane_handles`` is None single-host; under lockstep it
        is the synthetic mirror handle pair the fn must pass to the backend
        (e.g. ``backend.inference_step(..., handles=lane_handles)``).
        ``extract=False`` skips the checkout (fn receives kv_lane=None) for
        ops that wholesale REPLACE the lane (prefix seed, kv import) — under
        lockstep that saves every process a full-lane device copy.
        ``write_range=(t0, t1)`` declares the token range the fn writes:
        paged mode allocates/forks those pages up front (prepare_write) so
        the check-in scatter has somewhere to land."""

        async with self._lane_busy(lane):
            self._check_lane(lane)
            if self.page_size is not None and write_range is not None:
                await self.prepare_write(lane, int(write_range[0]), int(write_range[1]))
            # exclusive ops run alone on the device: their whole wall bills
            # to this one tenant, and a declared write range is prompt
            # tokens landing in its cache (dense-prefill / kv-import path)
            ledger_key = self._ledger_keys.get(lane)
            if ledger_key is not None and write_range is not None:
                self._ledger.note_tokens(
                    ledger_key, prefill=int(write_range[1]) - int(write_range[0])
                )

            def run():
                self._check_lane(lane)  # re-check: a reset may have raced the queue
                temp = self._new_temp()
                t_run = time.perf_counter()
                try:
                    kv_lane = self._extract_lane(lane, temp) if extract else None
                    result, kv_lane = fn(kv_lane, temp)
                    self._insert_lane(lane, kv_lane, temp)
                except BaseException:
                    self._release_temp(temp)
                    raise
                if ledger_key is not None:
                    self._ledger.note_compute(
                        [ledger_key], time.perf_counter() - t_run
                    )
                return result

            try:
                return await self.queue.submit(run, priority=PRIORITY_INFERENCE, size=size)
            except AllocationFailed:
                raise
            except BaseException:
                # exclusive ops donate the pool buffers too (_lane_insert_fn):
                # a failure here can consume them just like a batched step
                self._maybe_reset_pool()
                raise

    async def run_exclusive_chunks(
        self, lane: int, chunk_fns, *, size: int = 0,
        write_range: Optional[Tuple[int, int]] = None,
    ):
        """Chunked-prefill interleaving (Sarathi-style): extract the lane
        once, run each ``fn(kv_lane, lane_handles) -> (result, kv_lane')`` as
        its OWN priority-queue task, insert once. Between chunks the flush
        loop's batched decode steps run freely — a long prefill no longer
        stalls every decoding session for its full length. Safe while checked
        out: batched steps never write an idle-sentinel lane, and the FIFO
        queue guarantees the final insert lands before any new tenant's first
        task even if this session is cancelled mid-chunks (stale content
        beyond a tenant's position is masked by attention anyway)."""
        async with self._lane_busy(lane):
            return await self._run_exclusive_chunks(
                lane, chunk_fns, size=size, write_range=write_range
            )

    async def _run_exclusive_chunks(
        self, lane: int, chunk_fns, *, size: int = 0,
        write_range: Optional[Tuple[int, int]] = None,
    ):
        self._check_lane(lane)
        if self.page_size is not None and write_range is not None:
            await self.prepare_write(lane, int(write_range[0]), int(write_range[1]))
        ledger_key = self._ledger_keys.get(lane)
        if ledger_key is not None and write_range is not None:
            # bill the whole declared prompt span once, up front (the chunks
            # below and the single-chunk delegation never re-declare it)
            self._ledger.note_tokens(
                ledger_key, prefill=int(write_range[1]) - int(write_range[0])
            )
        if len(chunk_fns) == 1:
            # short prefills skip the extract/insert round-trips
            return [await self.run_exclusive(lane, chunk_fns[0], size=size)]
        state = {}

        def extract():
            self._check_lane(lane)  # re-check: a reset may have raced the queue
            state["temp"] = self._new_temp()
            state["kv"] = self._extract_lane(lane, state["temp"])

        def insert():
            self._check_lane(lane)  # a stale lane's data must not be re-inserted
            self._insert_lane(lane, state["kv"], state["temp"])

        try:
            await self.queue.submit(extract, priority=PRIORITY_INFERENCE, size=0)
        except BaseException:
            # a leader-side failure AFTER the extract broadcast leaves the
            # workers holding the temp mirror: free it before propagating
            self._release_temp(state.get("temp"))
            raise
        results = []
        try:
            for fn in chunk_fns:
                def run_chunk(fn=fn):
                    self._check_lane(lane)
                    t_run = time.perf_counter()
                    res, state["kv"] = fn(state["kv"], state["temp"])
                    self.stats["exclusive_chunks"] += 1
                    if ledger_key is not None:
                        self._ledger.note_compute(
                            [ledger_key], time.perf_counter() - t_run
                        )
                    return res

                try:
                    results.append(
                        await self.queue.submit(run_chunk, priority=PRIORITY_INFERENCE, size=size)
                    )
                except AllocationFailed:
                    raise
                except BaseException:
                    self._maybe_reset_pool()
                    raise
        finally:
            # always check the lane back in (a failed chunk leaves the last
            # consistent kv; the session's host-side position was not advanced)
            inserted = False
            if "kv" in state:
                try:
                    await self.queue.submit(insert, priority=PRIORITY_INFERENCE, size=0)
                    inserted = True
                except AllocationFailed:
                    pass  # lane invalidated mid-prefill: nothing to check in
                except BaseException:
                    self._maybe_reset_pool()
                    raise
                finally:
                    if not inserted:
                        # the workers' temp mirror will never be consumed by
                        # an insert: free it or it leaks a lane-sized buffer
                        self._release_temp(state.get("temp"))
        return results

    async def snapshot_lane(
        self, lane: int, position: int, b0: int, b1: int,
        *, return_device: bool = False,
    ):
        """Host copy of blocks [b0, b1) of a lane, sliced to ``position``
        (KV export/migration for pooled sessions). Under lockstep the lane's
        shards live on every process: a read-only extract registers a temp
        mirror, the export all_gather runs through it, and the temp is
        released (never inserted back — nothing was modified).

        ``return_device=True`` returns ``(k, v, k_dev, v_dev)`` where the
        device pair are the same slices still resident in HBM (None under
        lockstep, whose shards are per-process) — the prefix cache's device
        tier pins these so a later hit can seed without re-uploading."""

        self._check_lane(lane)

        def run():
            self._check_lane(lane)  # re-check: a reset may have raced the queue
            temp = self._new_temp()
            if temp is not None:
                kv_lane = self._extract_lane(lane, temp)
                try:
                    k, v = self.backend.export_kv(
                        temp, lambda: kv_lane, b0, b1, position
                    )
                    return (k, v, None, None) if return_device else (k, v)
                finally:
                    self.backend.release_temp(temp[0])
            k_pool, v_pool = self._buffers()
            if self.page_size is not None:
                k, v = self.backend._paged_lane_gather_fn(
                    k_pool, v_pool, self._tables[lane].copy()
                )
            else:
                k, v = self.backend._lane_extract_fn(k_pool, v_pool, np.int32(lane))
            kd = k[b0:b1, :, :position]
            vd = v[b0:b1, :, :position]
            host = (np.asarray(kd), np.asarray(vd))
            return (*host, kd, vd) if return_device else host

        async with self._lane_busy(lane):
            return await self.queue.submit(run, priority=PRIORITY_INFERENCE, size=0)

    async def snapshot_from_swap(self, lane: int, position: int, b0: int, b1: int):
        """Host KV snapshot of a SUSPENDED lane assembled straight from its
        SwapEntry — pure numpy, no device work. ``snapshot_lane`` would first
        swap the lane back IN (``_lane_busy`` -> ``_ensure_resident``),
        burning pool pages and two device copies just to read bytes that
        already sit in host RAM; a draining server parking its preempted
        tenants hits exactly that case. Returns ``(k, v)`` shaped like
        ``snapshot_lane``'s host pair, or None when the lane isn't suspended,
        is busy, or its swap entry doesn't cover ``[0, position)`` — the
        caller falls back to the device path."""
        if self.page_size is None:
            return None
        slot = self._scheduler.lanes.get(lane)
        if slot is None:
            return None
        lock = self._lane_lock(lane)
        # trylock (no sanitizer order edge): busy means a step or resume is
        # mid-flight — the device path serializes behind it correctly
        if not lock_try_acquire_nowait(lock):
            return None
        try:
            entry = slot.swap
            if entry is None or slot.suspending:
                return None
            ps = self.page_size
            n_slots = -(-position // ps)  # ceil: table slots covering [0, position)
            index_of = {int(s): i for i, s in enumerate(entry.slots)}
            if any(s not in index_of for s in range(n_slots)):
                return None  # partial residency: only the pool knows the rest

            def assemble():
                from petals_tpu.ops.paged_attention import PagedPool, dequantize_kv_np

                quantized = isinstance(entry.k, PagedPool)
                if quantized:
                    # packed swap entry: dequantize the covered slots to the
                    # dense fp view the snapshot contract promises
                    hkv = entry.k.scales.shape[-1]
                    d = entry.k.shape[-1]  # logical (PagedPool.shape unpacks)
                    out_dtype = np.float32
                else:
                    hkv, d = entry.k.shape[-2], entry.k.shape[-1]
                    out_dtype = entry.k.dtype
                nb = b1 - b0
                k_out = np.zeros((nb, 1, position, hkv, d), out_dtype)
                v_out = np.zeros((nb, 1, position, hkv, d), out_dtype)
                for s in range(n_slots):
                    i = index_of[s]
                    t0, t1 = s * ps, min((s + 1) * ps, position)
                    if quantized:
                        kind = entry.k.kind
                        k_out[:, 0, t0:t1] = dequantize_kv_np(
                            entry.k.codes[b0:b1, i, : t1 - t0],
                            entry.k.scales[b0:b1, i, : t1 - t0], kind,
                        )
                        v_out[:, 0, t0:t1] = dequantize_kv_np(
                            entry.v.codes[b0:b1, i, : t1 - t0],
                            entry.v.scales[b0:b1, i, : t1 - t0], kind,
                        )
                    else:
                        k_out[:, 0, t0:t1] = entry.k[b0:b1, i, : t1 - t0]
                        v_out[:, 0, t0:t1] = entry.v[b0:b1, i, : t1 - t0]
                return k_out, v_out

            # the lane lock stays held across the copy so a racing resume
            # can't consume the entry mid-assembly; it's a trylock, so the
            # sanitizer's await-under-lock rule is not in play
            return await asyncio.to_thread(assemble)
        finally:
            lock.release()
