"""Block sizing + auto num_blocks choice
(counterpart of reference src/petals/server/block_utils.py:12-65 +
server.py:275-326 `_choose_num_blocks`)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from petals_tpu.ops.quant import BITS_PER_PARAM
from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)

AUTOGRAD_RESERVE_FRACTION = 0.15  # headroom for activations/backward buffers


def block_params_count(family, cfg) -> int:
    shapes = family.block_param_shapes(cfg, jnp.bfloat16)
    return int(sum(np.prod(s.shape) for s in shapes.values()))


def estimated_block_size_bytes(family, cfg, quant_type: str = "none") -> int:
    """Bytes of one served block at the given quantization
    (reference block_utils.py:22-53; NF4 = 4.25 bits/param)."""
    return int(block_params_count(family, cfg) * BITS_PER_PARAM[quant_type] / 8)


def device_memory_bytes() -> Optional[int]:
    """Total memory of the local accelerator, if the backend reports it."""
    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"])
    except Exception:  # swarmlint: disable=no-silent-except — backend probe: plugins without memory_stats raise freely; the TPU/None fallback below is the answer
        pass
    if jax.default_backend() == "tpu":
        return 16 * 2**30  # v5e per-chip HBM as a fallback
    return None


def choose_num_blocks(
    family,
    cfg,
    *,
    quant_type: str = "none",
    attn_cache_bytes: int = 0,
    memory_limit_bytes: Optional[int] = None,
) -> int:
    """How many blocks fit this chip alongside the KV budget + autograd reserve
    (reference server.py:275-326)."""
    memory = memory_limit_bytes or device_memory_bytes()
    if memory is None:
        logger.warning("Unknown device memory; defaulting to serving all blocks")
        return cfg.num_hidden_layers
    usable = memory * (1 - AUTOGRAD_RESERVE_FRACTION) - attn_cache_bytes
    per_block = estimated_block_size_bytes(family, cfg, quant_type)
    n = max(int(usable // per_block), 1)
    n = min(n, cfg.num_hidden_layers)
    logger.info(
        f"Auto-selected {n} blocks ({per_block / 2**20:.0f} MiB each, "
        f"{memory / 2**30:.1f} GiB device memory, quant={quant_type})"
    )
    return n
