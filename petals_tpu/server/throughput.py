"""Server throughput self-measurement
(counterpart of reference src/petals/server/throughput.py:37-237).

Measures, per block:
- inference_rps: 1-token decode steps/sec through a real jitted block
- forward_rps:   1024-token forward tokens/sec
- network_rps:   how many requests/sec the wire could carry, from a loopback
  serialization+framing probe (the reference shells out to speedtest-cli; a
  private TPU swarm measures its own stack instead — pass --network_mbps to
  override with a known WAN budget)

Results are cached in a fcntl-locked JSON file keyed by (model shape, dtype,
quant, version) — reference throughput.py:53-94.
"""

from __future__ import annotations

import fcntl
import json
import os
import time
from pathlib import Path
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

import petals_tpu
from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)

DEFAULT_CACHE_PATH = Path(os.environ.get("PETALS_TPU_CACHE", Path.home() / ".cache" / "petals_tpu"))
THROUGHPUT_FILE = "throughput_v2.json"  # v2: compute-only entries (network always fresh)
RELAY_PENALTY = 0.2  # reference throughput.py:47


def get_server_throughput(
    family,
    cfg,
    *,
    compute_dtype=jnp.bfloat16,
    n_steps_inference: int = 50,
    n_steps_forward: int = 5,
    network_mbps: Optional[float] = None,
    num_blocks: int = 1,
    using_relay: bool = False,
    quant_type: str = "none",
    num_devices: int = 1,
    cache_dir: Optional[Path] = None,
    force_eval: bool = False,
) -> dict:
    """Returns {"throughput", "inference_rps", "forward_rps", "network_rps"}."""
    cache_dir = Path(cache_dir or DEFAULT_CACHE_PATH)
    cache_dir.mkdir(parents=True, exist_ok=True)
    cache_path = cache_dir / THROUGHPUT_FILE

    # every field that changes the measured speed must be in the key — a
    # server restarted with a different quant/shape/TP setting advertising a
    # stale number would mis-drive routing and block selection swarm-wide
    cache_key = json.dumps(
        {
            "family": family.name,
            "hidden": cfg.hidden_size,
            "intermediate": getattr(cfg, "intermediate_size", None),
            "kv_heads": getattr(cfg, "num_key_value_heads", None),
            "head_dim": getattr(cfg, "head_dim", None),
            "layers_probed": 1,
            "dtype": str(jnp.dtype(compute_dtype).name),
            "quant": str(quant_type),
            "num_devices": int(num_devices),
            "version": petals_tpu.__version__,
            "backend": jax.default_backend(),
        },
        sort_keys=True,
    )

    cache = _read_cache(cache_path)
    if not force_eval and cache_key in cache:
        info = dict(cache[cache_key])
        logger.info(f"Using cached compute throughput: {info}")
    else:
        info = measure_compute_rps(
            family, cfg, compute_dtype=compute_dtype, quant_type=quant_type,
            num_devices=num_devices,
            n_steps_inference=n_steps_inference, n_steps_forward=n_steps_forward,
        )
        if not info.pop("degraded", False):
            cache[cache_key] = info
            _write_cache(cache_path, cache)
        else:
            # degraded single-device estimate of a TP config: never persist it
            # under the TP key, or it would outlive the broken environment
            logger.warning("Not caching single-device estimate for a TP config")
    # the network figure is NEVER cached: the caller's swarm probe (or a
    # --network_mbps override) must always win — a cached compute entry
    # otherwise silently pins the network number from a past environment
    info["network_rps"] = measure_network_rps(cfg.hidden_size, network_mbps=network_mbps)

    # blended throughput (reference throughput.py:96-106): compute spread over
    # the hosted blocks vs what the network can carry
    compute_rps = info["forward_rps"] / max(num_blocks, 1)
    network_rps = info["network_rps"] * (RELAY_PENALTY if using_relay else 1.0)
    return {
        "throughput": min(compute_rps, network_rps),
        "inference_rps": info["inference_rps"],
        "forward_rps": info["forward_rps"],
        "network_rps": network_rps,
    }


def measure_compute_rps(
    family, cfg, *, compute_dtype=jnp.bfloat16, quant_type: str = "none",
    num_devices: int = 1, n_steps_inference: int = 50, n_steps_forward: int = 5,
) -> dict:
    """Benchmark one block through the REAL serving backend — same
    quantization, and the same TP mesh when the devices exist (reference
    throughput.py:190-237 measures the converted block for the same reason:
    the advertised number must describe the path that will serve)."""
    from petals_tpu.server.backend import TransformerBackend
    from petals_tpu.server.memory_cache import MemoryCache

    shapes = family.block_param_shapes(cfg, compute_dtype)
    key = jax.random.PRNGKey(0)
    params = {}
    for name, sds in sorted(shapes.items()):
        key, sub = jax.random.split(key)
        if jnp.issubdtype(sds.dtype, jnp.floating):
            params[name] = jax.random.normal(sub, sds.shape, sds.dtype) * 0.02
        else:
            # integer leaves (gemma2's per-block attn_window) must keep their
            # declared dtype — float noise would cast to a wrong config
            params[name] = jnp.zeros(sds.shape, sds.dtype)
    if "attn_window" in params and getattr(cfg, "layer_types", None):
        # probe block 0's REAL attention pattern: the advertised rps must
        # describe the path that serves (sliding layers cost less than full)
        window = (
            cfg.sliding_window
            if cfg.layer_types[0] == "sliding_attention" else 0
        )
        params["attn_window"] = jnp.asarray(window or 0, jnp.int32)
    if str(quant_type) != "none":
        from petals_tpu.utils.convert_block import convert_block_params

        # mirror the serving config: fused leaves single-chip, unfused under TP
        params = convert_block_params(
            params, family.name, quant_type, fuse=num_devices <= 1
        )
    stacked = jax.tree_util.tree_map(lambda x: x[None] if hasattr(x, "ndim") else x, params)

    mesh = None
    degraded = False
    if num_devices > 1:
        if len(jax.devices()) >= num_devices:
            from petals_tpu.parallel.mesh import tp_mesh

            mesh = tp_mesh(num_devices)
        else:
            degraded = True  # callers must not cache this as the TP number
            logger.warning(
                f"Measuring throughput for num_devices={num_devices} on "
                f"{len(jax.devices())} device(s): figure is a single-device estimate"
            )
    backend = TransformerBackend(
        family, cfg, stacked, first_block=0, n_blocks=1,
        memory_cache=MemoryCache(None), compute_dtype=compute_dtype, mesh=mesh,
    )

    kd, vd = backend.cache_descriptors(1, 256, 0, 1)
    kv = (kd.make_zeros(), vd.make_zeros())
    token = np.zeros((1, 1, cfg.hidden_size), np.float32)

    out, kv = backend.inference_step(token, kv, 0)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for i in range(n_steps_inference):
        out, kv = backend.inference_step(token, kv, i + 1)
    jax.block_until_ready(out)
    inference_rps = n_steps_inference / (time.perf_counter() - t0)

    batch = np.zeros((1, 1024, cfg.hidden_size), np.float32)
    jax.block_until_ready(backend.forward(batch))
    t0 = time.perf_counter()
    for _ in range(n_steps_forward):
        out = backend.forward(batch)
    jax.block_until_ready(out)
    forward_rps = n_steps_forward * 1024 / (time.perf_counter() - t0)

    logger.info(
        f"Measured compute: inference {inference_rps:.1f} steps/s, "
        f"forward {forward_rps:.0f} tok/s per block"
        + (f" (tp={num_devices})" if mesh is not None else "")
    )
    return {"inference_rps": inference_rps, "forward_rps": forward_rps, "degraded": degraded}


def measure_network_rps(hidden_size: int, *, network_mbps: Optional[float] = None) -> float:
    """Tokens/sec the wire can carry at 16 bits/activation element
    (reference throughput.py:147-175; default 100 Mbit/s on probe failure)."""
    if network_mbps is None:
        network_mbps = _loopback_serialization_mbps(hidden_size)
    bits_per_token = hidden_size * 16
    return network_mbps * 1e6 / bits_per_token


def _loopback_serialization_mbps(hidden_size: int) -> float:
    """Measure our own serialize->frame->deserialize path as the bandwidth
    ceiling; fall back to 100 Mbit/s (the reference's default) on failure."""
    try:
        from petals_tpu.rpc.protocol import encode_frame
        from petals_tpu.rpc.serialization import deserialize_array, serialize_array

        arr = np.random.randn(1, 1024, hidden_size).astype(np.float16)
        t0 = time.perf_counter()
        n = 5
        for _ in range(n):
            frame = encode_frame({"tensors": {"hidden": serialize_array(arr)}})
            _ = deserialize_array(
                {"shape": arr.shape, "dtype": "float16", "wire_dtype": "float16",
                 "compression": "none", "data": arr.tobytes()}
            )
        elapsed = time.perf_counter() - t0
        mbps = (n * len(frame) * 8) / elapsed / 1e6
        return min(mbps, 10_000.0)  # cap at 10 Gbit/s sanity bound
    except Exception as e:
        logger.warning(f"Network probe failed ({e}); assuming 100 Mbit/s")
        return 100.0


def _read_cache(path: Path) -> dict:
    try:
        with open(path) as f:
            fcntl.flock(f, fcntl.LOCK_SH)
            try:
                return json.load(f)
            finally:
                fcntl.flock(f, fcntl.LOCK_UN)
    except (FileNotFoundError, json.JSONDecodeError):
        return {}


def _write_cache(path: Path, cache: dict) -> None:
    with open(path, "w") as f:
        fcntl.flock(f, fcntl.LOCK_EX)
        try:
            json.dump(cache, f)
        finally:
            fcntl.flock(f, fcntl.LOCK_UN)
