"""Priority scheduling of compute onto the device
(counterpart of reference src/petals/server/task_pool.py:29-177 +
task_prioritizer.py:6-20).

The reference moves tasks between 8 forked handler processes and one Runtime
process via mp.SimpleQueue + MPFuture + shared memory. A JAX server is a single
process whose device work is dispatched asynchronously by XLA, so the same
guarantees (inference preempts training, FIFO within a class, oversized-task
rejection) reduce to a heap consumed by one worker thread. The worker calls the
jitted step and blocks until the result is ready, keeping exactly one program
in flight — same single-compute-stream model as hivemind's Runtime, with the
asyncio loop staying free for network I/O.
"""

from __future__ import annotations

import asyncio
import heapq
import itertools
import threading
from typing import Any, Callable, Optional

from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)

PRIORITY_INFERENCE = 1.0
PRIORITY_TRAINING = 2.0  # forward/backward (reference task_prioritizer.py:6-20)
PRIORITY_BARRIER = 10.0  # quiesce sentinel: runs after everything pending


class TaskRejected(Exception):
    pass


class PriorityTaskQueue:
    """Submit callables with (priority, fifo) ordering; one worker thread runs them."""

    def __init__(self, max_task_size: Optional[int] = None, name: str = "compute"):
        self.max_task_size = max_task_size
        self.name = name
        self._heap: list = []
        self._counter = itertools.count()
        self._cv = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        self._shutdown = False

    def start(self) -> None:
        assert self._thread is None, "already started"
        self._thread = threading.Thread(target=self._worker, name=f"ptu-{self.name}", daemon=True)
        self._thread.start()

    async def submit(
        self, fn: Callable[..., Any], *args, priority: float = PRIORITY_TRAINING, size: int = 0, **kwargs
    ) -> Any:
        """Run ``fn(*args, **kwargs)`` on the compute thread; lowest priority first."""
        if self.max_task_size is not None and size > self.max_task_size:
            raise TaskRejected(
                f"Task of size {size} exceeds queue limit {self.max_task_size}"
            )
        loop = asyncio.get_running_loop()
        future: asyncio.Future = loop.create_future()

        def run():
            try:
                result = fn(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 — must cross the thread boundary
                loop.call_soon_threadsafe(_set_exc, future, e)
            else:
                loop.call_soon_threadsafe(_set_result, future, result)

        with self._cv:
            if self._shutdown:
                raise TaskRejected("Task queue is shut down")
            heapq.heappush(self._heap, (priority, next(self._counter), run))
            self._cv.notify()
        return await future

    def _worker(self) -> None:
        while True:
            with self._cv:
                while not self._heap and not self._shutdown:
                    self._cv.wait()
                if self._shutdown and not self._heap:
                    return
                _, _, run = heapq.heappop(self._heap)
            run()

    def shutdown(self) -> None:
        with self._cv:
            self._shutdown = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)


def _set_result(future: asyncio.Future, result: Any) -> None:
    if not future.done():
        future.set_result(result)


def _set_exc(future: asyncio.Future, exc: BaseException) -> None:
    if not future.done():
        future.set_exception(exc)
