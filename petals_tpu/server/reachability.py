"""Reachability checking: can other peers dial us back?
(counterpart of reference src/petals/server/reachability.py:86-164 — the P2P
``rpc_check`` protocol where peers probe each other; the reference's
centralized health-API check (:22-52) has no private-swarm analogue, so the
peer-probe path is the implementation).
"""

from __future__ import annotations

import asyncio
from typing import Optional, Sequence

from petals_tpu.dht.node import DHTNode
from petals_tpu.dht.routing import PeerAddr
from petals_tpu.rpc.client import RpcClient
from petals_tpu.rpc.server import RpcContext, RpcServer
from petals_tpu.utils.logging import get_logger
from petals_tpu.utils.random_utils import sample_up_to

logger = get_logger(__name__)


class ReachabilityProtocol:
    """Registers ``reach.check`` on a node's RPC server: the callee dials the
    requested address back and reports success."""

    def __init__(self, *, probe_timeout: float = 5.0, identity=None):
        self.probe_timeout = probe_timeout
        # probing WITH an identity makes the target prove ITS identity back,
        # so a stale host:port reused by a different peer is detected
        self.identity = identity

    def register(self, server: RpcServer) -> None:
        if self.identity is None:
            self.identity = server.identity
        server.add_unary_handler("reach.check", self.rpc_check)

    async def rpc_check(self, payload, ctx: RpcContext):
        addr = PeerAddr.from_string(payload["addr"])
        try:
            deadline = asyncio.get_running_loop().time() + self.probe_timeout
            client = await asyncio.wait_for(
                RpcClient.connect(addr.host, addr.port, identity=self.identity),
                self.probe_timeout,
            )
            if self.identity is not None:
                # authenticated probe: the endpoint must PROVE the claimed id.
                # The whole probe shares ONE probe_timeout budget, so the reply
                # lands inside the asking peer's RPC timeout even when the
                # target accepts TCP but never proves (a definitive False beats
                # a dropped vote).
                remaining = max(deadline - asyncio.get_running_loop().time(), 0.1)
                proven = await client.wait_authenticated(remaining)
                ok = proven == addr.peer_id
            else:
                ok = client.remote_peer_id == addr.peer_id or client.remote_peer_id is None
            await client.close()
            return {"reachable": bool(ok)}
        except Exception as e:
            return {"reachable": False, "reason": f"{type(e).__name__}: {e}"}


async def check_direct_reachability(
    dht: DHTNode, *, max_peers: int = 3, threshold: float = 0.5
) -> Optional[bool]:
    """Ask a few peers to dial us back (reference server.py:137-150 decides
    client-mode/relay from this). None = inconclusive (nobody to ask)."""
    own = dht.own_addr
    if own is None:
        return None
    peers: Sequence[PeerAddr] = sample_up_to(dht.table.all_peers(), max_peers)
    if not peers:
        return None
    results = []
    for peer in peers:
        try:
            client = await dht.pool.get(peer.host, peer.port)
            reply = await asyncio.wait_for(
                client.call("reach.check", {"addr": own.to_string()}), 10.0
            )
            results.append(bool(reply.get("reachable")))
        except Exception as e:
            # a peer we cannot even ask is itself a (neutral) data point
            logger.debug("reachability probe via %s failed: %r", peer, e)
            continue
    if not results:
        return None
    return sum(results) / len(results) >= threshold
