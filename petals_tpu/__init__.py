"""petals_tpu: a TPU-native framework for collaborative inference and fine-tuning of
large language models over a decentralized swarm.

Re-designed from scratch for TPU hardware (JAX/XLA/Pallas/pjit for compute,
asyncio + a Kademlia DHT for the swarm control plane), with the capability
surface of the Petals reference (see SURVEY.md):

- A *server* hosts a contiguous span of transformer blocks of one model on its
  TPU slice (sharded over the ICI mesh with ``shard_map``/``pjit``).
- A *client* runs embeddings + LM head locally and routes hidden states through
  a chain of servers covering all blocks.
- Coordination happens through a DHT directory: servers announce which blocks
  they serve; clients build min-latency (inference) or max-throughput
  (training) chains, with bans/backoff and mid-generation failover.
"""

__version__ = "0.1.0"

from petals_tpu.data_structures import (
    ModuleUID,
    RemoteModuleInfo,
    RemoteSpanInfo,
    ServerInfo,
    ServerState,
    parse_uid,
)

__all__ = [
    "ModuleUID",
    "RemoteModuleInfo",
    "RemoteSpanInfo",
    "ServerInfo",
    "ServerState",
    "parse_uid",
    "__version__",
]
