"""petals_tpu: a TPU-native framework for collaborative inference and fine-tuning of
large language models over a decentralized swarm.

Re-designed from scratch for TPU hardware (JAX/XLA/Pallas/pjit for compute,
asyncio + a Kademlia DHT for the swarm control plane), with the capability
surface of the Petals reference (see SURVEY.md):

- A *server* hosts a contiguous span of transformer blocks of one model on its
  TPU slice (sharded over the ICI mesh with ``shard_map``/``pjit``).
- A *client* runs embeddings + LM head locally and routes hidden states through
  a chain of servers covering all blocks.
- Coordination happens through a DHT directory: servers announce which blocks
  they serve; clients build min-latency (inference) or max-throughput
  (training) chains, with bans/backoff and mid-generation failover.

Quick start::

    from petals_tpu import AutoDistributedModelForCausalLM

    model = AutoDistributedModelForCausalLM.from_pretrained(
        "/path/to/model", initial_peers=["host:port/peer_id"]
    )
    outputs = model.generate(input_ids, max_new_tokens=32)
"""

__version__ = "0.1.0"

from petals_tpu.data_structures import (
    ModuleUID,
    PeerID,
    RemoteModuleInfo,
    RemoteSpanInfo,
    ServerInfo,
    ServerState,
    parse_uid,
)

__all__ = [
    "ModuleUID",
    "PeerID",
    "RemoteModuleInfo",
    "RemoteSpanInfo",
    "ServerInfo",
    "ServerState",
    "parse_uid",
    "AutoDistributedModel",
    "DistributedModel",
    "AutoDistributedModelForCausalLM",
    "DistributedModelForCausalLM",
    "AutoDistributedModelForSequenceClassification",
    "DistributedModelForSequenceClassification",
    "DistributedModelForSpeculativeGeneration",
    "Server",
    "DHTNode",
    "InferenceSession",
    "RemoteSequential",
    "__version__",
]


def __getattr__(name):  # lazy: client/server pull in jax & friends
    if name in (
        "AutoDistributedModel",
        "DistributedModel",
        "AutoDistributedModelForCausalLM",
        "DistributedModelForCausalLM",
        "AutoDistributedModelForSequenceClassification",
        "DistributedModelForSequenceClassification",
        "DistributedModelForSpeculativeGeneration",
    ):
        from petals_tpu.client import model as _model

        return getattr(_model, name)
    if name == "Server":
        from petals_tpu.server.server import Server

        return Server
    if name == "DHTNode":
        from petals_tpu.dht.node import DHTNode

        return DHTNode
    if name in ("InferenceSession", "RemoteSequential"):
        import petals_tpu.client as _client

        return getattr(_client, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
