"""Run a standalone DHT bootstrap node:
``python -m petals_tpu.cli.run_dht [--host H] [--port P] [--identity_seed S]``
(counterpart of reference src/petals/cli/run_dht.py:37-106).
"""

from __future__ import annotations

import argparse
import asyncio
import signal

from petals_tpu.dht.node import DHTNode
from petals_tpu.server.reachability import ReachabilityProtocol
from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description="Bootstrap/relay node for a petals_tpu swarm")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--initial_peers", nargs="*", default=[])
    parser.add_argument("--identity_seed", default=None,
                        help="Seed string for a deterministic peer id (stable multiaddr)")
    parser.add_argument("--refresh_period", type=float, default=30.0,
                        help="Period of the liveness self-check (reference run_dht.py:24-34)")
    args = parser.parse_args(argv)

    async def run():
        node = await DHTNode.create(
            host=args.host,
            port=args.port,
            initial_peers=args.initial_peers,
            identity_seed=args.identity_seed.encode() if args.identity_seed else None,
        )
        ReachabilityProtocol().register(node.server)
        logger.info(f"DHT bootstrap running at {node.own_addr.to_string()}")
        print(node.own_addr.to_string(), flush=True)  # scripts consume this line

        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)

        async def heartbeat():
            while True:
                await asyncio.sleep(args.refresh_period)
                logger.debug(f"Alive; routing table size: {len(node.table)}")

        task = asyncio.create_task(heartbeat())
        await stop.wait()
        task.cancel()
        await node.shutdown()

    asyncio.run(run())


if __name__ == "__main__":
    main()
