"""Run a standalone DHT bootstrap node:
``python -m petals_tpu.cli.run_dht [--host H] [--port P] [--identity_seed S]``
(counterpart of reference src/petals/cli/run_dht.py:37-106).
"""

from __future__ import annotations

import argparse
import asyncio
import signal

from petals_tpu.dht.node import DHTNode
from petals_tpu.server.reachability import ReachabilityProtocol
from petals_tpu.utils.asyncio_utils import log_exception_callback
from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _local_ip() -> str:
    """Best-effort primary interface address (no packets are sent: connecting
    a UDP socket only selects a route)."""
    import socket

    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description="Bootstrap/relay node for a petals_tpu swarm")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--initial_peers", nargs="*", default=[])
    parser.add_argument("--identity_seed", default=None,
                        help="Seed string for a deterministic peer id (stable multiaddr)")
    parser.add_argument("--refresh_period", type=float, default=30.0,
                        help="Period of the liveness self-check (reference run_dht.py:24-34)")
    parser.add_argument("--no_relay", action="store_true",
                        help="Do not run a relay service for NAT'd servers (rpc/relay.py)")
    parser.add_argument("--relay_port", type=int, default=0,
                        help="Listen port for the relay service (default: ephemeral)")
    args = parser.parse_args(argv)

    async def run():
        node = await DHTNode.create(
            host=args.host,
            port=args.port,
            initial_peers=args.initial_peers,
            identity_seed=args.identity_seed.encode() if args.identity_seed else None,
        )
        ReachabilityProtocol().register(node.server)
        relay = None
        if not args.no_relay:
            from petals_tpu.rpc.relay import RelayServer

            relay = RelayServer(host=args.host, port=args.relay_port)
            await relay.start()
            relay.register_on(node.server)
            logger.info(f"Relay service at {relay.host}:{relay.port} (--relay_via target)")
        logger.info(f"DHT bootstrap running at {node.own_addr.to_string()}")
        print(node.own_addr.to_string(), flush=True)  # scripts consume this line
        if relay is not None:
            # 0.0.0.0 is a listen address, not a dialable one: print something
            # an operator can paste into --relay_via from another machine
            relay_host = relay.host if relay.host not in ("0.0.0.0", "::") else _local_ip()
            print(f"relay {relay_host}:{relay.port}", flush=True)

        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)

        async def heartbeat():
            while True:
                await asyncio.sleep(args.refresh_period)
                logger.debug(f"Alive; routing table size: {len(node.table)}")

        task = asyncio.create_task(heartbeat())
        task.add_done_callback(log_exception_callback(logger, "dht heartbeat"))
        await stop.wait()
        task.cancel()
        if relay is not None:
            await relay.stop()
        await node.shutdown()

    asyncio.run(run())


if __name__ == "__main__":
    main()
