"""Run the swarm health monitor (the health.petals.dev analogue):
``python -m petals_tpu.cli.run_health --initial_peers ADDR [--host H] [--port 8799]``
Serves / (HTML), /api/v1/state (JSON), /api/v1/metrics (swarm telemetry
aggregate), /api/v1/is_reachable/<peer>.

``--waterfall TRACE.json`` instead renders a saved client trace report
(``InferenceSession.trace_report()`` dumped as JSON, or a flight-recorder
entry containing one under ``waterfall``) as an ASCII per-hop latency
waterfall and exits — no swarm connection needed.

``--top`` joins the swarm, takes one snapshot, and renders the swarm-wide
top resource consumers (per-tenant page-seconds and dominant-resource
share, merged across every server's announced ledger digest) as an ASCII
table, then exits — the ledger analogue of ``top(1)``.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import signal

from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def render_waterfall_file(path: str) -> str:
    """Load a trace report (or flight-recorder entry wrapping one) and
    render it with telemetry.spans.format_waterfall."""
    from petals_tpu.telemetry.spans import format_waterfall

    with open(path, "r", encoding="utf-8") as f:
        report = json.load(f)
    if "hops" not in report and isinstance(report.get("waterfall"), dict):
        report = report["waterfall"]  # a flight-recorder breach entry
    return format_waterfall(report)


def render_top(summary: dict) -> str:
    """Render a ``metrics_summary()`` dict as the swarm-wide top-consumers
    table: one section per model, tenants ranked by page-seconds."""
    lines = []
    for prefix, model in (summary.get("models") or {}).items():
        agg = model.get("aggregate") or {}
        lines.append(
            f"{prefix}: {agg.get('ledger_sessions', 0)} sessions, "
            f"{agg.get('ledger_page_s', 0.0):.1f} page-s, "
            f"{agg.get('ledger_compute_s', 0.0):.1f} compute-s, "
            f"{agg.get('noisy_neighbor_events', 0)} noisy-neighbor events"
        )
        tiers = agg.get("tiers") or {}
        if tiers.get("prefill") or tiers.get("decode"):
            # disaggregated swarm: per-tier replica split + handoff volume
            lines.append(
                f"  tiers: {tiers.get('generalist', 0)} generalist / "
                f"{tiers.get('prefill', 0)} prefill / {tiers.get('decode', 0)} decode, "
                f"handoff {agg.get('handoff_bytes', 0) / 2**20:.1f} MiB "
                f"({agg.get('handoff_bytes_s', 0.0) / 2**10:.1f} KiB/s)"
            )
        rows = agg.get("top_consumers") or []
        if not rows:
            lines.append("  (no ledger digests announced yet)")
            continue
        lines.append(f"  {'peer':<18} {'page-s':>10} {'share':>7} {'servers':>8}")
        for row in rows:
            lines.append(
                f"  {str(row.get('peer', '?')):<18} {row.get('page_s', 0.0):>10.2f} "
                f"{row.get('share_max', 0.0):>7.2f} {row.get('servers', 0):>8}"
            )
    integ = summary.get("integrity") or {}
    quarantined = integ.get("quarantined") or {}
    if quarantined:
        lines.append(
            "integrity quarantine: "
            + ", ".join(f"{p} ({why})" for p, why in sorted(quarantined.items()))
        )
    return "\n".join(lines) if lines else "(no models announced)"


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description="Swarm health monitor")
    parser.add_argument(
        "--waterfall",
        metavar="TRACE.json",
        help="render a saved trace report as an ASCII waterfall and exit",
    )
    parser.add_argument(
        "--top",
        action="store_true",
        help="take one swarm snapshot, print the top resource consumers "
        "(per-tenant page-seconds from the servers' ledger digests), and exit",
    )
    parser.add_argument("--initial_peers", nargs="+")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8799)
    parser.add_argument("--update_period", type=float, default=15.0)
    parser.add_argument(
        "--canary_period",
        type=float,
        default=0.0,
        help="integrity canary cadence in seconds: replay seeded golden "
        "probes against every multi-replica span and quarantine fingerprint "
        "outliers by quorum (0 disables)",
    )
    args = parser.parse_args(argv)

    if args.waterfall:
        print(render_waterfall_file(args.waterfall), flush=True)
        return
    if not args.initial_peers:
        parser.error("--initial_peers is required (unless using --waterfall)")

    from petals_tpu.utils.health import HealthMonitor

    if args.top:
        async def run_top():
            monitor = HealthMonitor(
                args.initial_peers, host=args.host, port=0,
                update_period=args.update_period,
            )
            from petals_tpu.dht import DHTNode

            monitor.dht = await DHTNode.create(
                initial_peers=args.initial_peers, client_mode=True
            )
            try:
                await monitor.refresh()
                print(render_top(monitor.metrics_summary()), flush=True)
            finally:
                await monitor.dht.shutdown()

        asyncio.run(run_top())
        return

    async def run():
        monitor = HealthMonitor(
            args.initial_peers, host=args.host, port=args.port,
            update_period=args.update_period,
            canary_period=args.canary_period,
        )
        await monitor.start()
        print(f"http://{args.host}:{monitor.port}/", flush=True)
        print(f"http://{args.host}:{monitor.port}/api/v1/metrics", flush=True)
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        await monitor.stop()

    asyncio.run(run())


if __name__ == "__main__":
    main()
