"""Run the swarm health monitor (the health.petals.dev analogue):
``python -m petals_tpu.cli.run_health --initial_peers ADDR [--host H] [--port 8799]``
Serves / (HTML), /api/v1/state (JSON), /api/v1/metrics (swarm telemetry
aggregate), /api/v1/is_reachable/<peer>.
"""

from __future__ import annotations

import argparse
import asyncio
import signal

from petals_tpu.utils.health import HealthMonitor
from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description="Swarm health monitor")
    parser.add_argument("--initial_peers", nargs="+", required=True)
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8799)
    parser.add_argument("--update_period", type=float, default=15.0)
    args = parser.parse_args(argv)

    async def run():
        monitor = HealthMonitor(
            args.initial_peers, host=args.host, port=args.port,
            update_period=args.update_period,
        )
        await monitor.start()
        print(f"http://{args.host}:{monitor.port}/", flush=True)
        print(f"http://{args.host}:{monitor.port}/api/v1/metrics", flush=True)
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        await monitor.stop()

    asyncio.run(run())


if __name__ == "__main__":
    main()
