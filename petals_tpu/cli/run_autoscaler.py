"""Run the closed-loop swarm autoscaler against a live swarm:
``python -m petals_tpu.cli.run_autoscaler --initial_peers ADDR --model PREFIX``

Joins the swarm as a query-only DHT client (a HealthMonitor without the
HTTP server), samples the announced telemetry digests every
``--interval`` seconds, and runs the deterministic policy
(:mod:`petals_tpu.swarm.policy`) over the snapshots. Every decision is
journaled with its evidence (``autoscale_decision`` events; dump with
``--journal out.jsonl`` on exit).

By default the controller is ADVISORY: decisions are journaled and
printed, nothing is acted on. To close the loop, wire operator commands:

  --spawn_cmd  'systemctl start petals-replica@{start}-{end}'
  --drain_cmd  'curl -X POST http://admin/{peer}/drain'
  --resize_cmd 'curl -X POST http://admin/{peer}/resize?start={start}'

Commands are shell templates (``{start}``/``{end}``/``{peer}``
substituted) run locally with the operator's own credentials. There is
deliberately NO remote drain/spawn RPC in the swarm protocol: an
unauthenticated "please shut down" message in an open swarm is a DoS
primitive, so actuation stays an operator-side concern.
"""

from __future__ import annotations

import argparse
import asyncio
import signal

from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _shell_callback(template: str):
    """Turn a shell template into an async actuator callback."""

    async def run(*args) -> bool:
        if len(args) == 1 and isinstance(args[0], tuple):  # scale_out(span)
            fields = {"peer": "", "start": args[0][0], "end": args[0][1]}
        elif len(args) == 1:  # scale_in(peer)
            fields = {"peer": args[0], "start": "", "end": ""}
        else:  # resize(peer, span)
            fields = {"peer": args[0], "start": args[1][0], "end": args[1][1]}
        cmd = template.format(**fields)
        logger.info(f"autoscale exec: {cmd}")
        proc = await asyncio.create_subprocess_shell(cmd)
        code = await proc.wait()
        if code != 0:
            raise RuntimeError(f"actuator command exited {code}: {cmd!r}")
        return True

    return run


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description="Closed-loop swarm autoscaler")
    parser.add_argument("--initial_peers", nargs="+", required=True)
    parser.add_argument("--model", required=True, help="dht_prefix of the model to scale")
    parser.add_argument("--interval", type=float, default=15.0, help="seconds per tick")
    parser.add_argument("--ttft_p99_ms", type=float, default=10_000.0)
    parser.add_argument("--queue_share_high", type=float, default=0.5)
    parser.add_argument("--queue_share_low", type=float, default=0.1)
    parser.add_argument("--sustain_out", type=int, default=2)
    parser.add_argument("--sustain_in", type=int, default=3)
    parser.add_argument("--cooldown_out", type=int, default=5)
    parser.add_argument("--cooldown_in", type=int, default=5)
    parser.add_argument("--min_replicas", type=int, default=1)
    parser.add_argument("--max_replicas", type=int, default=8)
    parser.add_argument(
        "--span_blocks", type=int, default=0,
        help="span length for spawned replicas (0 = full model)",
    )
    parser.add_argument("--spawn_cmd", help="shell template run on scale_out ({start}/{end})")
    parser.add_argument("--drain_cmd", help="shell template run on scale_in ({peer})")
    parser.add_argument("--resize_cmd", help="shell template run on resize ({peer}/{start}/{end})")
    parser.add_argument("--journal", help="write the decision journal (JSONL) here on exit")
    parser.add_argument("--max_ticks", type=int, help="stop after N ticks (default: run forever)")
    args = parser.parse_args(argv)

    from petals_tpu.swarm import Autoscaler, CallbackActuator, PolicyConfig
    from petals_tpu.swarm.policy import snapshot_from_health
    from petals_tpu.utils.health import HealthMonitor

    config = PolicyConfig(
        ttft_p99_ms=args.ttft_p99_ms,
        queue_share_high=args.queue_share_high,
        queue_share_low=args.queue_share_low,
        sustain_out=args.sustain_out,
        sustain_in=args.sustain_in,
        cooldown_out=args.cooldown_out,
        cooldown_in=args.cooldown_in,
        min_replicas=args.min_replicas,
        max_replicas=args.max_replicas,
        span_blocks=args.span_blocks,
    )
    actuator = CallbackActuator(
        scale_out=_shell_callback(args.spawn_cmd) if args.spawn_cmd else None,
        scale_in=_shell_callback(args.drain_cmd) if args.drain_cmd else None,
        resize=_shell_callback(args.resize_cmd) if args.resize_cmd else None,
    )
    if not (args.spawn_cmd or args.drain_cmd or args.resize_cmd):
        logger.info("No actuator commands wired: running ADVISORY (journal-only)")

    async def run() -> None:
        monitor = HealthMonitor(args.initial_peers, port=0)
        from petals_tpu.dht import DHTNode

        monitor.dht = await DHTNode.create(
            initial_peers=args.initial_peers, client_mode=True
        )

        async def snapshot(tick: int):
            await monitor.refresh()
            model_state = monitor._state["models"].get(args.model)
            if model_state is None:
                logger.warning(f"model {args.model!r} not announced yet")
                return None
            return snapshot_from_health(model_state, tick=tick)

        scaler = Autoscaler(
            snapshot, actuator=actuator, config=config, interval_s=args.interval
        )
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        control = asyncio.create_task(scaler.run(max_ticks=args.max_ticks))
        try:
            stop_wait = asyncio.create_task(stop.wait())
            await asyncio.wait({control, stop_wait}, return_when=asyncio.FIRST_COMPLETED)
            stop_wait.cancel()
            control.cancel()
            try:
                await control
            except asyncio.CancelledError:
                pass
        finally:
            if args.journal:
                with open(args.journal, "w", encoding="utf-8") as f:
                    jsonl = scaler.policy.journal_jsonl()
                    f.write(jsonl + ("\n" if jsonl else ""))
                logger.info(
                    f"Wrote {len(scaler.policy.journal)} decision(s) to {args.journal}"
                )
            await monitor.dht.shutdown()

    asyncio.run(run())


if __name__ == "__main__":
    main()
