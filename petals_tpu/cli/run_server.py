"""Run a petals_tpu server: ``python -m petals_tpu.cli.run_server <model_path> [...]``
(counterpart of reference src/petals/cli/run_server.py:19-235).
"""

from __future__ import annotations

import argparse
import asyncio
import signal

import jax.numpy as jnp

from petals_tpu.constants import DTYPE_MAP
from petals_tpu.server.server import Server
from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description="Host a span of transformer blocks on this TPU host")
    parser.add_argument("model", help="Local path of the HF checkpoint to serve")
    parser.add_argument("--host", default="0.0.0.0", help="Listen address")
    parser.add_argument("--port", type=int, default=0, help="Listen port (0 = ephemeral)")
    parser.add_argument("--initial_peers", nargs="*", default=[],
                        help="Bootstrap peers as host:port/peer_id strings")
    parser.add_argument("--identity_seed", default=None,
                        help="Seed string for a deterministic peer id (test swarms)")
    parser.add_argument("--dht_prefix", default=None, help="Swarm namespace (default: derived from model name)")
    parser.add_argument("--first_block", type=int, default=None,
                        help="First block to serve (default: auto-placement from swarm state)")
    parser.add_argument("--num_blocks", type=int, default=None,
                        help="How many blocks to serve (default: auto-size to device memory)")
    parser.add_argument("--block_indices", default=None,
                        help="Alternative to first/num: a range like 0:16")
    parser.add_argument("--torch_dtype", "--dtype", dest="dtype", default="bfloat16",
                        choices=[k for k in DTYPE_MAP if k != "auto"], help="Compute dtype")
    parser.add_argument("--quant_type", default="none", choices=["none", "int8", "nf4", "nf4a", "int4", "nf4a+o", "int4+o"],
                        help="Weight quantization (ops/quant.py)")
    parser.add_argument("--coordinator_address", default=None,
                        help="multi-host serving: jax.distributed coordinator (host:port); "
                             "start num_hosts-1 run_worker processes with the same flags")
    parser.add_argument("--num_hosts", type=int, default=1,
                        help="multi-host serving: total processes incl. this leader")
    parser.add_argument("--no_quant_weight_cache", action="store_true",
                        help="Re-quantize at every start instead of persisting packed "
                             "quantized blocks in the disk cache (utils/quant_cache.py)")
    parser.add_argument("--attn_cache_tokens", type=int, default=8192,
                        help="KV-cache budget in tokens (converted to bytes for the allocator)")
    parser.add_argument("--max_chunk_size_bytes", type=int, default=256 * 1024 * 1024,
                        help="Prefill chunking bound (attention logits bytes)")
    parser.add_argument("--throughput", default="auto",
                        help='"auto" to self-measure, or a number')
    parser.add_argument("--update_period", type=float, default=30.0, help="DHT announce period, seconds")
    parser.add_argument("--mean_balance_check_period", type=float, default=0.0,
                        help=">0: periodically consider moving to under-served blocks")
    parser.add_argument("--num_tp_devices", type=int, default=None,
                        help="Tensor-parallel over this many local chips")
    parser.add_argument("--adapters", nargs="*", default=[],
                        help="PEFT adapter checkpoint dirs to host (multi-tenant LoRA)")
    parser.add_argument("--public_name", default=None, help="Display name announced to the swarm")
    parser.add_argument("--max_alloc_timeout", type=float, default=600.0)
    parser.add_argument("--num_sp_devices", type=int, default=None,
                        help=">1: ring-attention sequence parallelism for long-context "
                             "forward/backward (stateless path)")
    parser.add_argument("--compression", default="none",
                        choices=["none", "float16", "bfloat16", "qint8"],
                        help="Default reply compression (clients may override per request)")
    parser.add_argument("--max_disk_space", default=None,
                        help="Hub/checkpoint cache budget, e.g. 300GB (LRU-evicted)")
    parser.add_argument("--token", default=None,
                        help="HF Hub access token for gated/private repos (or set HF_TOKEN)")
    parser.add_argument("--network_mbps", type=float, default=None,
                        help="Known network budget in Mbit/s (default: probe swarm peers, "
                             "utils/bandwidth.py; loopback stack probe when alone)")
    parser.add_argument("--relay_via", default=None,
                        help="host:port of a relay peer (run_dht prints one): serve from behind "
                             "NAT/firewall with no inbound listener (rpc/relay.py)")
    parser.add_argument("--trace_dir", default=None,
                        help="Capture a bounded jax device trace here at startup "
                             "(or set PETALS_TPU_TRACE_DIR)")
    parser.add_argument("--drain_seconds", type=float, default=0.0,
                        help="On SIGTERM/SIGINT, park live sessions' KV and keep serving "
                             "ptu.session_export for this long before exiting, so clients "
                             "migrate caches to replacements instead of recomputing prefills")
    parser.add_argument("--inference_max_length", type=int, default=None,
                        help="Reject sessions longer than this (default: 8192 for GQA/MQA "
                             "models, 2048 otherwise — reference server.py:194-198)")
    parser.add_argument("--request_timeout", type=float, default=3 * 60,
                        help="Timeout for forward/backward requests, seconds")
    parser.add_argument("--session_timeout", type=float, default=30 * 60,
                        help="Max lifetime of an idle inference session, seconds")
    parser.add_argument("--step_timeout", type=float, default=5 * 60,
                        help="Timeout for one inference step, seconds")
    parser.add_argument("--balance_quality", type=float, default=0.75,
                        help="Rebalance only when swarm quality falls below this fraction "
                             "of the post-move optimum (reference --balance_quality)")
    parser.add_argument("--revision", default="main",
                        help="Hub revision (branch/tag/commit) for weight streaming")
    parser.add_argument("--cache_dir", default=None,
                        help="Hub download cache directory (default: PETALS_TPU_CACHE)")
    parser.add_argument("--no_batching", action="store_true",
                        help="Disable continuous batching of concurrent decode sessions")
    parser.add_argument("--batch_lanes", type=int, default=None,
                        help="Continuous-batching lane count (default: auto-size to the cache budget, <=8)")
    parser.add_argument("--batch_max_length", type=int, default=None,
                        help="Lane length in tokens (default: min(inference_max_length, 1024))")
    parser.add_argument("--page_size", type=int, default=64,
                        help="Paged KV cache: tokens per page (sessions grow page-by-page, so "
                             "admission costs one page instead of batch_max_length tokens); "
                             "0 reverts to the dense per-lane pool")
    parser.add_argument("--n_pages", type=int, default=None,
                        help="Paged KV pool size in pages (default: batch_lanes * pages-per-lane, "
                             "i.e. no oversubscription; raise to admit more sessions than lanes "
                             "could hold at full length)")
    parser.add_argument("--kv_quant_type", choices=["none", "int8", "nf4a"], default="none",
                        help="Quantize the paged KV pool in place: int8 (per-row absmax) or "
                             "packed nf4a halves decode HBM traffic ~2-4x and fits ~2-4x more "
                             "pages in the same cache budget; pages are dequantized inside the "
                             "fused attention kernel. Requires --page_size > 0")
    parser.add_argument("--prefill_token_budget", type=int, default=512,
                        help="Max prefill-chunk tokens folded into each mixed batched step "
                             "(paged lanes only: prefills share the step with decode lanes "
                             "instead of stalling them; halved under decode pressure)")
    parser.add_argument("--swap_host_bytes", type=int, default=0,
                        help="Host-RAM KV swap tier for session preemption (paged lanes only): "
                             "on pool exhaustion an idle victim session's pages are copied to "
                             "host RAM and freed, then transparently swapped back in on its "
                             "next step; 0 disables (full pool keeps the fail-at-timeout "
                             "backpressure behavior)")
    parser.add_argument("--preemption_policy", choices=["lru", "largest", "off"], default="lru",
                        help="Victim choice on pool exhaustion: 'lru' = lowest priority class "
                             "then least-recently-stepped; 'largest' = lowest class then most "
                             "pages held; 'off' disables preemption")
    parser.add_argument("--prefix_cache_bytes", type=int, default=256 * 2**20,
                        help="Host-RAM prompt-prefix cache budget; 0 disables")
    parser.add_argument("--no_server_side_generation", action="store_true",
                        help="disable the device-side greedy generation loop on full-span servers")
    parser.add_argument("--draft_model", default=None,
                        help="Local path of a SMALL checkpoint for speculative decoding: "
                             "it drafts --spec_k tokens per lane per tick and the span "
                             "verifies them in one paged step (full-span single-host "
                             "servers with server-side generation and a paged pool; "
                             "output stays bit-identical to plain decode)")
    parser.add_argument("--spec_k", type=int, default=4,
                        help="Draft tokens verified per lane per tick (with --draft_model)")
    parser.add_argument("--draft_window", type=int, default=None,
                        help="Draft context window in tokens (default 64): the draft "
                             "re-prefills the last N tokens each tick")
    parser.add_argument("--draft_quant_type", default="nf4a",
                        choices=["none", "int8", "nf4", "nf4a", "int4"],
                        help="Quantization for the draft model's blocks")
    parser.add_argument("--prefix_device_bytes", type=int, default=256 * 2**20,
                        help="HBM tier of the prefix cache (device-resident hit seeding); 0 disables")
    parser.add_argument("--metrics_port", type=int, default=None,
                        help="Serve Prometheus-text /metrics (plus the /journal scheduler "
                             "event log) on this local HTTP port; 0 = ephemeral, "
                             "omit to disable")
    parser.add_argument("--prefix_cache_policy", choices=["radix", "lru"], default="radix",
                        help="'radix' keys prefix-cache entries into a token-segment radix "
                             "tree with three-tier residency (HBM / host / swap) and "
                             "tenant-fair eviction; 'lru' is the flat insertion-order "
                             "baseline (A/B comparisons)")
    parser.add_argument("--phase_tier", choices=["generalist", "prefill", "decode"],
                        default="generalist",
                        help="Disaggregated serving tier announced to the swarm: 'prefill' "
                             "replicas soak heavy prompt processing and hand the finished KV "
                             "to a 'decode' replica over the server-to-server page-push path; "
                             "'generalist' (default) serves both phases")
    parser.add_argument("--prefix_share_scope", choices=["swarm", "peer"], default="swarm",
                        help="'swarm' shares cached prefixes across all clients (fastest; a client "
                             "can time-probe whether a prompt prefix was recently served); 'peer' "
                             "salts entries per authenticated client identity, closing that "
                             "side channel at the cost of cross-client sharing")
    return parser


def parse_block_range(args) -> tuple:
    if args.block_indices:
        first, last = args.block_indices.split(":")
        return int(first), int(last) - int(first)
    return args.first_block, args.num_blocks


def main(argv=None) -> None:
    import os

    args = build_parser().parse_args(argv)
    first_block, num_blocks = parse_block_range(args)

    # env-carried knobs: the hub/tracing modules read these at use time
    if args.max_disk_space:
        from petals_tpu.utils.hub import parse_size

        try:
            parse_size(args.max_disk_space)  # fail fast with the flag named
        except ValueError:
            build_parser().error(
                f"--max_disk_space: cannot parse {args.max_disk_space!r} "
                f"(expected e.g. 300GB, 512MB, or bytes)"
            )
        os.environ["PETALS_TPU_MAX_DISK_SPACE"] = args.max_disk_space
    if args.token:
        os.environ["HF_TOKEN"] = args.token
    if args.trace_dir:
        os.environ["PETALS_TPU_TRACE_DIR"] = args.trace_dir

    try:
        throughput = float(args.throughput)
    except ValueError:
        throughput = args.throughput

    # token budget -> bytes happens inside Server once the config is known
    from petals_tpu.server.from_pretrained import get_block_config

    family, cfg = get_block_config(
        args.model, revision=args.revision, cache_dir=args.cache_dir
    )
    hkv = getattr(cfg, "num_key_value_heads", cfg.num_attention_heads)
    dtype = DTYPE_MAP[args.dtype]
    attn_cache_bytes = (
        2 * args.attn_cache_tokens * hkv * cfg.head_dim * jnp.dtype(dtype).itemsize
        * (num_blocks or cfg.num_hidden_layers)
    )

    server = Server(
        args.model,
        first_block=first_block,
        num_blocks=num_blocks,
        dht_prefix=args.dht_prefix,
        host=args.host,
        port=args.port,
        initial_peers=args.initial_peers,
        identity_seed=args.identity_seed.encode() if args.identity_seed else None,
        compute_dtype=dtype,
        attn_cache_bytes=attn_cache_bytes,
        max_chunk_size_bytes=args.max_chunk_size_bytes,
        throughput=throughput,
        public_name=args.public_name,
        update_period=args.update_period,
        mean_balance_check_period=args.mean_balance_check_period,
        max_alloc_timeout=args.max_alloc_timeout,
        num_tp_devices=args.num_tp_devices,
        num_sp_devices=args.num_sp_devices,
        quant_type=args.quant_type,
        adapters=args.adapters,
        compression=args.compression,
        relay_via=args.relay_via,
        network_mbps=args.network_mbps,
        inference_max_length=args.inference_max_length,
        request_timeout=args.request_timeout,
        session_timeout=args.session_timeout,
        step_timeout=args.step_timeout,
        balance_quality=args.balance_quality,
        revision=args.revision,
        cache_dir=args.cache_dir,
        quant_weight_cache=not args.no_quant_weight_cache,
        coordinator_address=args.coordinator_address,
        num_hosts=args.num_hosts,
        batching=not args.no_batching,
        batch_lanes=args.batch_lanes,
        batch_max_length=args.batch_max_length,
        page_size=args.page_size,
        n_pages=args.n_pages,
        kv_quant_type=args.kv_quant_type,
        prefill_token_budget=args.prefill_token_budget,
        swap_host_bytes=args.swap_host_bytes,
        preemption_policy=args.preemption_policy,
        prefix_cache_bytes=args.prefix_cache_bytes,
        prefix_share_scope=args.prefix_share_scope,
        prefix_device_bytes=args.prefix_device_bytes,
        prefix_cache_policy=args.prefix_cache_policy,
        server_side_generation=not args.no_server_side_generation,
        draft_model=args.draft_model,
        spec_k=args.spec_k,
        draft_window=args.draft_window,
        draft_quant_type=args.draft_quant_type,
        metrics_port=args.metrics_port,
        phase_tier=args.phase_tier,
    )

    async def run():
        await server.start()
        logger.info(f"Serving; announce address: {server.contact_addr.to_string()}")
        stop = asyncio.Event()
        force = asyncio.Event()

        def on_signal():
            # second SIGINT/SIGTERM skips the remaining drain window: an
            # operator must always be able to force immediate shutdown
            if stop.is_set():
                force.set()
            else:
                stop.set()

        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, on_signal)
        await stop.wait()
        if args.drain_seconds > 0:
            parked = await server.drain(park_ttl=args.drain_seconds + 30)
            if parked:
                logger.info(
                    f"Drain window: serving KV exports for {parked} session(s) "
                    f"for {args.drain_seconds:.0f}s (signal again to skip)"
                )
                try:
                    await asyncio.wait_for(force.wait(), args.drain_seconds)
                    logger.info("Second signal: skipping the rest of the drain window")
                except asyncio.TimeoutError:
                    pass
        logger.info("Shutting down")
        await server.shutdown()

    asyncio.run(run())


if __name__ == "__main__":
    main()
