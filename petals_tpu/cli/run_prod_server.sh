#!/usr/bin/env bash
# Production wrapper: keep a server running across crashes
# (counterpart of reference src/petals/cli/run_prod_server.sh:1-8).
# Usage: ./run_prod_server.sh MODEL_PATH [run_server flags...]
set -u

while true; do
  python -m petals_tpu.cli.run_server "$@"
  code=$?
  if [ $code -eq 0 ]; then
    echo "Server exited cleanly; stopping the restart loop."
    break
  fi
  echo "Server died with code $code; restarting in 5s..."
  sleep 5
done
