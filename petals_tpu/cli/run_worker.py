"""Multi-host serving worker: the non-leader half of a span server whose
tensor parallelism spans several hosts (parallel/multihost.py).

Start ONE leader (``run_server`` with --coordinator_address/--num_hosts) and
``num_hosts - 1`` workers, each with the SAME model/span/quant/dtype flags:

    # host 0 (leader: DHT + RPC + scheduler)
    python -m petals_tpu.cli.run_server MODEL --first_block 0 --num_blocks 8 \
        --coordinator_address host0:8476 --num_hosts 2 --throughput 100

    # host 1 (worker: lockstep compute replica)
    python -m petals_tpu.cli.run_worker MODEL --first_block 0 --num_blocks 8 \
        --coordinator_address host0:8476 --num_hosts 2 --host_index 1

The worker builds the identical backend from the identical checkpoint, joins
the jax.distributed group, and executes the leader's broadcast ops until the
leader shuts down. There is no reference analogue: reference tensor
parallelism is bounded by one machine's GPUs (convert_block.py:118-135).
"""

from __future__ import annotations

import argparse


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("model", help="model path or repo id (must match the leader's)")
    parser.add_argument("--coordinator_address", required=True)
    parser.add_argument("--num_hosts", type=int, required=True)
    parser.add_argument("--host_index", type=int, required=True,
                        help="this worker's process id (1..num_hosts-1)")
    parser.add_argument("--first_block", type=int, required=True)
    parser.add_argument("--num_blocks", type=int, required=True)
    parser.add_argument("--num_tp_devices", type=int, default=None,
                        help="global tp width (default: every device in the group / sp)")
    parser.add_argument("--num_sp_devices", type=int, default=None,
                        help="sequence-parallel width — MUST match the leader's flag")
    parser.add_argument("--quant_type", default="none",
                        choices=["none", "int8", "nf4", "nf4a", "int4", "nf4a+o", "int4+o"])
    from petals_tpu.constants import DTYPE_MAP

    parser.add_argument("--torch_dtype", "--dtype", dest="dtype", default="bfloat16",
                        choices=[k for k in DTYPE_MAP if k != "auto"])
    parser.add_argument("--max_chunk_size_bytes", type=int, default=256 * 1024 * 1024)
    parser.add_argument("--adapters", nargs="*", default=(),
                        help="PEFT checkpoint dirs — MUST match the leader's --adapters")
    parser.add_argument("--revision", default="main")
    parser.add_argument("--cache_dir", default=None)
    parser.add_argument("--no_quant_weight_cache", action="store_true")
    args = parser.parse_args()
    if not 1 <= args.host_index:
        raise SystemExit("--host_index must be >= 1 (process 0 is the run_server leader)")

    # join the group BEFORE anything initializes the XLA backend
    from petals_tpu.parallel.multihost import (
        LockstepWorker,
        init_multihost,
        multihost_mesh,
    )

    init_multihost(args.coordinator_address, args.num_hosts, args.host_index)

    import jax
    import jax.numpy as jnp

    from petals_tpu.server.backend import TransformerBackend
    from petals_tpu.server.from_pretrained import get_block_config, load_block_params
    from petals_tpu.server.memory_cache import MemoryCache
    from petals_tpu.utils.convert_block import QuantType, convert_block_params
    from petals_tpu.utils.logging import get_logger

    from petals_tpu.constants import DTYPE_MAP

    logger = get_logger("petals_tpu.cli.run_worker")
    dtype = DTYPE_MAP[args.dtype]
    family, cfg = get_block_config(args.model, revision=args.revision, cache_dir=args.cache_dir)

    # the span params must BIT-MATCH the leader's: same checkpoint, same
    # conversion pipeline, same quant disk-cache format (utils/quant_cache.py)
    def load_block(i):
        params = load_block_params(
            args.model, i, dtype=dtype, family=family, cfg=cfg,
            revision=args.revision, cache_dir=args.cache_dir,
        )
        return convert_block_params(params, family.name, args.quant_type, fuse=False)

    mesh = multihost_mesh(args.num_tp_devices, args.num_sp_devices or 1)

    def build_backend(first_block: int) -> TransformerBackend:
        """Initial build AND the live-span-move rebuild (OP_RELOAD_SPAN):
        adapters re-slice for the new span like the leader's reload does."""
        per_block = [
            load_block(i) for i in range(first_block, first_block + args.num_blocks)
        ]
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_block)
        backend = TransformerBackend(
            family, cfg, stacked,
            first_block=first_block,
            n_blocks=args.num_blocks,
            memory_cache=MemoryCache(None),
            compute_dtype=dtype,
            max_chunk_size_bytes=args.max_chunk_size_bytes,
            mesh=mesh,
        )
        if args.adapters:
            from petals_tpu.utils.peft import load_adapter, stack_adapter

            block_range = range(first_block, first_block + args.num_blocks)
            for path in args.adapters:
                adapter = load_adapter(path, family.name, block_range=block_range)
                stacked_a = stack_adapter(adapter, first_block, args.num_blocks, dtype)
                backend.adapters[adapter.name] = (stacked_a, adapter.scaling)
            logger.info(f"worker hosting adapters: {sorted(backend.adapters)}")
        return backend

    backend = build_backend(args.first_block)

    logger.info(
        f"worker {args.host_index}/{args.num_hosts}: span "
        f"[{args.first_block}, {args.first_block + args.num_blocks}) over "
        f"tp={mesh.shape['tp']}"
        + (f" x sp={mesh.shape['sp']}" if "sp" in mesh.shape else "")
    )
    LockstepWorker(backend, rebuild_fn=build_backend).run()


if __name__ == "__main__":
    main()
