"""Finding + pragma machinery for swarmlint (petals_tpu.analysis).

A finding is one rule violation at one source line. Findings can be
suppressed in-source with a pragma comment::

    risky_call()  # swarmlint: disable=<rule-name> — reason why this is OK

Pragma grammar:

- ``# swarmlint: disable=<rule>[,<rule>...]`` followed by a REQUIRED
  free-text reason (separated by ``—``, ``--``, ``:`` or whitespace — a
  single space after the last rule token is enough). A pragma without a
  reason is itself reported as a finding (rule ``pragma-needs-reason``)
  and fails the CLI.
- A trailing pragma suppresses matching findings on its own line.
- A pragma on a comment-only line suppresses matching findings on the next
  line that holds code (so multi-line statements can be annotated above).
- ``disable=all`` suppresses every rule on the target line.

Unknown rule names in a pragma are reported (rule ``pragma-unknown-rule``)
so typos cannot silently disable nothing.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Tuple

# the payload must start with a rule character (or be empty) so prose that
# merely MENTIONS the syntax, e.g. ``disable=<rule>``, is not itself a pragma
PRAGMA_RE = re.compile(r"#\s*swarmlint:\s*disable=([A-Za-z0-9_\-].*)?$")

# leading comma-joined rule tokens of the pragma payload
_RULES_PREFIX_RE = re.compile(r"[A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*")
# one explicit separator between the rule list and the reason; a bare single
# space also counts, so ``disable=<rule> because ...`` parses cleanly
_LEADING_SEP_RE = re.compile(r"^\s*(?:[—–:]|--)\s*|^\s+")


def _split_rules_reason(rest: str) -> Tuple[str, str]:
    """Split a pragma payload into (rule-list text, reason text)."""
    m = _RULES_PREFIX_RE.match(rest)
    if m is None:
        return rest, ""  # malformed: surfaces via pragma-unknown-rule
    rules_part, tail = rest[: m.end()], rest[m.end() :]
    return rules_part, _LEADING_SEP_RE.sub("", tail, count=1).strip()

# pseudo-rules emitted by the pragma machinery itself (never suppressible)
PRAGMA_NEEDS_REASON = "pragma-needs-reason"
PRAGMA_UNKNOWN_RULE = "pragma-unknown-rule"
STALE_PRAGMA = "stale-pragma"

_PRAGMA_META_RULES = (PRAGMA_NEEDS_REASON, PRAGMA_UNKNOWN_RULE, STALE_PRAGMA)


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    message: str
    suppressed: bool = False
    suppress_reason: str = ""

    def format(self) -> str:
        tag = " (suppressed: %s)" % self.suppress_reason if self.suppressed else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{tag}"


@dataclasses.dataclass
class Pragma:
    line: int  # line the pragma comment lives on (1-based)
    target_line: int  # line whose findings it suppresses
    rules: Tuple[str, ...]
    reason: str
    used: bool = False  # set by apply_pragmas when it suppresses a finding


def _is_code_line(text: str) -> bool:
    stripped = text.strip()
    return bool(stripped) and not stripped.startswith("#")


def parse_pragmas(source_lines: Sequence[str]) -> List[Pragma]:
    """Extract pragmas; comment-only pragmas attach to the next code line."""
    pragmas: List[Pragma] = []
    n = len(source_lines)
    for i, text in enumerate(source_lines):
        m = PRAGMA_RE.search(text)
        if m is None:
            continue
        rules_part, reason = _split_rules_reason(m.group(1) or "")
        rules = tuple(r.strip() for r in rules_part.split(",") if r.strip())
        lineno = i + 1
        target = lineno
        if not _is_code_line(text[: m.start()] if m.start() else ""):
            # comment-only line: attach to the next line holding code
            j = i + 1
            while j < n and not _is_code_line(source_lines[j]):
                j += 1
            if j < n:
                target = j + 1
        pragmas.append(Pragma(line=lineno, target_line=target, rules=rules, reason=reason))
    return pragmas


def apply_pragmas(
    findings: List[Finding],
    pragmas: Sequence[Pragma],
    path: str,
    known_rules: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Mark findings suppressed where a pragma covers (line, rule); emit
    pragma-needs-reason / pragma-unknown-rule findings for malformed ones."""
    by_line: Dict[int, List[Pragma]] = {}
    out = list(findings)
    for p in pragmas:
        by_line.setdefault(p.target_line, []).append(p)
        if not p.reason:
            out.append(
                Finding(
                    rule=PRAGMA_NEEDS_REASON,
                    path=path,
                    line=p.line,
                    message=(
                        "suppression pragma must carry a reason: "
                        "'# swarmlint: disable=<rule> — <why this is safe>'"
                    ),
                )
            )
        if known_rules is not None:
            for r in p.rules:
                if r != "all" and r not in known_rules:
                    out.append(
                        Finding(
                            rule=PRAGMA_UNKNOWN_RULE,
                            path=path,
                            line=p.line,
                            message=f"pragma disables unknown rule {r!r}",
                        )
                    )
    for f in out:
        if f.rule in _PRAGMA_META_RULES:
            continue
        for p in by_line.get(f.line, ()):  # pragmas targeting this line
            if ("all" in p.rules or f.rule in p.rules) and p.reason:
                f.suppressed = True
                f.suppress_reason = p.reason
                p.used = True
                break
    return out


def stale_pragma_findings(
    pragmas: Sequence[Pragma], path: str, known_rules: Sequence[str]
) -> List[Finding]:
    """A well-formed pragma that suppressed zero findings is itself a finding
    (like an unused ``noqa``): fixed code must shed its suppressions. Only
    meaningful when the FULL rule set just ran over ``path`` and
    ``apply_pragmas`` marked the used ones — malformed pragmas are excluded
    because they already surface as pragma-needs-reason / pragma-unknown-rule."""
    out: List[Finding] = []
    for p in pragmas:
        if p.used or not p.reason:
            continue
        if any(r != "all" and r not in known_rules for r in p.rules):
            continue
        out.append(
            Finding(
                rule=STALE_PRAGMA,
                path=path,
                line=p.line,
                message=(
                    f"pragma disable={','.join(p.rules)} suppresses no "
                    "findings — the code it covered was fixed, drop the pragma"
                ),
            )
        )
    return out
