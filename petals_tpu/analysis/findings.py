"""Finding + pragma machinery for swarmlint (petals_tpu.analysis).

A finding is one rule violation at one source line. Findings can be
suppressed in-source with a pragma comment::

    risky_call()  # swarmlint: disable=no-silent-except — reason why this is OK

Pragma grammar:

- ``# swarmlint: disable=<rule>[,<rule>...]`` followed by a REQUIRED
  free-text reason (separated by ``—``, ``--``, ``:`` or whitespace).
  A pragma without a reason is itself reported as a finding
  (rule ``pragma-needs-reason``) and fails the CLI.
- A trailing pragma suppresses matching findings on its own line.
- A pragma on a comment-only line suppresses matching findings on the next
  line that holds code (so multi-line statements can be annotated above).
- ``disable=all`` suppresses every rule on the target line.

Unknown rule names in a pragma are reported (rule ``pragma-unknown-rule``)
so typos cannot silently disable nothing.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Tuple

PRAGMA_RE = re.compile(
    r"#\s*swarmlint:\s*disable=([A-Za-z0-9_,\- ]*?)(?:\s*(?:[—–:]|--)\s*(.*)|\s{2,}(.*))?$"
)

# pseudo-rules emitted by the pragma machinery itself (never suppressible)
PRAGMA_NEEDS_REASON = "pragma-needs-reason"
PRAGMA_UNKNOWN_RULE = "pragma-unknown-rule"


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    message: str
    suppressed: bool = False
    suppress_reason: str = ""

    def format(self) -> str:
        tag = " (suppressed: %s)" % self.suppress_reason if self.suppressed else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{tag}"


@dataclasses.dataclass
class Pragma:
    line: int  # line the pragma comment lives on (1-based)
    target_line: int  # line whose findings it suppresses
    rules: Tuple[str, ...]
    reason: str


def _is_code_line(text: str) -> bool:
    stripped = text.strip()
    return bool(stripped) and not stripped.startswith("#")


def parse_pragmas(source_lines: Sequence[str]) -> List[Pragma]:
    """Extract pragmas; comment-only pragmas attach to the next code line."""
    pragmas: List[Pragma] = []
    n = len(source_lines)
    for i, text in enumerate(source_lines):
        m = PRAGMA_RE.search(text)
        if m is None:
            continue
        rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
        reason = (m.group(2) or m.group(3) or "").strip()
        lineno = i + 1
        target = lineno
        if not _is_code_line(text[: m.start()] if m.start() else ""):
            # comment-only line: attach to the next line holding code
            j = i + 1
            while j < n and not _is_code_line(source_lines[j]):
                j += 1
            if j < n:
                target = j + 1
        pragmas.append(Pragma(line=lineno, target_line=target, rules=rules, reason=reason))
    return pragmas


def apply_pragmas(
    findings: List[Finding],
    pragmas: Sequence[Pragma],
    path: str,
    known_rules: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Mark findings suppressed where a pragma covers (line, rule); emit
    pragma-needs-reason / pragma-unknown-rule findings for malformed ones."""
    by_line: Dict[int, List[Pragma]] = {}
    out = list(findings)
    for p in pragmas:
        by_line.setdefault(p.target_line, []).append(p)
        if not p.reason:
            out.append(
                Finding(
                    rule=PRAGMA_NEEDS_REASON,
                    path=path,
                    line=p.line,
                    message=(
                        "suppression pragma must carry a reason: "
                        "'# swarmlint: disable=<rule> — <why this is safe>'"
                    ),
                )
            )
        if known_rules is not None:
            for r in p.rules:
                if r != "all" and r not in known_rules:
                    out.append(
                        Finding(
                            rule=PRAGMA_UNKNOWN_RULE,
                            path=path,
                            line=p.line,
                            message=f"pragma disables unknown rule {r!r}",
                        )
                    )
    for f in out:
        if f.rule in (PRAGMA_NEEDS_REASON, PRAGMA_UNKNOWN_RULE):
            continue
        for p in by_line.get(f.line, ()):  # pragmas targeting this line
            if ("all" in p.rules or f.rule in p.rules) and p.reason:
                f.suppressed = True
                f.suppress_reason = p.reason
                break
    return out
