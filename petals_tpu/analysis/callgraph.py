"""Module-level call graph + per-function fact extraction for swarmlint v2.

The v1 rules are per-function AST walks; the dangerous state transitions in
this codebase (swap tier, live migration, radix residency, phase handoff)
moved into helper-call chains those walks are structurally blind to. This
module builds the project model the interprocedural passes run on:

- :class:`FunctionFacts` — one function's *direct* facts, extracted in a
  single AST pass: every call site (with the locks lexically held around it
  and the try/finally protection enclosing it), await points, blocking-call
  points, page incref/decref sites, lane-typestate mutations, manual
  ``.acquire()``/``.release()`` pairs, and donation decorators.
- :class:`ModuleFacts` — a file's functions + classes + imports + pragmas +
  the names its thread locks and donating jit-callables are bound to.
- :class:`Project` — the whole-tree index with call resolution:

  1. nested defs in the caller,
  2. module-level functions in the caller's module,
  3. ``self.method()`` through the caller's class and its bases found in
     the tree (method resolution on ``self``),
  4. ``from x import f`` / ``import x`` aliases,
  5. otherwise *dynamic dispatch falls back to top*: the join of every
     function with that name anywhere in the tree (a receiver we cannot
     type could be any of them, so effect summaries union over all).

Everything here is a plain picklable dataclass so the per-file extraction
can run in worker processes (``engine.check_project(jobs=N)``) and only the
cheap fact records cross back — never the ASTs themselves.

A deliberate precision choice, relied on throughout: a function passed as a
*value* (``queue.submit(self._gather)``, ``asyncio.to_thread(fn)``) creates
NO call edge. Compute-thread bodies blocking under the reset lock are the
design, not a bug — only direct calls propagate effects.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .findings import Pragma, parse_pragmas
from .rules import (
    BLOCKING_CALLS,
    BLOCKING_METHODS,
    INCREF_CALLS,
    RELEASE_CALLS,
    collect_thread_lock_names,
    dotted,
    last_segment,
    looks_like_lock,
)

# Lane/session lifecycle fields (scheduler.SessionSlot): the typestate rule
# and cancellation-safety's dirty tracking key off mutations to these.
TYPESTATE_FIELDS = ("suspending", "swap")

# self.<attr> fields whose mutation marks an invariant-critical region dirty
# for cancellation-safety (lane tables, page pool, migration/handoff parking).
CRITICAL_FIELDS = {
    "suspending",
    "swap",
    "_tables",
    "_pages",
    "_lane_generation",
    "_generation",
    "_inflight",
    "_gen_states",
    "_prefill_queue",
    "_pending",
    "_migrated",
    "_migrated_away",
    "_migrated_bytes",
    "_parked",
}

_MUTATING_METHODS = {
    "append",
    "add",
    "clear",
    "extend",
    "insert",
    "pop",
    "popitem",
    "remove",
    "setdefault",
    "update",
}

_JIT_FAMILY = {"tracked_jit", "jit"}  # final segment of the decorator callee
_PROPERTY_DECORATORS = {"property", "cached_property"}

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


LockCtx = Tuple[str, bool, int]  # (name, is_async, with-statement line)
TryCtx = Tuple[int, bool, bool]  # (try line, has finally, catches cancellation)


def _handler_catches_cancel(h: ast.excepthandler) -> bool:
    """Bare ``except:`` or a type list naming BaseException/CancelledError —
    the handlers that still run when the task is cancelled at an await."""
    if h.type is None:
        return True
    nodes = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
    for n in nodes:
        d = dotted(n)
        if d and d.split(".")[-1] in ("BaseException", "CancelledError"):
            return True
    return False


@dataclasses.dataclass(frozen=True)
class CallEvent:
    """One direct call site, with enough context to resolve and judge it."""

    line: int
    col: int
    end_line: int
    end_col: int
    kind: str  # 'name' | 'self' | 'attr' | 'dotted'
    name: str  # final callee segment
    base: Optional[str]  # dotted receiver ('self', 'self._pages', 'batching', ...)
    args: Tuple[Tuple[int, Optional[str]], ...]  # positional (index, dotted repr)
    kwargs: Tuple[Tuple[str, Optional[str]], ...]  # keyword (name, dotted repr)
    assigns: Tuple[str, ...]  # dotted assignment targets of the call's statement
    awaited: bool
    locks: Tuple[LockCtx, ...]
    trys: Tuple[TryCtx, ...]
    cleanup: bool  # inside a finally block or except handler
    # '' | 'except' | 'except_cancel' | 'finally' — which kind of cleanup
    # region encloses this site. 'except' does NOT run on CancelledError
    # (BaseException since 3.8), so a refcount release there does not
    # protect a function that can suspend; 'finally'/'except_cancel' do.
    cleanup_kind: str = ""


@dataclasses.dataclass(frozen=True)
class Event:
    """One non-call fact: kinds 'await', 'block', 'ref_inc', 'ref_rel',
    'mutate', 'ts', 'lock_acq', 'lock_rel', 'trylock', 'return'."""

    kind: str
    line: int
    col: int
    detail: str
    locks: Tuple[LockCtx, ...]
    trys: Tuple[TryCtx, ...]
    cleanup: bool
    cleanup_kind: str = ""  # see CallEvent.cleanup_kind


@dataclasses.dataclass(frozen=True)
class DonationSpec:
    argnums: Tuple[int, ...]
    argnames: Tuple[str, ...]

    def __bool__(self) -> bool:
        return bool(self.argnums or self.argnames)


@dataclasses.dataclass
class FunctionFacts:
    qualname: str
    name: str
    cls: Optional[str]
    path: str
    lineno: int
    is_async: bool
    params: Tuple[str, ...]
    calls: List[CallEvent]
    events: List[Event]
    nested: Tuple[str, ...]  # qualnames of directly nested defs
    donation: Optional[DonationSpec]  # jit-with-donation decorator on this def
    is_property: bool
    returns_nested: Tuple[str, ...]  # simple names of nested defs it returns
    # every identifier (Name / dotted Attribute) touched in this function:
    # dotted name -> ordered ((line, col, 'load'|'store'), ...). Drives the
    # use-after-donate read scan without shipping ASTs between processes.
    name_uses: Dict[str, Tuple[Tuple[int, int, str], ...]] = dataclasses.field(
        default_factory=dict
    )


@dataclasses.dataclass
class ClassFacts:
    name: str
    bases: Tuple[str, ...]
    methods: Dict[str, str]  # method name -> qualname


@dataclasses.dataclass
class ModuleFacts:
    path: str
    funcs: List[FunctionFacts]
    classes: Dict[str, ClassFacts]
    imports: Dict[str, str]  # alias -> dotted module / "mod.name" for from-imports
    thread_locks: Tuple[str, ...]
    donating_names: Dict[str, DonationSpec]  # bound name/attr tail -> spec
    pragmas: List[Pragma]


# ------------------------------------------------------------ decorator parse


def _const_ints(node: ast.AST) -> Tuple[int, ...]:
    out = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, int):
            out.append(sub.value)
    return tuple(out)


def _const_strs(node: ast.AST) -> Tuple[str, ...]:
    return tuple(
        sub.value
        for sub in ast.walk(node)
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str)
    )


def donation_spec(call: ast.AST) -> Optional[DonationSpec]:
    """DonationSpec for a jit-family call carrying donate_argnums/argnames
    (``tracked_jit(...)``, ``jax.jit(...)``, ``functools.partial(jax.jit,
    ...)``); None when ``call`` is not a donating jit call."""
    if not isinstance(call, ast.Call):
        return None
    callee = dotted(call.func) or ""
    seg = callee.split(".")[-1]
    if seg == "partial":
        if not call.args:
            return None
        inner = dotted(call.args[0]) or ""
        if inner.split(".")[-1] not in _JIT_FAMILY:
            return None
    elif seg not in _JIT_FAMILY:
        return None
    argnums: Tuple[int, ...] = ()
    argnames: Tuple[str, ...] = ()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            argnums = _const_ints(kw.value)
        elif kw.arg == "donate_argnames":
            argnames = _const_strs(kw.value)
    spec = DonationSpec(argnums=argnums, argnames=argnames)
    return spec if spec else None


def _param_names(fn: ast.AST) -> Tuple[str, ...]:
    a = fn.args
    names = [p.arg for p in [*a.posonlyargs, *a.args, *a.kwonlyargs]]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return tuple(names)


# ------------------------------------------------------------- the extractor


class _FunctionWalker:
    """Single in-order pass over one function body: records calls and events
    with the lock regions and try protection lexically enclosing each one.
    Does NOT descend into nested defs (their code runs at call time)."""

    def __init__(self, facts: FunctionFacts, thread_locks: Set[str]):
        self.facts = facts
        self.thread_locks = thread_locks
        self.locks: List[LockCtx] = []
        self.trys: List[TryCtx] = []
        self.cleanup_stack: List[str] = []  # 'except' | 'except_cancel' | 'finally'

    # -- context helpers

    def _ctx(self) -> Tuple[Tuple[LockCtx, ...], Tuple[TryCtx, ...], bool, str]:
        kind = self.cleanup_stack[-1] if self.cleanup_stack else ""
        return tuple(self.locks), tuple(self.trys), bool(self.cleanup_stack), kind

    def event(self, kind: str, node: ast.AST, detail: str) -> None:
        locks, trys, cleanup, cleanup_kind = self._ctx()
        self.facts.events.append(
            Event(
                kind=kind,
                line=node.lineno,
                col=getattr(node, "col_offset", 0),
                detail=detail,
                locks=locks,
                trys=trys,
                cleanup=cleanup,
                cleanup_kind=cleanup_kind,
            )
        )

    # -- expression scanning (records calls/awaits/refcounts in one walk)

    def scan_expr(self, node: ast.AST, assigns: Tuple[str, ...] = ()) -> None:
        for sub in self._walk_no_functions(node):
            if isinstance(sub, ast.Await):
                self.event("await", sub, "")
            elif isinstance(sub, ast.Call):
                self._record_call(sub, node, assigns)

    def _walk_no_functions(self, node: ast.AST):
        stack = [node]
        while stack:
            cur = stack.pop()
            if isinstance(cur, _FUNC_NODES) and cur is not node:
                continue
            yield cur
            if not (isinstance(cur, _FUNC_NODES) and cur is not node):
                stack.extend(ast.iter_child_nodes(cur))

    def _record_call(self, call: ast.Call, stmt_expr: ast.AST, assigns) -> None:
        func = call.func
        full = dotted(func)
        name = last_segment(func)
        if name is None:
            return  # dynamic callee ((fns[i])(...)): no edge
        base: Optional[str] = None
        kind = "name"
        if isinstance(func, ast.Attribute):
            base = dotted(func.value)
            if base == "self":
                kind = "self"
            elif base is not None:
                kind = "dotted"
            else:
                kind = "attr"
        locks, trys, cleanup, cleanup_kind = self._ctx()
        awaited = False
        # the await wrapping this call, if any, was already recorded; mark
        # the call itself so rules can tell `await f()` from bare `f()`
        parent = getattr(call, "_swarmlint_parent", None)
        if isinstance(parent, ast.Await):
            awaited = True
        args = tuple(
            (i, dotted(a)) for i, a in enumerate(call.args)
            if not isinstance(a, ast.Starred)
        )
        kwargs = tuple(
            (kw.arg, dotted(kw.value)) for kw in call.keywords if kw.arg
        )
        self.facts.calls.append(
            CallEvent(
                line=call.lineno,
                col=call.col_offset,
                end_line=getattr(call, "end_lineno", call.lineno) or call.lineno,
                end_col=getattr(call, "end_col_offset", call.col_offset) or 0,
                kind=kind,
                name=name,
                base=base,
                args=args,
                kwargs=kwargs,
                assigns=assigns,
                awaited=awaited,
                locks=locks,
                trys=trys,
                cleanup=cleanup,
                cleanup_kind=cleanup_kind,
            )
        )
        # classify side-effect facts off the same node
        if full in BLOCKING_CALLS:
            self.event("block", call, full)
        elif (
            isinstance(func, ast.Attribute)
            and name in BLOCKING_METHODS
            and not call.args
            and not call.keywords
        ):
            self.event("block", call, f".{name}()")
        if isinstance(func, ast.Attribute):
            if name in INCREF_CALLS:
                self.event("ref_inc", call, name)
            elif name in RELEASE_CALLS:
                self.event("ref_rel", call, name)
            if name in _MUTATING_METHODS and base and base.startswith("self."):
                attr = base.split(".")[1]
                self.event("mutate", call, attr)
            if name == "acquire" and base:
                self.event("lock_acq", call, base.split(".")[-1])
            elif name == "release" and base:
                self.event("lock_rel", call, base.split(".")[-1])
        if name == "lock_try_acquire_nowait":
            self.event("trylock", call, dotted(call.args[0]) if call.args else "")

    # -- statement dispatch

    def walk_body(self, body: Sequence[ast.stmt]) -> None:
        for stmt in body:
            self.walk_stmt(stmt)

    def walk_stmt(self, stmt: ast.stmt) -> None:
        # annotate parents of Await-wrapped calls before scanning
        for sub in ast.walk(stmt):
            for child in ast.iter_child_nodes(sub):
                child._swarmlint_parent = sub  # type: ignore[attr-defined]
        if isinstance(stmt, _FUNC_NODES) or isinstance(stmt, ast.ClassDef):
            return  # separate facts / out of scope
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            pushed = 0
            is_async = isinstance(stmt, ast.AsyncWith)
            if is_async:
                self.event("await", stmt, "async with")
            for item in stmt.items:
                self.scan_expr(item.context_expr)
                seg = last_segment(item.context_expr)
                # lock-looking names, plus anything this module binds a
                # threading.Lock/RLock/Condition to (e.g. ``self._cv``)
                if seg and (
                    looks_like_lock(item.context_expr) or seg in self.thread_locks
                ):
                    self.locks.append((seg, is_async, stmt.lineno))
                    pushed += 1
            self.walk_body(stmt.body)
            for _ in range(pushed):
                self.locks.pop()
            return
        if isinstance(stmt, ast.Try):
            handlers_catch_cancel = any(
                _handler_catches_cancel(h) for h in stmt.handlers
            )
            ctx: TryCtx = (stmt.lineno, bool(stmt.finalbody), handlers_catch_cancel)
            self.trys.append(ctx)
            self.walk_body(stmt.body)
            self.trys.pop()
            # exceptions raised in handlers/else/finally are NOT caught here
            for h in stmt.handlers:
                self.cleanup_stack.append(
                    "except_cancel" if _handler_catches_cancel(h) else "except"
                )
                self.walk_body(h.body)
                self.cleanup_stack.pop()
            self.walk_body(stmt.orelse)
            self.cleanup_stack.append("finally")
            self.walk_body(stmt.finalbody)
            self.cleanup_stack.pop()
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self.scan_expr(stmt.test)
            self.walk_body(stmt.body)
            self.walk_body(stmt.orelse)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            if isinstance(stmt, ast.AsyncFor):
                self.event("await", stmt, "async for")
            self.scan_expr(stmt.iter)
            self.walk_body(stmt.body)
            self.walk_body(stmt.orelse)
            return
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self.scan_expr(stmt.value)
                d = dotted(stmt.value)
                self.event("return", stmt, d or "")
            else:
                self.event("return", stmt, "")
            return
        # plain statements: record stores, then scan all expressions
        assigns: Tuple[str, ...] = ()
        if isinstance(stmt, ast.Assign):
            assigns = self._store_targets(stmt.targets)
            self._record_stores(stmt, stmt.targets)
        elif isinstance(stmt, ast.AugAssign):
            assigns = self._store_targets([stmt.target])
            self._record_stores(stmt, [stmt.target])
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            assigns = self._store_targets([stmt.target])
            self._record_stores(stmt, [stmt.target])
        self.scan_expr(stmt, assigns=assigns)

    def _store_targets(self, targets: Sequence[ast.AST]) -> Tuple[str, ...]:
        out: List[str] = []
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                out.extend(self._store_targets(t.elts))
            else:
                d = dotted(t)
                if d:
                    out.append(d)
        return tuple(out)

    def _record_stores(self, stmt: ast.stmt, targets: Sequence[ast.AST]) -> None:
        value = getattr(stmt, "value", None)
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                self._record_stores(stmt, t.elts)
                continue
            if isinstance(t, ast.Attribute):
                if t.attr in TYPESTATE_FIELDS:
                    self.event("ts", stmt, f"{t.attr}={self._value_kind(value)}")
                base = dotted(t.value)
                if base == "self":
                    self.event("mutate", stmt, t.attr)
            elif isinstance(t, ast.Subscript):
                d = dotted(t.value)
                if d and d.startswith("self."):
                    self.event("mutate", stmt, d.split(".")[1])

    @staticmethod
    def _value_kind(value: Optional[ast.AST]) -> str:
        if isinstance(value, ast.Constant):
            if value.value is True:
                return "true"
            if value.value is False:
                return "false"
            if value.value is None:
                return "none"
        return "value"


def _extract_function(
    node: ast.AST,
    path: str,
    cls: Optional[str],
    qualname: str,
    thread_locks: Set[str],
) -> FunctionFacts:
    spec: Optional[DonationSpec] = None
    is_property = False
    for dec in node.decorator_list:
        s = donation_spec(dec)
        if s is not None:
            spec = s
        d = dotted(dec) or (dotted(dec.func) if isinstance(dec, ast.Call) else None)
        if d and d.split(".")[-1] in _PROPERTY_DECORATORS:
            is_property = True
    facts = FunctionFacts(
        qualname=qualname,
        name=node.name,
        cls=cls,
        path=path,
        lineno=node.lineno,
        is_async=isinstance(node, ast.AsyncFunctionDef),
        params=_param_names(node),
        calls=[],
        events=[],
        nested=(),
        donation=spec,
        is_property=is_property,
        returns_nested=(),
    )
    walker = _FunctionWalker(facts, thread_locks)
    walker.walk_body(node.body)
    nested_names = [
        n.name
        for n in node.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    facts.returns_nested = tuple(
        e.detail for e in facts.events if e.kind == "return" and e.detail in nested_names
    )
    # identifier use index (use-after-donate read scan)
    uses: Dict[str, List[Tuple[int, int, str]]] = {}

    def record_uses(sub: ast.AST) -> None:
        if isinstance(sub, (ast.Name, ast.Attribute)):
            d = dotted(sub)
            if d is not None:
                ctx = getattr(sub, "ctx", None)
                kind = "store" if isinstance(ctx, (ast.Store, ast.Del)) else "load"
                uses.setdefault(d, []).append(
                    (sub.lineno, sub.col_offset, kind)
                )

    stack: List[ast.AST] = list(ast.iter_child_nodes(node))
    while stack:
        sub = stack.pop()
        if isinstance(sub, _FUNC_NODES):
            continue
        record_uses(sub)
        stack.extend(ast.iter_child_nodes(sub))
    facts.name_uses = {k: tuple(sorted(v)) for k, v in uses.items()}
    return facts


def extract_module(
    tree: ast.AST, source_lines: Sequence[str], path: str
) -> ModuleFacts:
    """One parsed file -> its picklable fact record."""
    thread_locks = collect_thread_lock_names(tree)
    mod = ModuleFacts(
        path=path,
        funcs=[],
        classes={},
        imports={},
        thread_locks=tuple(sorted(thread_locks)),
        donating_names={},
        pragmas=parse_pragmas(source_lines),
    )

    def add_function(node, cls: Optional[str], prefix: str) -> str:
        qualname = f"{path}::{prefix}{node.name}"
        while any(f.qualname == qualname for f in mod.funcs):
            qualname += "'"
        facts = _extract_function(node, path, cls, qualname, thread_locks)
        mod.funcs.append(facts)
        # directly nested defs get their own facts, scoped to the parent
        nested = []
        for sub in node.body:
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nested.append(add_function(sub, cls, f"{prefix}{node.name}."))
        facts.nested = tuple(nested)
        return qualname

    for node in tree.body if isinstance(tree, ast.Module) else []:
        if isinstance(node, ast.Import):
            for alias in node.names:
                mod.imports[alias.asname or alias.name.split(".")[-1]] = alias.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                mod.imports[alias.asname or alias.name] = f"{node.module}.{alias.name}"
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            add_function(node, None, "")
        elif isinstance(node, ast.ClassDef):
            bases = tuple(d for b in node.bases for d in [dotted(b)] if d)
            cf = ClassFacts(name=node.name, bases=bases, methods={})
            mod.classes[node.name] = cf
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qn = add_function(sub, node.name, f"{node.name}.")
                    cf.methods[sub.name] = qn

    # donating callables bound to names/attrs anywhere in the module:
    # ``step = tracked_jit(..., donate_argnums=...)`` (jax.jit(fn, ...) form)
    # and ``self._fn = tracked_jit(...)(fn)`` (factory form)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
            continue
        call = node.value
        spec = donation_spec(call)
        if spec is None and isinstance(call.func, ast.Call):
            spec = donation_spec(call.func)  # tracked_jit(...)(fn)
        if spec is None:
            continue
        for t in node.targets:
            seg = last_segment(t)
            if seg:
                mod.donating_names[seg] = spec
    return mod


# --------------------------------------------------------------- the project


class Project:
    """Whole-tree index + call resolution over extracted module facts."""

    def __init__(self, modules: Sequence[ModuleFacts]):
        self.modules: Dict[str, ModuleFacts] = {m.path: m for m in modules}
        self.functions: Dict[str, FunctionFacts] = {}
        self.by_name: Dict[str, List[str]] = {}
        self.classes: Dict[str, List[Tuple[str, ClassFacts]]] = {}
        self.thread_lock_names: Set[str] = set()
        self.donating_names: Dict[str, DonationSpec] = {}
        self._module_level: Dict[Tuple[str, str], str] = {}  # (path, fname) -> qn
        for m in modules:
            self.thread_lock_names.update(m.thread_locks)
            self.donating_names.update(m.donating_names)
            for cf in m.classes.values():
                self.classes.setdefault(cf.name, []).append((m.path, cf))
            for f in m.funcs:
                self.functions[f.qualname] = f
                self.by_name.setdefault(f.name, []).append(f.qualname)
                if "." not in f.qualname.split("::", 1)[1]:
                    self._module_level[(m.path, f.name)] = f.qualname

    # -- method resolution on self (walks base classes found in the tree)

    def _resolve_method(self, cls_name: str, method: str) -> Optional[str]:
        seen: Set[str] = set()
        queue = [cls_name]
        while queue:
            cname = queue.pop(0)
            if cname in seen:
                continue
            seen.add(cname)
            for _path, cf in self.classes.get(cname, []):
                qn = cf.methods.get(method)
                if qn is not None:
                    return qn
                queue.extend(b.split(".")[-1] for b in cf.bases)
        return None

    def resolve(
        self, call: CallEvent, caller: FunctionFacts
    ) -> Tuple[str, List[str]]:
        """(kind, qualnames) for a call site. kind: 'nested' | 'module' |
        'method' | 'import' | 'fallback' | 'none'. 'fallback' is the
        dynamic-dispatch join over every same-named function in the tree."""
        if call.kind == "self":
            if caller.cls is not None:
                qn = self._resolve_method(caller.cls, call.name)
                if qn is not None:
                    return "method", [qn]
            return self._fallback(call.name)
        if call.kind == "name":
            # nested def in the caller
            for qn in caller.nested:
                f = self.functions.get(qn)
                if f is not None and f.name == call.name:
                    return "nested", [qn]
            qn = self._module_level.get((caller.path, call.name))
            if qn is not None:
                return "module", [qn]
            target = self.modules[caller.path].imports.get(call.name)
            if target is not None:
                qn = self._resolve_import(target)
                if qn is not None:
                    return "import", [qn]
            return self._fallback(call.name)
        if call.kind == "dotted" and call.base is not None:
            # module-alias call: batching.foo(...)
            target = self.modules[caller.path].imports.get(call.base.split(".")[0])
            if target is not None:
                qn = self._resolve_import(f"{target}.{call.name}")
                if qn is not None:
                    return "import", [qn]
        return self._fallback(call.name)

    def _fallback(self, name: str) -> Tuple[str, List[str]]:
        qns = self.by_name.get(name, [])
        return ("fallback", list(qns)) if qns else ("none", [])

    def _resolve_import(self, target: str) -> Optional[str]:
        """'pkg.mod.func' -> qualname of a module-level func in a module
        whose path ends with mod.py (best-effort over the scanned tree)."""
        parts = target.split(".")
        if len(parts) < 2:
            return None
        fname, mod_tail = parts[-1], parts[-2]
        for (path, func_name), qn in self._module_level.items():
            if func_name != fname:
                continue
            base = path.replace("\\", "/").rsplit("/", 1)[-1]
            if base == f"{mod_tail}.py":
                return qn
        return None

    def callers_of(self, qualname: str) -> List[Tuple[FunctionFacts, CallEvent]]:
        """Every (caller, call site) in the tree that may target qualname."""
        target = self.functions.get(qualname)
        if target is None:
            return []
        out = []
        for f in self.functions.values():
            for c in f.calls:
                if c.name != target.name:
                    continue
                _kind, qns = self.resolve(c, f)
                if qualname in qns:
                    out.append((f, c))
        return out
