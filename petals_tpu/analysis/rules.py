"""swarmlint rules: this codebase's concurrency + tracer invariants as AST checks.

Each rule is a function ``rule(tree, source_lines, path) -> [(line, message)]``
registered in ``RULES``. Rules are heuristic but *named*: a finding is either
fixed or suppressed in-source with a reasoned pragma, so the whole tree stays
reviewable by ``python -m petals_tpu.analysis petals_tpu/``.

The rule set (motivation in each docstring):

- no-blocking-under-lock    — event-loop stalls: blocking device/host calls
                              inside ``async with <lock>`` bodies
- no-await-under-thread-lock — awaiting while a threading.Lock is held wedges
                              every other task needing that lock
- lock-order                — declared hierarchy, checked on lexical nesting
- paired-refcount           — incref/pin/adopt must have a release on exit paths
- no-orphan-task            — create_task results must be held + observed
- no-silent-except          — no broad swallow without log/raise in hot paths
- tracer-safety             — no host branching/impurity inside jit bodies
- no-unbounded-metric-labels — no request-controlled values (session/peer ids)
                              as metric labels: unbounded series cardinality
- no-naive-wallclock-in-span — durations/spans must come from a monotonic
                              clock, not time.time() subtraction (NTP slew)
- no-untracked-jit          — server hot paths must compile via tracked_jit
                              (compiled-program observatory), not bare jax.jit
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

Findings = List[Tuple[int, str]]

# ------------------------------------------------------------------ helpers


def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains (None for anything dynamic)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def last_segment(expr: ast.AST) -> Optional[str]:
    """Final identifier of a with-context expression; calls resolve through
    their callee (``self._lane_lock(lane)`` -> ``_lane_lock``)."""
    e = expr
    if isinstance(e, ast.Call):
        e = e.func
    if isinstance(e, ast.Attribute):
        return e.attr
    if isinstance(e, ast.Name):
        return e.id
    return None


_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def walk_no_functions(node: ast.AST) -> Iterator[ast.AST]:
    """ast.walk that does not descend into nested function bodies (their code
    runs at call time, not under the enclosing lock)."""
    if isinstance(node, _FUNC_NODES):
        return
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if not isinstance(child, _FUNC_NODES):
            stack.extend(ast.iter_child_nodes(child))


def iter_functions(tree: ast.AST) -> Iterator[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


LOCK_TOKEN = re.compile(r"(lock|turnstile|mutex|semaphore)", re.IGNORECASE)


def looks_like_lock(expr: ast.AST) -> bool:
    seg = last_segment(expr)
    return bool(seg and LOCK_TOKEN.search(seg))


# --------------------------------------------------- no-blocking-under-lock

BLOCKING_CALLS = {
    "time.sleep",
    "jax.block_until_ready",
    "jax.device_get",
    "jax.effects_barrier",
}
BLOCKING_METHODS = {"result", "block_until_ready"}  # X.result(), arr.block_until_ready()


def rule_no_blocking_under_lock(tree, source_lines, path) -> Findings:
    """No blocking host/device call inside an ``async with <lock>`` body: the
    event loop stalls for every session, and on this server a stalled loop
    also starves the compute queue's result futures."""
    out: Findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.AsyncWith):
            continue
        if not any(looks_like_lock(item.context_expr) for item in node.items):
            continue
        for sub in [n for b in node.body for n in [b, *walk_no_functions(b)]]:
            if not isinstance(sub, ast.Call):
                continue
            name = dotted(sub.func)
            if name in BLOCKING_CALLS:
                out.append(
                    (sub.lineno, f"blocking call {name}() inside an async lock body")
                )
            elif (
                isinstance(sub.func, ast.Attribute)
                and sub.func.attr in BLOCKING_METHODS
                and not sub.args
                and not sub.keywords
            ):
                out.append(
                    (
                        sub.lineno,
                        f".{sub.func.attr}() inside an async lock body can block "
                        "the event loop (await it or move it off-loop)",
                    )
                )
    return out


# ------------------------------------------------ no-await-under-thread-lock

THREAD_LOCK_CTORS = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "make_thread_lock",
    "sanitizer.make_thread_lock",
}


def collect_thread_lock_names(tree: ast.AST) -> Set[str]:
    names: Set[str] = set()
    for node in ast.walk(tree):
        value = None
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            value, targets = node.value, node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            value, targets = node.value, [node.target]
        if not isinstance(value, ast.Call):
            continue
        callee = dotted(value.func)
        if callee is None or callee.split(".", 1)[-1] not in {
            c.split(".", 1)[-1] for c in THREAD_LOCK_CTORS
        }:
            if callee not in THREAD_LOCK_CTORS:
                continue
        if not (
            callee in THREAD_LOCK_CTORS
            or callee.endswith(".Lock")
            or callee.endswith(".RLock")
            or callee.endswith(".Condition")
            or callee.endswith("make_thread_lock")
        ):
            continue
        for t in targets:
            seg = last_segment(t)
            if seg:
                names.add(seg)
    return names


def rule_no_await_under_thread_lock(tree, source_lines, path) -> Findings:
    """Never ``await`` while holding a ``threading.Lock``/``RLock``: the lock
    is NOT released at the suspension point, so the compute thread (or any
    other task running a ``with`` on it via the loop) blocks a kernel thread
    while the event loop believes it is making progress — the exact stall
    ``batching._reset_lock`` is one un-reviewed edit away from."""
    thread_locks = collect_thread_lock_names(tree)
    if not thread_locks:
        return []
    out: Findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.With):
            continue
        held = [
            last_segment(item.context_expr)
            for item in node.items
            if last_segment(item.context_expr) in thread_locks
        ]
        if not held:
            continue
        for sub in [n for b in node.body for n in [b, *walk_no_functions(b)]]:
            if isinstance(sub, (ast.Await, ast.AsyncWith, ast.AsyncFor)):
                out.append(
                    (
                        sub.lineno,
                        f"await while holding thread lock {held[0]!r} "
                        "(event-loop stall; release the lock first)",
                    )
                )
    return out


# ------------------------------------------------------------------ lock-order

# Declared hierarchy for this codebase (lower level acquired first). All lane
# locks share one level: ordering within a level is the sanitizer's job.
LOCK_HIERARCHY: Dict[str, int] = {
    "_open_lock": 0,
    "_lane_lock": 10,
    "_lane_locks": 10,
    "_swap_in_turnstile": 20,
    "_lock": 20,  # MemoryCache's pool lock
    "_reset_lock": 30,
    "_cv": 30,
}


def rule_lock_order(tree, source_lines, path) -> Findings:
    """Locks must be taken in declared order (``_open_lock`` -> lane lock ->
    pool lock/turnstile -> ``_reset_lock``): checked where statically
    resolvable, i.e. on lexically nested with-blocks inside one function."""
    out: Findings = []

    def visit(node: ast.AST, held: List[Tuple[str, int]]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNC_NODES):
                visit(child, [])  # new call frame: nesting does not carry over
                continue
            pushed = 0
            if isinstance(child, (ast.With, ast.AsyncWith)):
                for item in child.items:
                    seg = last_segment(item.context_expr)
                    if seg in LOCK_HIERARCHY:
                        level = LOCK_HIERARCHY[seg]
                        for h_seg, h_level in held:
                            if h_level > level:
                                out.append(
                                    (
                                        child.lineno,
                                        f"acquires {seg!r} (level {level}) while "
                                        f"holding {h_seg!r} (level {h_level}) — "
                                        "violates the declared lock hierarchy",
                                    )
                                )
                        held.append((seg, level))
                        pushed += 1
            visit(child, held)
            for _ in range(pushed):
                held.pop()

    visit(tree, [])
    return out


# ------------------------------------------------------------ paired-refcount

INCREF_CALLS = {"incref", "pin_lane_pages", "adopt_pages", "try_reserve"}
RELEASE_CALLS = {
    "decref",
    "unpin_pages",
    "free",
    "release",
    "release_lane",
    "release_temp",
}


def rule_paired_refcount(tree, source_lines, path) -> Findings:
    """Every incref/pin/adopt_pages/try_reserve needs a decref/release on ALL
    exit paths of the taking function (i.e. reachable from a finally/except),
    or an explicit ownership-transfer pragma — an unpaired reference leaks a
    page (or swap bytes) forever on the first exception."""
    out: Findings = []
    for fn in iter_functions(tree):
        inc_calls = []
        rel_anywhere = False
        rel_protected = False  # in a finally block or except handler
        has_await = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Await):
                has_await = True
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                if attr in INCREF_CALLS:
                    inc_calls.append(node)
                elif attr in RELEASE_CALLS:
                    rel_anywhere = True
        for node in ast.walk(fn):
            if isinstance(node, ast.Try):
                for region in [node.finalbody, *[h.body for h in node.handlers]]:
                    for stmt in region:
                        for sub in [stmt, *list(ast.walk(stmt))]:
                            if (
                                isinstance(sub, ast.Call)
                                and isinstance(sub.func, ast.Attribute)
                                and sub.func.attr in RELEASE_CALLS
                            ):
                                rel_protected = True
        if not inc_calls:
            continue
        if not rel_anywhere:
            out.append(
                (
                    inc_calls[0].lineno,
                    f"{inc_calls[0].func.attr}() in {fn.name}() has no matching "
                    "decref/release in this function (annotate ownership "
                    "transfer with a pragma if intentional)",
                )
            )
        elif has_await and not rel_protected:
            out.append(
                (
                    inc_calls[0].lineno,
                    f"{inc_calls[0].func.attr}() in {fn.name}() is not released "
                    "on all exit paths (no decref/release in a finally/except, "
                    "but the function can suspend or raise at an await)",
                )
            )
    return out


# ------------------------------------------------------------- no-orphan-task

TASK_SPAWN = {"create_task", "ensure_future"}


def _is_spawn(call: ast.AST) -> bool:
    return (
        isinstance(call, ast.Call)
        and isinstance(call.func, (ast.Attribute, ast.Name))
        and last_segment(call.func) in TASK_SPAWN
    )


def _target_key(target: ast.AST) -> Optional[Tuple[str, str]]:
    """(kind, ident) used to look the stored task back up: Name -> its id,
    Attribute -> the attr, Subscript -> the base name."""
    if isinstance(target, ast.Name):
        return ("name", target.id)
    if isinstance(target, ast.Attribute):
        return ("attr", target.attr)
    if isinstance(target, ast.Subscript):
        base = target.value
        seg = last_segment(base)
        return ("name", seg) if seg else None
    return None


def _matches_key(node: ast.AST, key: Tuple[str, str]) -> bool:
    kind, ident = key
    if kind == "name" and isinstance(node, ast.Name):
        return node.id == ident
    if kind == "attr" and isinstance(node, ast.Attribute):
        return node.attr == ident
    return False


def _key_observed(scope: ast.AST, key: Tuple[str, str]) -> bool:
    """True when the stored task is awaited (incl. via wait/gather/shield —
    anything inside an Await subtree) or given a done-callback in ``scope``."""
    for node in ast.walk(scope):
        if isinstance(node, ast.Await):
            if any(_matches_key(sub, key) for sub in ast.walk(node)):
                return True
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "add_done_callback"
            and any(_matches_key(sub, key) for sub in ast.walk(node.func.value))
        ):
            return True
    return False


def rule_no_orphan_task(tree, source_lines, path) -> Findings:
    """Every asyncio.create_task/ensure_future result must be stored AND
    observed (awaited, or given a done-callback): asyncio holds tasks weakly,
    so an unstored task can be garbage-collected mid-flight, and an
    unobserved one drops its exception on the floor."""
    out: Findings = []
    # map each function to its enclosing chain so attr-targets can fall back
    # to a module-wide search (self._task assigned here, awaited in close())
    enclosing: Dict[ast.AST, ast.AST] = {}
    for fn in iter_functions(tree):
        for child in ast.walk(fn):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)) and child is not fn:
                enclosing.setdefault(child, fn)

    def scope_of(node_fn: Optional[ast.AST]) -> ast.AST:
        return node_fn if node_fn is not None else tree

    fn_of: Dict[int, ast.AST] = {}
    for fn in iter_functions(tree):
        for child in ast.walk(fn):
            fn_of.setdefault(id(child), fn)

    for node in ast.walk(tree):
        if isinstance(node, ast.Expr) and _is_spawn(node.value):
            out.append(
                (
                    node.lineno,
                    "create_task result discarded: the task can be GC'd "
                    "mid-flight and its exception is lost — store it and "
                    "attach an exception-logging done-callback",
                )
            )
            continue
        if not isinstance(node, ast.Assign) or not _is_spawn(node.value):
            continue
        keys = [k for t in node.targets for k in [_target_key(t)] if k]
        if not keys:
            out.append((node.lineno, "create_task stored into an unresolvable target"))
            continue
        fn = fn_of.get(id(node))
        observed = False
        for key in keys:
            if _key_observed(scope_of(fn), key):
                observed = True
                break
            if key[0] == "attr" and _key_observed(tree, key):
                observed = True  # attribute task observed elsewhere in module
                break
        if not observed:
            out.append(
                (
                    node.lineno,
                    f"task stored in {ast.unparse(node.targets[0])!r} is never "
                    "awaited and has no done-callback: its exception would "
                    "vanish silently",
                )
            )
    return out


# ------------------------------------------------------------ no-silent-except

HOT_PATHS = ("/server/", "/ops/")
LOGGING_BASES = ("logger", "logging", "warnings")


def _handler_is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    for node in [t] if not isinstance(t, ast.Tuple) else t.elts:
        d = dotted(node)
        if d:
            names.append(d.split(".")[-1])
    return any(n in ("Exception", "BaseException") for n in names)


def rule_no_silent_except(tree, source_lines, path) -> Findings:
    """In server/ops hot paths, a broad ``except`` must re-raise, log, or use
    the caught exception — a silent swallow hides the first signal of device
    failures, refcount bugs, and protocol violations. Intentional best-effort
    sites stay, but as annotated suppressions with a reason."""
    norm = "/" + path.replace("\\", "/").lstrip("./")
    if not any(p in norm for p in HOT_PATHS):
        return []
    out: Findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler) or not _handler_is_broad(node):
            continue
        has_raise = any(isinstance(n, ast.Raise) for s in node.body for n in ast.walk(s))
        has_log = False
        uses_exc = False
        for s in node.body:
            for n in ast.walk(s):
                if isinstance(n, ast.Call):
                    d = dotted(n.func) or ""
                    root = d.split(".")[0]
                    if root in LOGGING_BASES or (
                        isinstance(n.func, ast.Attribute) and n.func.attr == "exception"
                    ):
                        has_log = True
                if node.name and isinstance(n, ast.Name) and n.id == node.name:
                    uses_exc = True
        if not (has_raise or has_log or uses_exc):
            out.append(
                (
                    node.lineno,
                    "broad except swallows the exception silently (no raise, "
                    "log, or use of the caught error) in a server/ops hot path",
                )
            )
    return out


# -------------------------------------------------------------- tracer-safety

IMPURE_CALLS = {
    "time.time",
    "time.monotonic",
    "time.perf_counter",
    "time.time_ns",
    "datetime.now",
    "datetime.datetime.now",
}
IMPURE_PREFIXES = ("np.random.", "numpy.random.", "random.")
SHAPE_ATTRS = {"shape", "ndim", "dtype", "size"}
HOST_GUARDS = {"len", "isinstance", "getattr", "hasattr", "range"}


_JIT_CALLEES = ("jax.jit", "jit")
# tracked_jit (telemetry.observatory) is jit with its compilations observed:
# tracer-safety applies to its wrapped functions exactly as to bare jit
_TRACKED_JIT_CALLEES = ("tracked_jit", "observatory.tracked_jit")


def _jit_static_names(dec: ast.AST) -> Optional[Set[str]]:
    """static_argnames of a jit decorator, or None when ``dec`` is not jit."""
    target = dec
    statics: Set[str] = set()
    if isinstance(dec, ast.Call):
        callee = dotted(dec.func)
        if callee in ("functools.partial", "partial"):
            if not dec.args:
                return None
            inner = dotted(dec.args[0])
            if inner not in _JIT_CALLEES:
                return None
        elif callee not in _JIT_CALLEES + _TRACKED_JIT_CALLEES:
            return None
        for kw in dec.keywords:
            if kw.arg == "static_argnames":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and isinstance(n.value, str):
                        statics.add(n.value)
        return statics
    name = dotted(target)
    if name in ("jax.jit", "jit"):
        return statics
    return None


def _param_names(fn) -> List[str]:
    a = fn.args
    names = [p.arg for p in [*a.posonlyargs, *a.args, *a.kwonlyargs]]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def _build_parents(root: ast.AST) -> Dict[int, ast.AST]:
    parents: Dict[int, ast.AST] = {}
    for node in ast.walk(root):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    return parents


def _guarded(name_node: ast.Name, parents: Dict[int, ast.AST]) -> bool:
    """A traced-param reference is harmless when only its static metadata is
    read: ``x.shape``/``x.ndim``/``len(x)``/``x is None`` etc."""
    node: ast.AST = name_node
    parent = parents.get(id(node))
    while parent is not None:
        if isinstance(parent, ast.Attribute) and parent.attr in SHAPE_ATTRS:
            return True
        if isinstance(parent, ast.Call):
            callee = dotted(parent.func)
            if callee in HOST_GUARDS:
                return True
            if isinstance(parent.func, ast.Attribute) and node is parent.func:
                # x.astype(...) etc: the call itself is traced, keep climbing
                pass
        if isinstance(parent, ast.Compare):
            comparators = [parent.left, *parent.comparators]
            others = [c for c in comparators if c is not node]
            if all(
                isinstance(c, ast.Constant) and c.value is None for c in others
            ) and all(isinstance(op, (ast.Is, ast.IsNot)) for op in parent.ops):
                return True
        if isinstance(parent, (ast.Subscript,)) and parent.value is node:
            # x[...] stays traced; keep climbing
            pass
        node, parent = parent, parents.get(id(parent))
    return False


def _traced_refs(test: ast.AST, traced: Set[str], parents) -> List[ast.Name]:
    return [
        n
        for n in ast.walk(test)
        if isinstance(n, ast.Name) and n.id in traced and not _guarded(n, parents)
    ]


def rule_tracer_safety(tree, source_lines, path) -> Findings:
    """Inside ``@jax.jit`` bodies: no Python branching on traced values (each
    branch bakes ONE outcome into the compiled program or triggers a
    recompile per distinct value), no ``int()``/``.item()`` forcing a device
    sync, and no wall-clock/np.random impurity (traced once, then frozen as a
    constant in every later step)."""
    out: Findings = []
    for fn in iter_functions(tree):
        statics: Optional[Set[str]] = None
        for dec in fn.decorator_list:
            s = _jit_static_names(dec)
            if s is not None:
                statics = s
                break
        if statics is None:
            continue
        traced = {p for p in _param_names(fn) if p not in statics and p != "self"}
        # nested defs (scan/cond bodies) trace their params too
        for sub in ast.walk(fn):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) and sub is not fn:
                traced |= {p for p in _param_names(sub) if p not in statics}
        parents = _build_parents(fn)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                d = dotted(node.func) or ""
                if d in IMPURE_CALLS or any(d.startswith(p) for p in IMPURE_PREFIXES):
                    out.append(
                        (
                            node.lineno,
                            f"{d}() inside a jit body is traced ONCE and baked "
                            "into the compiled program (wrong constants / no "
                            "fresh randomness per step)",
                        )
                    )
                elif isinstance(node.func, ast.Attribute) and node.func.attr == "item":
                    out.append(
                        (
                            node.lineno,
                            ".item() inside a jit body forces a host sync / "
                            "fails on tracers",
                        )
                    )
                elif (
                    isinstance(node.func, ast.Name)
                    and node.func.id in ("int", "float", "bool")
                    and node.args
                    and _traced_refs(node.args[0], traced, parents)
                ):
                    out.append(
                        (
                            node.lineno,
                            f"{node.func.id}() on a traced value inside a jit "
                            "body (concretization error or silent recompile "
                            "per distinct value)",
                        )
                    )
            elif isinstance(node, (ast.If, ast.While, ast.IfExp)):
                refs = _traced_refs(node.test, traced, parents)
                if refs:
                    out.append(
                        (
                            node.lineno,
                            f"Python branch on traced value {refs[0].id!r} "
                            "inside a jit body — use lax.cond/jnp.where, or "
                            "mark the argument static",
                        )
                    )
    return out


# ------------------------------------------- no-unbounded-metric-labels

# Identifier fragments that mark a value as request-controlled: one metric
# label fed from these on a public swarm means one SERIES PER CLIENT —
# unbounded memory until the registry's cardinality cap silently routes
# everything to the overflow series and the metric stops meaning anything.
TAINTED_LABEL_NAMES = {
    "session_id",
    "peer_id",
    "trace_id",
    "request_id",
    "client_id",
    "uid",
    "uids",
    "session",
    "peer",
    # activation-fingerprint digests (ops/fingerprint.py): one distinct
    # value per (session, position) — worse than per-client cardinality.
    # Divergence evidence belongs in journal/flight events, never labels.
    "fp",
    "fingerprint",
    "digest",
    "digest_hex",
    "fp_hex",
}


def _label_value_names(node: ast.AST) -> Iterator[str]:
    """Identifier-ish names reachable from one labels() argument value:
    bare names, attribute tails (``slot.peer_id`` -> ``peer_id``), string-
    constant subscript keys (``entry["peer_id"]`` -> ``peer_id`` — how the
    ledger's per-peer dicts are keyed), and any of these inside f-strings /
    str() / formatting calls."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            yield sub.id
        elif isinstance(sub, ast.Attribute):
            yield sub.attr
        elif (
            isinstance(sub, ast.Subscript)
            and isinstance(sub.slice, ast.Constant)
            and isinstance(sub.slice.value, str)
        ):
            yield sub.slice.value


def rule_no_unbounded_metric_labels(tree, source_lines, path) -> Findings:
    """``.labels(...)`` with a request-controlled value (session/peer/trace
    ids) creates one time series per client. The telemetry registry caps
    cardinality, but hitting the cap degrades the whole metric to the
    ``_overflow`` series — label sets must be STATIC (variant/mode/direction
    enums), with per-request identity carried in spans and journal events
    instead (telemetry/instruments.py)."""
    out: Findings = []
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "labels"
        ):
            continue
        values = list(node.args) + [kw.value for kw in node.keywords]
        for value in values:
            tainted = sorted(
                {
                    name
                    for name in _label_value_names(value)
                    if name.strip("_").lower() in TAINTED_LABEL_NAMES
                }
            )
            if tainted:
                out.append(
                    (
                        node.lineno,
                        f"request-controlled value {tainted[0]!r} used as a "
                        "metric label: one series per client is unbounded "
                        "cardinality — use a static label set and put the id "
                        "in a span/journal event instead",
                    )
                )
                break
    return out


# ------------------------------------------- no-naive-wallclock-in-span


def _is_wallclock_call(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and not node.args
        and not node.keywords
        and dotted(node.func) == "time.time"
    )


def rule_no_naive_wallclock_in_span(tree, source_lines, path) -> Findings:
    """Durations computed from ``time.time()`` go backwards under NTP slew
    and stamp negative queue/compute components into spans and trace
    reports. Latency attribution must use a monotonic clock
    (``time.perf_counter()`` / ``time.monotonic()``). ``time.time()`` as an
    absolute TIMESTAMP (journal events, flight-recorder entries) is fine —
    only arithmetic that turns it into a duration is flagged: a subtraction
    whose operand is ``time.time()`` itself or a local assigned from it."""
    out: Findings = []
    scopes = [tree] + list(iter_functions(tree))
    for scope in scopes:
        body = getattr(scope, "body", [])
        nodes = [n for b in body for n in [b, *walk_no_functions(b)]]
        wall_names: Set[str] = set()
        for node in nodes:
            if isinstance(node, ast.Assign) and _is_wallclock_call(node.value):
                wall_names.update(
                    t.id for t in node.targets if isinstance(t, ast.Name)
                )

        def from_wallclock(expr: ast.AST) -> bool:
            return _is_wallclock_call(expr) or (
                isinstance(expr, ast.Name) and expr.id in wall_names
            )

        for node in nodes:
            if (
                isinstance(node, ast.BinOp)
                and isinstance(node.op, ast.Sub)
                and (from_wallclock(node.left) or from_wallclock(node.right))
            ):
                out.append(
                    (
                        node.lineno,
                        "duration computed from time.time(): the wall clock "
                        "is not monotonic (NTP slew makes spans negative) — "
                        "use time.perf_counter() or time.monotonic() for "
                        "latency attribution",
                    )
                )
    return out


# ---------------------------------------------------------- no-untracked-jit


def _imports_bare_jit(tree: ast.AST) -> bool:
    """True when the module does ``from jax import jit`` (so a bare ``jit``
    name refers to the compiler, not some local helper)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            if any(alias.name == "jit" for alias in node.names):
                return True
    return False


# Kernel modules whose entry points sit INSIDE the per-step hot path (called
# from the jitted step programs or jitted at the top level themselves): an
# invisible compile here is exactly the recompile-storm class the observatory
# exists to catch. Generic ops/ modules stay out of scope — most compile cold
# at load time or only under tests.
_JIT_HOT_KERNEL_MODULES = (
    "petals_tpu/ops/flash_attention.py",
    "petals_tpu/ops/paged_flash_attention.py",
)


def rule_no_untracked_jit(tree, source_lines, path) -> Findings:
    """Server hot paths must compile through ``telemetry.observatory
    .tracked_jit`` so every executable lands in the compiled-program
    observatory (recompile sentinel, cost table, compile-count gate). A bare
    ``jax.jit`` — as a decorator, a call, or inside ``functools.partial(
    jax.jit, ...)`` — creates programs the observatory cannot see. Scoped to
    ``petals_tpu/server/`` plus the attention-kernel hot modules; genuinely
    cold paths (one-shot load-time compiles) are pragma-exempted with a
    reason."""
    norm = path.replace("\\", "/")
    if "petals_tpu/server/" not in norm and not norm.endswith(
        _JIT_HOT_KERNEL_MODULES
    ):
        return []
    bare_jit = _imports_bare_jit(tree)
    out: Findings = []
    for node in ast.walk(tree):
        hit = (
            isinstance(node, ast.Attribute) and dotted(node) == "jax.jit"
        ) or (bare_jit and isinstance(node, ast.Name) and node.id == "jit")
        if hit:
            out.append(
                (
                    node.lineno,
                    "bare jax.jit bypasses the compiled-program observatory "
                    "(no recompile sentinel, no cost attribution, invisible "
                    "to the bench compile gate) — route through "
                    "telemetry.observatory.tracked_jit, or pragma-exempt a "
                    "genuinely cold path with a reason",
                )
            )
    return out


# ------------------------------------------------------------------ registry

RULES = {
    "no-blocking-under-lock": rule_no_blocking_under_lock,
    "no-await-under-thread-lock": rule_no_await_under_thread_lock,
    "lock-order": rule_lock_order,
    "paired-refcount": rule_paired_refcount,
    "no-orphan-task": rule_no_orphan_task,
    "no-silent-except": rule_no_silent_except,
    "tracer-safety": rule_tracer_safety,
    "no-unbounded-metric-labels": rule_no_unbounded_metric_labels,
    "no-naive-wallclock-in-span": rule_no_naive_wallclock_in_span,
    "no-untracked-jit": rule_no_untracked_jit,
}
