"""swarmlint engine: run every rule over a file tree, apply pragmas.

Library entry points:

- ``check_source(source, path)`` -> list[Finding] (pragmas applied)
- ``check_file(path)`` / ``check_paths(paths)`` -> same, reading from disk
- ``unsuppressed(findings)`` -> the findings that should fail a build
"""

from __future__ import annotations

import ast
import os
from typing import Iterable, Iterator, List, Optional, Sequence

from .findings import Finding, apply_pragmas, parse_pragmas
from .rules import RULES

SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules", "build", "dist"}


def check_source(
    source: str, path: str, rules: Optional[Sequence[str]] = None
) -> List[Finding]:
    """Run the (selected) rules over one source string; apply its pragmas."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [
            Finding(
                rule="syntax-error",
                path=path,
                line=e.lineno or 0,
                message=f"file does not parse: {e.msg}",
            )
        ]
    lines = source.splitlines()
    selected = rules if rules is not None else list(RULES)
    findings: List[Finding] = []
    for name in selected:
        for line, message in RULES[name](tree, lines, path):
            findings.append(Finding(rule=name, path=path, line=line, message=message))
    pragmas = parse_pragmas(lines)
    findings = apply_pragmas(findings, pragmas, path, known_rules=list(RULES))
    findings.sort(key=lambda f: (f.line, f.rule))
    return findings


def check_file(path: str, rules: Optional[Sequence[str]] = None) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as f:
        return check_source(f.read(), path, rules=rules)


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    for root in paths:
        if os.path.isfile(root):
            if root.endswith(".py"):
                yield root
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames if d not in SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def check_paths(
    paths: Iterable[str], rules: Optional[Sequence[str]] = None
) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        findings.extend(check_file(path, rules=rules))
    return findings


def unsuppressed(findings: Iterable[Finding]) -> List[Finding]:
    return [f for f in findings if not f.suppressed]
