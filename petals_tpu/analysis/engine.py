"""swarmlint engine: run every rule over a file tree, apply pragmas.

Library entry points:

- ``check_source(source, path)`` -> list[Finding] — v1 per-function rules
  over one source string (pragmas applied).
- ``check_file(path)`` / ``check_paths(paths)`` -> same, reading from disk
  through the per-file parse cache.
- ``check_project(paths, jobs=N)`` -> list[Finding] — the v2 engine: per-file
  rules AND the interprocedural passes (call graph + summaries + fixpoint)
  over the whole tree at once. Fact extraction parallelizes across worker
  processes; only picklable fact records cross back, never ASTs. In project
  mode the per-function versions of ``paired-refcount`` and
  ``no-await-under-thread-lock`` are replaced by their interprocedural
  supersets (same lines for the lexical cases, so pragmas keep working),
  and stale pragmas — suppressions that suppress nothing — become findings.
- ``check_sources({path: source})`` -> project mode over in-memory sources
  (fixture corpora in tests).
- ``unsuppressed(findings)`` -> the findings that should fail a build.
- ``fingerprint(finding)`` -> stable id for the committed-baseline gate.
"""

from __future__ import annotations

import ast
import concurrent.futures
import hashlib
import os
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

from .callgraph import ModuleFacts, Project, extract_module
from .findings import (
    Finding,
    Pragma,
    apply_pragmas,
    parse_pragmas,
    stale_pragma_findings,
)
from .interp import INTERP_RULES, NEW_RULE_NAMES, REPLACES_V1, run_interp_rules
from .rules import RULES
from .summaries import Summaries

SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules", "build", "dist"}

# every name a pragma may legally disable: v1 rules + the interp-only
# families. Used everywhere known_rules is needed so a pragma naming e.g.
# ``use-after-donate`` is not flagged pragma-unknown-rule by a v1-only run.
ALL_RULE_NAMES: Tuple[str, ...] = tuple(sorted(set(RULES) | set(INTERP_RULES)))


def check_source(
    source: str, path: str, rules: Optional[Sequence[str]] = None
) -> List[Finding]:
    """Run the (selected) v1 rules over one source string; apply its pragmas."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [
            Finding(
                rule="syntax-error",
                path=path,
                line=e.lineno or 0,
                message=f"file does not parse: {e.msg}",
            )
        ]
    lines = source.splitlines()
    selected = rules if rules is not None else list(RULES)
    findings: List[Finding] = []
    for name in selected:
        for line, message in RULES[name](tree, lines, path):
            findings.append(Finding(rule=name, path=path, line=line, message=message))
    pragmas = parse_pragmas(lines)
    findings = apply_pragmas(findings, pragmas, path, known_rules=ALL_RULE_NAMES)
    findings.sort(key=lambda f: (f.line, f.rule))
    return findings


# ------------------------------------------------------------ parse cache

# path -> ((mtime_ns, size), tree, source_lines). Per process; worker
# processes build their own. Re-stat on every hit so edits invalidate.
_PARSE_CACHE: Dict[str, Tuple[Tuple[int, int], ast.AST, List[str]]] = {}


def _read_parsed(path: str) -> Tuple[ast.AST, List[str]]:
    st = os.stat(path)
    key = (st.st_mtime_ns, st.st_size)
    hit = _PARSE_CACHE.get(path)
    if hit is not None and hit[0] == key:
        return hit[1], hit[2]
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    tree = ast.parse(source, filename=path)
    lines = source.splitlines()
    _PARSE_CACHE[path] = (key, tree, lines)
    return tree, lines


def check_file(path: str, rules: Optional[Sequence[str]] = None) -> List[Finding]:
    try:
        tree, lines = _read_parsed(path)
    except SyntaxError as e:
        return [
            Finding(
                rule="syntax-error",
                path=path,
                line=e.lineno or 0,
                message=f"file does not parse: {e.msg}",
            )
        ]
    selected = rules if rules is not None else list(RULES)
    findings: List[Finding] = []
    for name in selected:
        for line, message in RULES[name](tree, lines, path):
            findings.append(Finding(rule=name, path=path, line=line, message=message))
    findings = apply_pragmas(
        findings, parse_pragmas(lines), path, known_rules=ALL_RULE_NAMES
    )
    findings.sort(key=lambda f: (f.line, f.rule))
    return findings


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    for root in paths:
        if os.path.isfile(root):
            if root.endswith(".py"):
                yield root
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames if d not in SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def check_paths(
    paths: Iterable[str], rules: Optional[Sequence[str]] = None
) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        findings.extend(check_file(path, rules=rules))
    return findings


def unsuppressed(findings: Iterable[Finding]) -> List[Finding]:
    return [f for f in findings if not f.suppressed]


# --------------------------------------------------------------- project mode


def _analyze_one(
    path: str,
    v1_rules: Sequence[str],
    source: Optional[str] = None,
) -> Tuple[List[Finding], Optional[ModuleFacts]]:
    """Per-file half of project mode: v1 findings (un-pragma'd — pragmas are
    applied centrally after the interp pass) + extracted module facts.
    Module-level and picklable so it can run in a worker process."""
    try:
        if source is None:
            tree, lines = _read_parsed(path)
        else:
            tree = ast.parse(source, filename=path)
            lines = source.splitlines()
    except SyntaxError as e:
        return (
            [
                Finding(
                    rule="syntax-error",
                    path=path,
                    line=e.lineno or 0,
                    message=f"file does not parse: {e.msg}",
                )
            ],
            None,
        )
    findings: List[Finding] = []
    for name in v1_rules:
        for line, message in RULES[name](tree, lines, path):
            findings.append(Finding(rule=name, path=path, line=line, message=message))
    return findings, extract_module(tree, lines, path)


def _resolve_jobs(jobs: int, n_files: int) -> int:
    if jobs == 0:
        jobs = min(os.cpu_count() or 1, 8)
    return max(1, min(jobs, n_files))


def check_project(
    paths: Iterable[str],
    *,
    sources: Optional[Dict[str, str]] = None,
    rules: Optional[Sequence[str]] = None,
    jobs: int = 1,
    interp: bool = True,
) -> List[Finding]:
    """The v2 engine: v1 per-file rules + the interprocedural passes over the
    whole tree, pragmas applied once at the end. ``sources`` maps path ->
    source text for in-memory analysis (tests); otherwise ``paths`` is
    walked. ``jobs`` parallelizes fact extraction (0 = one per core, capped)."""
    selected = list(rules) if rules is not None else list(ALL_RULE_NAMES)
    v1_rules = [r for r in selected if r in RULES]
    if interp:
        v1_rules = [r for r in v1_rules if r not in REPLACES_V1]
        interp_rules = [r for r in selected if r in INTERP_RULES]
    else:
        interp_rules = []
    full_run = rules is None and interp

    if sources is not None:
        files = list(sources)
        results = [_analyze_one(p, v1_rules, source=sources[p]) for p in files]
    else:
        files = list(iter_python_files(paths))
        results = _map_files(files, v1_rules, _resolve_jobs(jobs, len(files)))

    findings: List[Finding] = []
    modules: List[ModuleFacts] = []
    for per_file, mod in results:
        findings.extend(per_file)
        if mod is not None:
            modules.append(mod)

    if interp_rules and modules:
        project = Project(modules)
        summaries = Summaries(project)
        for rule, path, line, message in run_interp_rules(
            project, summaries, only=interp_rules
        ):
            findings.append(Finding(rule=rule, path=path, line=line, message=message))

    # dedup (a lexical case reported by both layers), then pragmas per module
    seen = set()
    deduped: List[Finding] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule, f.message)):
        key = (f.rule, f.path, f.line)
        if key in seen:
            continue
        seen.add(key)
        deduped.append(f)

    by_path: Dict[str, List[Finding]] = {}
    for f in deduped:
        by_path.setdefault(f.path, []).append(f)
    out: List[Finding] = []
    for mod in modules:
        per_file = apply_pragmas(
            by_path.pop(mod.path, []),
            mod.pragmas,
            mod.path,
            known_rules=ALL_RULE_NAMES,
        )
        if full_run:
            per_file.extend(
                stale_pragma_findings(mod.pragmas, mod.path, ALL_RULE_NAMES)
            )
        out.extend(per_file)
    for leftover in by_path.values():  # files that failed to parse
        out.extend(leftover)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def check_sources(
    sources: Dict[str, str],
    rules: Optional[Sequence[str]] = None,
    interp: bool = True,
) -> List[Finding]:
    """Project mode over an in-memory fixture corpus."""
    return check_project([], sources=sources, rules=rules, interp=interp)


def _map_files(
    files: Sequence[str], v1_rules: Sequence[str], jobs: int
) -> List[Tuple[List[Finding], Optional[ModuleFacts]]]:
    if jobs <= 1 or len(files) <= 1:
        return [_analyze_one(p, v1_rules) for p in files]
    try:
        chunk = max(1, len(files) // (jobs * 4))
        with concurrent.futures.ProcessPoolExecutor(max_workers=jobs) as pool:
            return list(
                pool.map(
                    _analyze_one, files, [v1_rules] * len(files), chunksize=chunk
                )
            )
    except (OSError, PermissionError, concurrent.futures.process.BrokenProcessPool):
        # restricted environments (no fork / no semaphores): degrade serially
        return [_analyze_one(p, v1_rules) for p in files]


# ------------------------------------------------------------------ baseline


def fingerprint(f: Finding) -> str:
    """Stable id for the committed-baseline gate: rule + path (as given) +
    message, NOT the line number, so pure line drift does not churn the
    baseline while any change to what the rule saw does."""
    digest = hashlib.sha1(
        f"{f.rule}|{f.path}|{f.message}".encode("utf-8")
    ).hexdigest()
    return digest[:16]
