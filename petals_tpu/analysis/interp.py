"""Interprocedural swarmlint rules over callgraph + summaries.

Three upgraded families replace or extend their v1 per-function versions:

- ``no-blocking-under-lock``  (additive) — v1 flags a *direct* blocking call
  inside an ``async with <lock>`` body; this pass also flags a call that
  *resolves to* a project function whose summary says it may block, any
  number of helpers down, with the witness chain in the message.
- ``no-await-under-thread-lock`` (replaces v1) — the lexical check (await /
  async-with / async-for inside ``with <thread lock>``) at the SAME lines as
  v1 so existing pragmas keep working, plus the hidden-acquire case: a
  helper that ``.acquire()``s a thread lock and returns holding it
  (net lock summary), after which the caller awaits.
- ``paired-refcount`` (replaces v1) — "takes" now include calls to helpers
  with a net incref effect, releases include calls to net-release helpers,
  and a release is exit-path-protected when it happens in a finally/except
  *or* via a helper called there. Kills both v1 blind spots: the leak hidden
  in a helper, and the false positive on ``finally: self._cleanup(page)``.

Three new families ride on the same summaries:

- ``use-after-donate`` — a call whose resolved target donates an argument
  buffer to XLA (``donate_argnums``/``donate_argnames`` on tracked_jit /
  jax.jit, including the property-returns-a-donating-``step`` idiom in
  backend.py) followed by a read of that same name: the buffer is dead. A
  rebind of the name (including ``k, v = step(params, k, v)``) cleans it.
  Reads reached only via a loop back-edge are a documented miss.
- ``cancellation-safety`` — inside an ``async with <lock>`` region, once an
  invariant goes dirty (typestate flip, page incref, mutation of a critical
  field — directly or via a resolved helper), every later ``await`` in the
  region is a cancellation point that can abandon the half-done transition;
  it must sit under a ``try`` with a ``finally`` or a handler catching
  BaseException/CancelledError. Helpers themselves are checked too when any
  call site holds an async lock.
- ``lane-typestate`` — the declared lane/session lifecycle
  (``LANE_TYPESTATE``) enforced at every ``suspending``/``swap`` store in
  ``server/``: the lane lock must be held (lexically, via an earlier
  trylock in the same function, or because every caller holds it), a swap
  entry may only be installed while suspending, and a ``suspending = True``
  followed by awaits needs a cleanup-path reset.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Set, Tuple

from .callgraph import CRITICAL_FIELDS, CallEvent, Event, FunctionFacts, Project
from .summaries import _RESOLVED_KINDS, Summaries, render_chain

RawFinding = Tuple[str, str, int, str]  # (rule, path, line, message)

# Declared lane/session state machine (ROADMAP PRs 4/9/16/17). The table is
# the documentation of record (README renders it); the checks below enforce
# its mechanizable projection onto the two persisted fields:
#   suspending=True  : active -> suspending        (lane lock held)
#   swap=<entry>     : suspending -> swapped        (only while suspending)
#   suspending=False : suspending -> suspended/active (incl. cleanup paths)
#   swap=None        : swapped -> active/migrated/handed-off (lane lock held)
LANE_TYPESTATE: Dict[str, Tuple[str, ...]] = {
    "active": ("suspending",),
    "suspending": ("suspended", "swapped", "active"),
    "suspended": ("swapped", "active"),
    "swapped": ("active", "migrated", "handed-off"),
    "migrated": (),
    "handed-off": (),
}


def _is_lane_lock(name: str) -> bool:
    n = name.lower()
    return "lane" in n and "lock" in n


def _ordered(f: FunctionFacts) -> List[Tuple[str, object]]:
    """Events and call sites of one function merged into source order."""
    items: List[Tuple[int, int, str, object]] = []
    for e in f.events:
        items.append((e.line, e.col, "event", e))
    for c in f.calls:
        items.append((c.line, c.col, "call", c))
    items.sort(key=lambda t: (t[0], t[1]))
    return [(kind, obj) for _l, _c, kind, obj in items]


def _try_protected(trys) -> bool:
    return any(has_finally or catches for _line, has_finally, catches in trys)


# ----------------------------------------------------- no-blocking-under-lock


def interp_no_blocking_under_lock(
    project: Project, summaries: Summaries
) -> List[RawFinding]:
    out: List[RawFinding] = []
    for f in project.functions.values():
        for call in f.calls:
            if not any(is_async for _n, is_async, _l in call.locks):
                continue
            kind, targets = summaries.resolve(call, f)
            if kind not in _RESOLVED_KINDS:
                continue
            for qn in targets:
                s = summaries.by_qualname.get(qn)
                if s is None or s.may_block is None:
                    continue
                # direct blocking calls are v1's finding; only report the
                # hidden-in-a-helper chain here
                if len(s.may_block) == 0:
                    continue
                out.append(
                    (
                        "no-blocking-under-lock",
                        f.path,
                        call.line,
                        f"{call.name}() called under an async lock can block "
                        f"the event loop: {render_chain(s.may_block)}",
                    )
                )
                break
    return out


# -------------------------------------------------- no-await-under-thread-lock


def interp_no_await_under_thread_lock(
    project: Project, summaries: Summaries
) -> List[RawFinding]:
    out: List[RawFinding] = []
    for f in project.functions.values():
        mod_locks = set(project.modules[f.path].thread_locks)
        # lexical case — identical lines to the v1 rule
        for e in f.events:
            if e.kind != "await":
                continue
            held = [n for n, is_async, _l in e.locks if not is_async and n in mod_locks]
            if held:
                out.append(
                    (
                        "no-await-under-thread-lock",
                        f.path,
                        e.line,
                        f"await while holding thread lock {held[0]!r} "
                        "(event-loop stall; release the lock first)",
                    )
                )
        # hidden-acquire case: a thread lock left held by an earlier
        # .acquire() or a helper with a net-acquire summary
        held_manual: Dict[str, str] = {}  # lock -> how it was taken
        for kind, obj in _ordered(f):
            if kind == "event":
                e = obj
                if e.kind == "lock_acq" and e.detail in project.thread_lock_names:
                    held_manual.setdefault(e.detail, f"{e.detail}.acquire()")
                elif e.kind == "lock_rel":
                    held_manual.pop(e.detail, None)
                elif e.kind == "await" and held_manual:
                    lock, how = next(iter(held_manual.items()))
                    lexical = {n for n, _a, _l in e.locks}
                    if lock in lexical:
                        continue  # already reported by the lexical case
                    out.append(
                        (
                            "no-await-under-thread-lock",
                            f.path,
                            e.line,
                            f"await while thread lock {lock!r} is still held "
                            f"(taken via {how}; release it before suspending)",
                        )
                    )
            else:
                call = obj
                rkind, targets = summaries.resolve(call, f)
                if rkind not in _RESOLVED_KINDS:
                    continue
                for qn in targets:
                    s = summaries.by_qualname.get(qn)
                    if s is None:
                        continue
                    for lock, chain in s.net_lock_acq.items():
                        held_manual.setdefault(
                            lock, f"{call.name}() -> {render_chain(chain)}"
                        )
                    for lock in s.net_lock_rel:
                        held_manual.pop(lock, None)
    return out


# ------------------------------------------------------------ paired-refcount


def interp_paired_refcount(
    project: Project, summaries: Summaries
) -> List[RawFinding]:
    out: List[RawFinding] = []
    # a release protects the exit paths of an AWAITING function only when
    # its cleanup region still runs on cancellation: finally, or a handler
    # catching BaseException/CancelledError. ``except Exception`` does not —
    # a task cancelled at an await skips it and the reference leaks.
    _PROTECTING = ("finally", "except_cancel")
    for f in project.functions.values():
        takes: List[Tuple[int, str, str]] = []  # (line, name, via)
        rel_anywhere = False
        rel_protected = False
        rel_cleanup_kinds: Set[str] = set()
        has_await = any(e.kind == "await" for e in f.events)
        for e in f.events:
            if e.kind == "ref_inc":
                takes.append((e.line, e.detail, "direct"))
            elif e.kind == "ref_rel":
                rel_anywhere = True
                if e.cleanup:
                    rel_cleanup_kinds.add(e.cleanup_kind)
                    if e.cleanup_kind in _PROTECTING:
                        rel_protected = True
        for call in f.calls:
            kind, targets = summaries.resolve(call, f)
            if kind not in _RESOLVED_KINDS:
                continue
            for qn in targets:
                s = summaries.by_qualname.get(qn)
                if s is None:
                    continue
                if s.net_ref_inc is not None:
                    takes.append(
                        (call.line, call.name, render_chain(s.net_ref_inc))
                    )
                if s.net_ref_rel is not None:
                    rel_anywhere = True
                    if call.cleanup:
                        rel_cleanup_kinds.add(call.cleanup_kind)
                        if call.cleanup_kind in _PROTECTING:
                            rel_protected = True
                break
        if not takes:
            continue
        takes.sort()
        line, name, via = takes[0]
        hidden = "" if via == "direct" else f" (takes a reference via {via})"
        if not rel_anywhere:
            out.append(
                (
                    "paired-refcount",
                    f.path,
                    line,
                    f"{name}() in {f.name}() has no matching decref/release in "
                    f"this function{hidden} (annotate ownership transfer with "
                    "a pragma if intentional)",
                )
            )
        elif has_await and not rel_protected:
            detail = (
                "the only cleanup-path release is under `except Exception`, "
                "which a task cancelled at an await skips — use finally or "
                "catch BaseException"
                if "except" in rel_cleanup_kinds
                else "no decref/release reachable from a finally/except, but "
                "the function can suspend or raise at an await"
            )
            out.append(
                (
                    "paired-refcount",
                    f.path,
                    line,
                    f"{name}() in {f.name}() is not released on all exit "
                    f"paths{hidden} ({detail})",
                )
            )
    return out


# ------------------------------------------------------------ use-after-donate


def interp_use_after_donate(
    project: Project, summaries: Summaries
) -> List[RawFinding]:
    out: List[RawFinding] = []
    for f in project.functions.values():
        for call in f.calls:
            donated = summaries.donated_positions(call, f)
            if not donated:
                continue
            for pos, argname, chain in donated:
                names: List[str] = []
                for i, d in call.args:
                    if i == pos and d is not None:
                        names.append(d)
                if argname is not None:
                    for kw, d in call.kwargs:
                        if kw == argname and d is not None:
                            names.append(d)
                for d in names:
                    if d in call.assigns:
                        continue  # k, v = step(params, k, v): rebound, clean
                    verdict = _first_read_after(f, d, call)
                    if verdict is not None:
                        out.append(
                            (
                                "use-after-donate",
                                f.path,
                                verdict,
                                f"{d!r} is read after being donated to "
                                f"{call.name}() at line {call.line} "
                                f"({render_chain(chain)}); the donated buffer "
                                "is invalidated by XLA — reload it from the "
                                "call's result instead",
                            )
                        )
    return out


def _first_read_after(
    f: FunctionFacts, name: str, call: CallEvent
) -> Optional[int]:
    """Line of the first load of ``name`` strictly after ``call`` ends, or
    None if the name is rebound first (or never read again). Prefix reads of
    a dotted name (``x`` stored cleans ``x.attr``) are handled by also
    honoring stores to any dotted prefix."""
    prefixes = {name}
    parts = name.split(".")
    for i in range(1, len(parts)):
        prefixes.add(".".join(parts[:i]))
    after: List[Tuple[int, int, str, str]] = []
    for used, uses in f.name_uses.items():
        if used != name and used not in prefixes:
            continue
        for line, col, kind in uses:
            if (line, col) > (call.end_line, call.end_col):
                after.append((line, col, kind, used))
    after.sort()
    for line, _col, kind, used in after:
        if kind == "store":
            return None  # rebound before any read
        if used == name:
            return line
    return None


# -------------------------------------------------------- cancellation-safety


def interp_cancellation_safety(
    project: Project, summaries: Summaries
) -> List[RawFinding]:
    out: List[RawFinding] = []
    locked_helpers: Set[str] = set()
    for f in project.functions.values():
        out.extend(_scan_regions(f, summaries, locked_helpers))
    # helpers invoked while an async lock is held: their whole body runs
    # inside the caller's critical region, so check them the same way
    for qn in sorted(locked_helpers):
        t = project.functions.get(qn)
        if t is None:
            continue
        out.extend(_scan_whole_body(t, summaries))
    return out


def _dirties(
    item_kind: str, obj, summaries: Summaries, f: FunctionFacts
) -> Optional[str]:
    """Why this event/call leaves the enclosing critical region half-done
    (or None). Only effects the CALLER owns unwinding count as dirt: its own
    typestate/refcount/critical-field writes, a helper that hands back a
    reference (net incref), and a helper that returns with the transient
    ``suspending`` flag still set. A resolved call that completes its own
    transition internally (swap-out restores the flag on every path) is the
    callee's business — its awaits are checked by the helper-body scan."""
    if item_kind == "event":
        e = obj
        if e.kind == "ref_inc":
            return f"{e.detail}() at line {e.line}"
        if e.kind == "ts" and not e.detail.endswith(("=false", "=none")):
            return f"{e.detail} at line {e.line}"
        if e.kind == "mutate" and e.detail in CRITICAL_FIELDS:
            return f"{e.detail} mutated at line {e.line}"
        return None
    call = obj
    kind, targets = summaries.resolve(call, f)
    if kind not in _RESOLVED_KINDS:
        return None
    for qn in targets:
        s = summaries.by_qualname.get(qn)
        if s is None:
            continue
        if s.net_ref_inc is not None:
            return f"{call.name}() at line {call.line} -> {render_chain(s.net_ref_inc)}"
        if s.leaves_dirty is not None:
            return f"{call.name}() at line {call.line} -> {render_chain(s.leaves_dirty)}"
    return None


def _scan_regions(
    f: FunctionFacts, summaries: Summaries, locked_helpers: Set[str]
) -> List[RawFinding]:
    out: List[RawFinding] = []
    dirty: Dict[Tuple[str, int], str] = {}  # region -> why
    reported: Set[Tuple[str, int]] = set()
    for item_kind, obj in _ordered(f):
        locks = obj.locks
        async_regions = [
            (n, line) for n, is_async, line in locks if is_async and n
        ]
        if item_kind == "call" and async_regions:
            kind, targets = summaries.resolve(obj, f)
            if kind in _RESOLVED_KINDS:
                locked_helpers.update(targets)
        # judge the await against dirt accumulated BEFORE this item: an
        # awaited call that itself dirties only goes dirty once the await
        # completes, so it cannot be its own violation
        is_await = (item_kind == "event" and obj.kind == "await") or (
            item_kind == "call" and obj.awaited
        )
        if is_await:
            for region in async_regions:
                if region not in dirty or region in reported:
                    continue
                if _try_protected(obj.trys):
                    continue
                reported.add(region)
                out.append(
                    (
                        "cancellation-safety",
                        f.path,
                        obj.line,
                        f"await inside `async with {region[0]}` (line "
                        f"{region[1]}) after the region went dirty "
                        f"({dirty[region]}): cancellation here abandons the "
                        "half-done transition — wrap in try/finally that "
                        "restores the invariant",
                    )
                )
        if async_regions:
            why = _dirties(item_kind, obj, summaries, f)
            if why is not None:
                for region in async_regions:
                    dirty.setdefault(region, why)
            elif item_kind == "event" and obj.kind == "ts" and obj.detail.endswith(
                ("=false", "=none")
            ):
                # an explicit restore completes the transition: later awaits
                # in the region are clean again (unless re-dirtied)
                for region in async_regions:
                    dirty.pop(region, None)
    return out


def _scan_whole_body(f: FunctionFacts, summaries: Summaries) -> List[RawFinding]:
    out: List[RawFinding] = []
    dirty_why: Optional[str] = None
    for item_kind, obj in _ordered(f):
        is_await = (item_kind == "event" and obj.kind == "await") or (
            item_kind == "call" and obj.awaited
        )
        if not (is_await and dirty_why is not None):
            why = _dirties(item_kind, obj, summaries, f)
            if why is not None and dirty_why is None:
                dirty_why = why
            elif item_kind == "event" and obj.kind == "ts" and obj.detail.endswith(
                ("=false", "=none")
            ):
                dirty_why = None
            continue
        if _try_protected(obj.trys):
            continue
        out.append(
            (
                "cancellation-safety",
                f.path,
                obj.line,
                f"await in {f.name}() after dirtying state ({dirty_why}); "
                "this helper runs inside a caller's async lock region, so "
                "cancellation here abandons the half-done transition — wrap "
                "in try/finally that restores the invariant",
            )
        )
        break  # one finding per helper is enough signal
    return out


# --------------------------------------------------------------- lane-typestate


def _lane_locked_only(project: Project) -> Set[str]:
    """Greatest fixpoint: functions whose EVERY known call site holds the
    lane lock (lexically or via an earlier trylock in the caller), possibly
    because the caller is itself lane-locked-only. No call sites -> False."""
    callers: Dict[str, List[Tuple[FunctionFacts, CallEvent]]] = {}
    for f in project.functions.values():
        for c in f.calls:
            kind, targets = project.resolve(c, f)
            if kind not in _RESOLVED_KINDS:
                continue
            for qn in targets:
                callers.setdefault(qn, []).append((f, c))
    locked = {qn for qn, sites in callers.items() if sites}
    changed = True
    while changed:
        changed = False
        for qn in list(locked):
            for caller, call in callers.get(qn, []):
                if _site_holds_lane_lock(caller, call):
                    continue
                if caller.qualname in locked and caller.qualname != qn:
                    continue
                locked.discard(qn)
                changed = True
                break
    return locked


def _site_holds_lane_lock(caller: FunctionFacts, call: CallEvent) -> bool:
    if any(_is_lane_lock(n) for n, _a, _l in call.locks):
        return True
    return _earlier_lane_trylock(caller, call.line)


def _earlier_lane_trylock(f: FunctionFacts, line: int) -> bool:
    return any(
        e.kind == "trylock" and e.line <= line for e in f.events
    )


def interp_lane_typestate(
    project: Project, summaries: Summaries
) -> List[RawFinding]:
    out: List[RawFinding] = []
    locked_only = _lane_locked_only(project)
    for f in project.functions.values():
        norm = f.path.replace("\\", "/")
        if "/server/" not in f"/{norm}":
            continue
        ts_events = [e for e in f.events if e.kind == "ts"]
        if not ts_events:
            continue
        has_await_after = lambda line: any(  # noqa: E731
            e.kind == "await" and e.line > line for e in f.events
        ) or any(c.awaited and c.line > line for c in f.calls)
        for e in ts_events:
            field, _, value = e.detail.partition("=")
            # T1: lane lock must be held at every typestate mutation
            if not (
                any(_is_lane_lock(n) for n, _a, _l in e.locks)
                or _earlier_lane_trylock(f, e.line)
                or f.qualname in locked_only
            ):
                legal = ", ".join(
                    f"{s} -> {t}" for s, ts in LANE_TYPESTATE.items() for t in ts
                )
                out.append(
                    (
                        "lane-typestate",
                        f.path,
                        e.line,
                        f"lane typestate field {field!r} mutated in {f.name}() "
                        "without the lane lock held (not lexically, by an "
                        "earlier trylock, or by every caller) — transitions "
                        f"[{legal}] are only atomic under the lane lock",
                    )
                )
            # T2: a swap entry may only be installed while suspending
            if field == "swap" and value not in ("none",):
                if not any(
                    t.kind == "ts"
                    and t.detail == "suspending=true"
                    and t.line <= e.line
                    for t in f.events
                ):
                    out.append(
                        (
                            "lane-typestate",
                            f.path,
                            e.line,
                            f"swap entry installed in {f.name}() without a "
                            "prior `suspending = True` in the same function: "
                            "illegal transition (declared machine: active -> "
                            "suspending -> swapped)",
                        )
                    )
            # T3: suspending=True followed by suspension points needs a
            # cleanup-path reset or the lane wedges in 'suspending' forever
            if e.detail == "suspending=true" and has_await_after(e.line):
                if not any(
                    t.kind == "ts"
                    and t.detail.startswith("suspending=")
                    and t.detail != "suspending=true"
                    and t.cleanup
                    for t in f.events
                ):
                    out.append(
                        (
                            "lane-typestate",
                            f.path,
                            e.line,
                            f"`suspending = True` in {f.name}() with awaits "
                            "after it but no cleanup-path reset "
                            "(finally/except must restore `suspending` or "
                            "the lane wedges mid-transition on error)",
                        )
                    )
    return out


# ---------------------------------------------------------------- the registry

INTERP_RULES = {
    "no-blocking-under-lock": interp_no_blocking_under_lock,
    "no-await-under-thread-lock": interp_no_await_under_thread_lock,
    "paired-refcount": interp_paired_refcount,
    "use-after-donate": interp_use_after_donate,
    "cancellation-safety": interp_cancellation_safety,
    "lane-typestate": interp_lane_typestate,
}

# v1 rules superseded by the interprocedural versions in project mode (the
# interp versions report the lexical cases at the same lines, so in-source
# pragmas keep working; running both would double-report)
REPLACES_V1 = {"no-await-under-thread-lock", "paired-refcount"}

# new rule families (for pragma known-rule validation and --rule choices)
NEW_RULE_NAMES = ("use-after-donate", "cancellation-safety", "lane-typestate")


def run_interp_rules(
    project: Project,
    summaries: Summaries,
    only: Optional[Iterable[str]] = None,
) -> List[RawFinding]:
    names = set(only) if only is not None else set(INTERP_RULES)
    out: List[RawFinding] = []
    for name, fn in INTERP_RULES.items():
        if name in names:
            out.extend(fn(project, summaries))
    return out
