"""swarmlint: static concurrency/tracer analysis + runtime lock sanitizer.

- Static: ``python -m petals_tpu.analysis petals_tpu/`` (see .rules for the
  rule set, .findings for the pragma grammar).
- Runtime: set ``PETALS_TPU_SANITIZE=1`` so the server's locks are built by
  ``sanitizer.make_thread_lock``/``make_async_lock`` wrappers that record
  acquisition order and detect AB/BA cycles and await-under-thread-lock.
"""

from .findings import Finding
from .engine import (
    ALL_RULE_NAMES,
    check_file,
    check_paths,
    check_project,
    check_source,
    check_sources,
    unsuppressed,
)
from .rules import RULES

__all__ = [
    "ALL_RULE_NAMES",
    "Finding",
    "RULES",
    "check_file",
    "check_paths",
    "check_project",
    "check_source",
    "check_sources",
    "unsuppressed",
]
