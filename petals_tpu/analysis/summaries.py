"""Per-function effect summaries + the interprocedural fixpoint.

Each function gets a :class:`Summary` of caller-visible effects, seeded from
its direct facts (callgraph.FunctionFacts) and propagated over the call
graph with a monotone worklist until stable — cycles in the graph simply
converge, no SCC machinery needed because every fact only ever *grows*:

- ``may_block``       — can a call into this function stall the event loop
                        (time.sleep / .result() / device sync), directly or
                        any number of helper calls down. Propagates through
                        resolved edges, and through a dynamic-dispatch
                        fallback edge only when the join is UNANIMOUS (every
                        same-named function in the tree blocks): a dict's
                        ``.get`` must not inherit a blocking ``get`` defined
                        somewhere else, but if every candidate blocks the
                        dispatch cannot save the caller.
- ``mutates_critical``— touches a lane/session invariant field
                        (callgraph.CRITICAL_FIELDS). Resolved edges only
                        (self-method / local / import): the fallback join
                        over common method names would drown the signal.
- ``has_ref_inc`` / ``has_ref_rel`` — page/swap refcount effects; the *net*
                        flavors (inc without rel, rel without inc) are
                        derived AFTER the fixpoint so a balanced helper
                        (takes and releases internally) stays neutral. Both
                        has-sets are monotone; net is not, which is exactly
                        why it is derived, not iterated.
- ``lock_acq`` / ``lock_rel`` — thread-lock names this function can leave
                        acquired/released across its return (manual
                        ``.acquire()`` without ``.release()`` and vice
                        versa, transitively). Net derived post-fixpoint.
- ``donates``         — caller arg positions this function hands to XLA
                        donation (its own jit decorator, a donating callable
                        it forwards a parameter into, or a property
                        returning a donating nested def). Flows UP the
                        graph: a wrapper around a donating step donates.
- ``leaves_dirty``    — returns with the transient ``suspending`` lifecycle
                        flag still set (its last write in source order sets
                        it rather than restoring it): the CALLER owns
                        completing or unwinding the transition, so a later
                        await in the caller is a cancellation hazard. A
                        helper that restores the flag before returning (like
                        a full swap-out) is clean.

Witness chains (``Chain``: tuples of "site" strings) ride along with each
propagated fact so findings can say *how* the effect reaches the flagged
line: ``f() blocks via _helper (batching.py:88) -> time.sleep``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .callgraph import (
    CRITICAL_FIELDS,
    CallEvent,
    DonationSpec,
    FunctionFacts,
    Project,
)

Chain = Tuple[str, ...]

_RESOLVED_KINDS = ("nested", "module", "method", "import")
_MAX_CHAIN = 6


def _site(f: FunctionFacts, line: int) -> str:
    return f"{f.path}:{line}"


@dataclasses.dataclass
class Summary:
    qualname: str
    may_block: Optional[Chain] = None
    mutates_critical: Optional[Chain] = None
    has_ref_inc: Optional[Chain] = None
    has_ref_rel: Optional[Chain] = None
    lock_acq: Dict[str, Chain] = dataclasses.field(default_factory=dict)
    lock_rel: Set[str] = dataclasses.field(default_factory=set)
    donates: Dict[int, Chain] = dataclasses.field(default_factory=dict)
    leaves_dirty: Optional[Chain] = None

    # derived after the fixpoint (non-monotone, so never iterated on)
    @property
    def net_ref_inc(self) -> Optional[Chain]:
        return self.has_ref_inc if self.has_ref_rel is None else None

    @property
    def net_ref_rel(self) -> Optional[Chain]:
        return self.has_ref_rel if self.has_ref_inc is None else None

    @property
    def net_lock_acq(self) -> Dict[str, Chain]:
        return {k: v for k, v in self.lock_acq.items() if k not in self.lock_rel}

    @property
    def net_lock_rel(self) -> Set[str]:
        return self.lock_rel - set(self.lock_acq)


class Summaries:
    def __init__(self, project: Project):
        self.project = project
        self.by_qualname: Dict[str, Summary] = {
            qn: Summary(qualname=qn) for qn in project.functions
        }
        self._resolution: Dict[Tuple[str, int, int], Tuple[str, List[str]]] = {}
        self._seed()
        self._fixpoint()

    def __getitem__(self, qualname: str) -> Summary:
        return self.by_qualname[qualname]

    def resolve(self, call: CallEvent, caller: FunctionFacts):
        """Memoized project.resolve — the fixpoint hits each site many times."""
        key = (caller.qualname, call.line, call.col)
        hit = self._resolution.get(key)
        if hit is None:
            hit = self._resolution[key] = self.project.resolve(call, caller)
        return hit

    # ------------------------------------------------------------------ seed

    def _seed(self) -> None:
        for f in self.project.functions.values():
            s = self.by_qualname[f.qualname]
            for e in f.events:
                if e.kind == "block" and s.may_block is None:
                    s.may_block = (f"{e.detail} at {_site(f, e.line)}",)
                elif e.kind == "ts" or (
                    e.kind == "mutate" and e.detail in CRITICAL_FIELDS
                ):
                    if s.mutates_critical is None:
                        s.mutates_critical = (
                            f"{e.detail} mutated at {_site(f, e.line)}",
                        )
                elif e.kind == "ref_inc" and s.has_ref_inc is None:
                    s.has_ref_inc = (f"{e.detail}() at {_site(f, e.line)}",)
                elif e.kind == "ref_rel" and s.has_ref_rel is None:
                    s.has_ref_rel = (f"{e.detail}() at {_site(f, e.line)}",)
                elif e.kind == "lock_acq":
                    if e.detail in self.project.thread_lock_names:
                        s.lock_acq.setdefault(
                            e.detail, (f"{e.detail}.acquire() at {_site(f, e.line)}",)
                        )
                elif e.kind == "lock_rel":
                    if e.detail in self.project.thread_lock_names:
                        s.lock_rel.add(e.detail)
            ts_writes = sorted(
                (e.line, e.col, e.detail)
                for e in f.events
                if e.kind == "ts" and e.detail.startswith("suspending=")
            )
            if ts_writes and ts_writes[-1][2] in (
                "suspending=true",
                "suspending=value",
            ):
                line = ts_writes[-1][0]
                s.leaves_dirty = (
                    f"returns with suspending set ({_site(f, line)})",
                )
            if f.donation is not None:
                self._seed_own_donation(f)

    def _seed_own_donation(self, f: FunctionFacts) -> None:
        s = self.by_qualname[f.qualname]
        spec = f.donation
        params = list(f.params)
        offset = 1 if params[:1] == ["self"] else 0
        for num in spec.argnums:
            idx = num - offset
            if 0 <= idx:
                s.donates.setdefault(
                    idx, (f"donate_argnums on {f.name} ({_site(f, f.lineno)})",)
                )
        for name in spec.argnames:
            if name in params:
                idx = params.index(name) - offset
                if idx >= 0:
                    s.donates.setdefault(
                        idx, (f"donate_argnames on {f.name} ({_site(f, f.lineno)})",)
                    )

    # ------------------------------------------------------------- fixpoint

    def _fixpoint(self) -> None:
        funcs = list(self.project.functions.values())
        changed = True
        while changed:
            changed = False
            for f in funcs:
                if self._propagate(f):
                    changed = True

    def _chain_via(
        self, caller: FunctionFacts, call: CallEvent, tail: Chain
    ) -> Chain:
        head = f"{call.name}() at {_site(caller, call.line)}"
        return ((head,) + tail)[:_MAX_CHAIN]

    def _propagate(self, f: FunctionFacts) -> bool:
        s = self.by_qualname[f.qualname]
        changed = False
        restores_flag = any(
            e.kind == "ts"
            and e.detail in ("suspending=false", "suspending=none")
            for e in f.events
        )
        for call in f.calls:
            kind, targets = self.resolve(call, f)
            if kind == "none":
                continue
            resolved = kind in _RESOLVED_KINDS
            if (
                not resolved
                and s.may_block is None
                and targets
                and call.kind in ("self", "name")
            ):
                # fallback edge: only for a receiver that genuinely *could*
                # be a project function (an untypeable self-method or bare
                # name — not ``writer.drain()`` matching a project ``drain``
                # by accident), and only on a unanimous join: every
                # same-named function must block before the dispatch does
                blockers = [
                    self.by_qualname[qn].may_block
                    for qn in targets
                    if qn != f.qualname and qn in self.by_qualname
                ]
                if blockers and all(b is not None for b in blockers):
                    s.may_block = self._chain_via(f, call, blockers[0])
                    changed = True
            for qn in targets:
                t = self.by_qualname.get(qn)
                if t is None or qn == f.qualname:
                    continue
                if not resolved:
                    continue
                if s.may_block is None and t.may_block is not None:
                    s.may_block = self._chain_via(f, call, t.may_block)
                    changed = True
                if (
                    s.leaves_dirty is None
                    and t.leaves_dirty is not None
                    and not restores_flag
                ):
                    s.leaves_dirty = self._chain_via(f, call, t.leaves_dirty)
                    changed = True
                if s.mutates_critical is None and t.mutates_critical is not None:
                    s.mutates_critical = self._chain_via(f, call, t.mutates_critical)
                    changed = True
                if s.has_ref_inc is None and t.has_ref_inc is not None:
                    s.has_ref_inc = self._chain_via(f, call, t.has_ref_inc)
                    changed = True
                if s.has_ref_rel is None and t.has_ref_rel is not None:
                    s.has_ref_rel = self._chain_via(f, call, t.has_ref_rel)
                    changed = True
                for lock, chain in t.lock_acq.items():
                    if lock not in s.lock_acq:
                        s.lock_acq[lock] = self._chain_via(f, call, chain)
                        changed = True
                for lock in t.lock_rel:
                    if lock not in s.lock_rel:
                        s.lock_rel.add(lock)
                        changed = True
            # donation flows up: passing own param into a donated position
            donated = self.donated_positions(call, f)
            if donated:
                params = list(f.params)
                for pos, _argname, chain in donated:
                    for i, d in call.args:
                        if i != pos or d is None:
                            continue
                        if d in params:
                            pidx = params.index(d)
                            if d == "self":
                                continue
                            offset = 1 if params[:1] == ["self"] else 0
                            key = pidx - offset
                            if key >= 0 and key not in s.donates:
                                s.donates[key] = self._chain_via(f, call, chain)
                                changed = True
        return changed

    # --------------------------------------------------- donation resolution

    def donated_positions(
        self, call: CallEvent, caller: FunctionFacts
    ) -> List[Tuple[int, Optional[str], Chain]]:
        """Caller-side positional indices whose argument is donated by this
        call: (position, argname-if-known, witness chain). Sources, in
        order: the resolved target's own jit decorator / wrapper summary, a
        property returning a donating nested def, and the module registry of
        names bound to donating jit callables."""
        out: List[Tuple[int, Optional[str], Chain]] = []
        kind, targets = self.resolve(call, caller)
        if kind in _RESOLVED_KINDS:
            for qn in targets:
                t_facts = self.project.functions.get(qn)
                t_sum = self.by_qualname.get(qn)
                if t_facts is None or t_sum is None:
                    continue
                for idx, chain in t_sum.donates.items():
                    out.append((idx, None, chain))
                if t_facts.is_property and t_facts.returns_nested:
                    for nested_qn in t_facts.nested:
                        nf = self.project.functions.get(nested_qn)
                        if (
                            nf is not None
                            and nf.name in t_facts.returns_nested
                            and nf.donation is not None
                        ):
                            out.extend(self._spec_positions(nf, nf.donation))
        if not out:
            spec = self.project.donating_names.get(call.name)
            if spec is not None:
                chain = (f"{call.name} bound to a donating jit callable",)
                for num in spec.argnums:
                    out.append((num, None, chain))
        # dedup by position
        seen: Set[int] = set()
        uniq = []
        for pos, name, chain in out:
            if pos not in seen:
                seen.add(pos)
                uniq.append((pos, name, chain))
        return uniq

    def _spec_positions(
        self, fn: FunctionFacts, spec: DonationSpec
    ) -> List[Tuple[int, Optional[str], Chain]]:
        params = list(fn.params)
        offset = 1 if params[:1] == ["self"] else 0
        chain = (f"donating jit def {fn.name} ({_site(fn, fn.lineno)})",)
        out = []
        for num in spec.argnums:
            idx = num - offset
            if idx >= 0:
                out.append((idx, None, chain))
        for name in spec.argnames:
            if name in params:
                idx = params.index(name) - offset
                if idx >= 0:
                    out.append((idx, name, chain))
        return out


def render_chain(chain: Optional[Chain]) -> str:
    return " -> ".join(chain) if chain else ""
