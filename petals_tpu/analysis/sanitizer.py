"""Runtime concurrency sanitizer (opt-in: ``PETALS_TPU_SANITIZE=1``).

Two detectors, both zero-cost when disabled (the factories hand back a plain
``threading.Lock`` / an unwrapped ``AsyncTryLock``):

1. **Lock-order (AB/BA) cycles.** ``make_thread_lock(name)`` /
   ``make_async_lock(name)`` return wrappers that record, per execution
   context (thread or asyncio task, via contextvars), which locks are held
   when another is acquired. Holding A while acquiring B adds the edge A->B
   to a global graph; an acquisition whose new edge closes a cycle is
   reported with BOTH acquire-site stacks (this side and the recorded
   opposing edge), lockdep-style. Locks sharing one *name* form an
   equivalence class — all lane locks are "lane_lock" — so ordering inside a
   class is intentionally not checked (self-edges are skipped), and
   non-blocking try-acquires (``blocking=False`` / ``acquire_nowait``)
   record no incoming edge, matching lockdep's trylock exemption.

2. **Await while holding a thread lock.** ``SanitizingEventLoopPolicy``
   installs a task factory that wraps every task's coroutine in a trampoline
   calling ``note_suspension()`` after each yield: if the suspending context
   still holds a sanitized ``threading.Lock``, the event loop would stall
   every other task needing it — reported with the holder's acquire stack.

Typical test wiring (see tests/conftest.py)::

    asyncio.set_event_loop_policy(sanitizer.SanitizingEventLoopPolicy())
    sanitizer.get_sanitizer().reset()
    ... run ...
    assert not sanitizer.get_sanitizer().violations()
"""

from __future__ import annotations

import asyncio
import collections.abc
import contextvars
import dataclasses
import itertools
import os
import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple

from petals_tpu.utils.locks import AsyncTryLock
from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_STACK_LIMIT = 12


def enabled() -> bool:
    return os.environ.get("PETALS_TPU_SANITIZE", "").strip().lower() not in (
        "",
        "0",
        "false",
        "off",
    )


# Held locks of the current execution context. contextvars give the right
# scope for both detectors: each thread has its own default context, and each
# asyncio task runs its steps in its own (copied) context. Stored as an
# immutable tuple so one task's update can never leak into another.
_held: contextvars.ContextVar[Tuple["_HeldLock", ...]] = contextvars.ContextVar(
    "petals_tpu_sanitizer_held", default=()
)


_next_seq = itertools.count(1).__next__  # GIL-atomic unique ids for acquires


@dataclasses.dataclass(frozen=True)
class _HeldLock:
    name: str
    kind: str  # "thread" | "async"
    stack: str  # formatted acquire-site stack
    seq: int = 0  # unique per acquire: makes membership tests identity-like


@dataclasses.dataclass(frozen=True)
class _Edge:
    src: str
    dst: str
    src_stack: str  # where src was holding
    dst_stack: str  # where dst was acquired under src


def _capture_stack() -> str:
    return "".join(traceback.format_stack(limit=_STACK_LIMIT)[:-2])


class LockOrderSanitizer:
    """Global acquisition-order graph + violation log (thread-safe)."""

    def __init__(self) -> None:
        self._mu = threading.Lock()  # guards graph/violations, never sanitized
        self._edges: Dict[str, Dict[str, _Edge]] = {}
        self._violations: List[str] = []
        self._reported: set = set()
        # seqs of entries released from a context other than their acquirer's
        # (legal for threading.Lock); the acquirer's held-tuple is pruned of
        # them lazily, since its contextvar can't be written from here
        self._released_elsewhere: Set[int] = set()

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self._violations.clear()
            self._reported.clear()
            self._released_elsewhere.clear()

    def violations(self) -> List[str]:
        with self._mu:
            return list(self._violations)

    # ------------------------------------------------------------- recording

    def _prune_held(self) -> Tuple[_HeldLock, ...]:
        """Current context's held locks, minus entries whose lock was since
        released from another context (acquire on loop thread, release in an
        executor) — those would otherwise read as held-forever here."""
        held = _held.get()
        if held:
            with self._mu:
                if self._released_elsewhere:
                    live = tuple(
                        h for h in held if h.seq not in self._released_elsewhere
                    )
                    if len(live) != len(held):
                        self._released_elsewhere.difference_update(
                            h.seq for h in held
                        )
                        _held.set(live)
                        return live
        return held

    def note_acquire(self, name: str, kind: str, *, ordered: bool = True) -> _HeldLock:
        """Register a successful acquire in the current context; when
        ``ordered`` (a blocking acquire), add order edges from held locks."""
        stack = _capture_stack()
        entry = _HeldLock(name=name, kind=kind, stack=stack, seq=_next_seq())
        held = self._prune_held()
        if ordered:
            for h in held:
                if h.name != name:  # same name = equivalence class (lane locks)
                    self._add_edge(_Edge(h.name, name, h.stack, stack))
        _held.set(held + (entry,))
        return entry

    def note_release(self, entry: _HeldLock) -> None:
        held = self._prune_held()
        if entry in held:
            idx = len(held) - 1 - held[::-1].index(entry)
            _held.set(held[:idx] + held[idx + 1 :])
        else:
            # released from a different context than it was acquired in; mark
            # the seq so the acquirer's held-tuple is pruned at its next
            # note_acquire/note_suspension instead of reading held-forever
            with self._mu:
                self._released_elsewhere.add(entry.seq)

    def note_suspension(self) -> None:
        """Called by the task trampoline at every coroutine yield."""
        for h in self._prune_held():
            if h.kind != "thread":
                continue
            key = ("await-under-thread-lock", h.name)
            with self._mu:
                if key in self._reported:
                    continue
                self._reported.add(key)
                self._violations.append(
                    f"await while holding thread lock {h.name!r}: the event "
                    "loop cannot release it at the suspension point, so every "
                    "other user of the lock stalls.\n"
                    f"--- lock acquired at ---\n{h.stack}"
                    f"--- suspended at ---\n{_capture_stack()}"
                )

    # ------------------------------------------------------------ edge graph

    def _add_edge(self, edge: _Edge) -> None:
        with self._mu:
            dsts = self._edges.setdefault(edge.src, {})
            if edge.dst in dsts:
                return  # keep the first-seen stacks for this edge
            path = self._find_path(edge.dst, edge.src)
            dsts[edge.dst] = edge
            if path is None:
                return
            key = ("lock-order",) + tuple(sorted((edge.src, edge.dst)))
            if key in self._reported:
                return
            self._reported.add(key)
            lines = [
                f"lock-order cycle: acquiring {edge.dst!r} while holding "
                f"{edge.src!r}, but the opposite order "
                f"({' -> '.join([edge.dst] + [e.dst for e in path])}) was also "
                "observed — two contexts interleaving here deadlock.",
                f"--- this side: {edge.src!r} held at ---\n{edge.src_stack}",
                f"--- this side: {edge.dst!r} acquired at ---\n{edge.dst_stack}",
            ]
            for e in path:
                lines.append(
                    f"--- opposing edge {e.src!r} -> {e.dst!r}: {e.src!r} held at ---\n"
                    f"{e.src_stack}"
                    f"--- opposing edge: {e.dst!r} acquired at ---\n{e.dst_stack}"
                )
            self._violations.append("\n".join(lines))

    def _find_path(self, src: str, dst: str) -> Optional[List[_Edge]]:
        """Edge path src -> ... -> dst in the current graph (caller holds _mu)."""
        seen = {src}
        stack: List[Tuple[str, List[_Edge]]] = [(src, [])]
        while stack:
            node, path = stack.pop()
            for nxt, edge in self._edges.get(node, {}).items():
                if nxt == dst:
                    return path + [edge]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [edge]))
        return None


_SANITIZER = LockOrderSanitizer()


def get_sanitizer() -> LockOrderSanitizer:
    return _SANITIZER


# ------------------------------------------------------------ lock wrappers


class SanitizedThreadLock:
    """threading.Lock wrapper feeding the sanitizer. Non-reentrant."""

    def __init__(self, name: str):
        self._name = name
        self._lock = threading.Lock()
        # single holder at a time (non-reentrant); one slot means a release
        # from a thread other than the acquirer's (legal for threading.Lock)
        # still clears the right entry
        self._entry: Optional[_HeldLock] = None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            # timed/non-blocking acquires are trylocks: no incoming edges
            ordered = blocking and timeout == -1
            self._entry = _SANITIZER.note_acquire(
                self._name, "thread", ordered=ordered
            )
        return ok

    def release(self) -> None:
        entry, self._entry = self._entry, None
        self._lock.release()
        if entry is not None:
            _SANITIZER.note_release(entry)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "SanitizedThreadLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class SanitizedAsyncLock:
    """AsyncTryLock wrapper feeding the sanitizer."""

    def __init__(self, name: str):
        self._name = name
        self._lock = AsyncTryLock()
        self._entry: Optional[_HeldLock] = None  # single holder at a time

    async def acquire(self) -> bool:
        await self._lock.acquire()
        self._entry = _SANITIZER.note_acquire(self._name, "async")
        return True

    def acquire_nowait(self) -> bool:
        """Try-acquire without suspending (records no order edge). The inner
        AsyncTryLock refuses when held OR when a woken waiter is pending, so
        this can never co-own the lock with a blocking acquirer."""
        if not self._lock.acquire_nowait():
            return False
        self._entry = _SANITIZER.note_acquire(self._name, "async", ordered=False)
        return True

    def release(self) -> None:
        entry, self._entry = self._entry, None
        self._lock.release()
        if entry is not None:
            _SANITIZER.note_release(entry)

    def locked(self) -> bool:
        return self._lock.locked()

    async def __aenter__(self) -> "SanitizedAsyncLock":
        await self.acquire()
        return self

    async def __aexit__(self, *exc) -> None:
        self.release()


def make_thread_lock(name: str):
    """A threading.Lock, sanitized when PETALS_TPU_SANITIZE is set."""
    return SanitizedThreadLock(name) if enabled() else threading.Lock()


def make_async_lock(name: str):
    """An AsyncTryLock (asyncio.Lock-compatible, safely try-lockable),
    sanitizer-wrapped when PETALS_TPU_SANITIZE is set."""
    return SanitizedAsyncLock(name) if enabled() else AsyncTryLock()


def lock_try_acquire_nowait(lock) -> bool:
    """Uniform non-blocking try-acquire for the locks ``make_async_lock``
    hands out (AsyncTryLock / SanitizedAsyncLock).

    Callers must be on the event loop with no await between their own
    ``locked()`` reasoning and this call (the check-and-take is atomic
    there). Sanitized locks record no lock-order edge for the trylock.

    A plain ``asyncio.Lock`` is rejected outright: its ``release()`` hands
    ownership to a woken waiter while ``locked()`` still reads False, so no
    outside trylock can be made safe without relying on CPython internals.
    """
    nowait = getattr(lock, "acquire_nowait", None)
    if nowait is None:
        raise TypeError(
            "lock_try_acquire_nowait needs an acquire_nowait()-capable lock "
            f"(AsyncTryLock / SanitizedAsyncLock), got {type(lock).__name__}"
        )
    return bool(nowait())


# --------------------------------------------------------- task trampoline


class _CoroShim:
    """Delegating coroutine wrapper: notifies the sanitizer at every yield
    (i.e. every point the wrapped task actually suspends)."""

    def __init__(self, coro):
        self._coro = coro
        # instance attrs (a class-level __qualname__ property is illegal):
        # keep asyncio's task reprs and debug helpers readable
        self.__name__ = getattr(coro, "__name__", "coro")
        self.__qualname__ = getattr(coro, "__qualname__", "coro")

    def send(self, value):
        result = self._coro.send(value)
        _SANITIZER.note_suspension()
        return result

    def throw(self, *exc_info):
        result = self._coro.throw(*exc_info)
        _SANITIZER.note_suspension()
        return result

    def close(self):
        return self._coro.close()

    def __iter__(self):
        return self

    def __next__(self):
        return self.send(None)

    def __await__(self):
        return self

    # keep asyncio/task reprs and debug helpers working
    @property
    def cr_code(self):
        return getattr(self._coro, "cr_code", None)

    @property
    def cr_frame(self):
        return getattr(self._coro, "cr_frame", None)

    @property
    def cr_running(self):
        return getattr(self._coro, "cr_running", False)

    @property
    def cr_await(self):
        return getattr(self._coro, "cr_await", None)


collections.abc.Coroutine.register(_CoroShim)


def _sanitizing_task_factory(loop, coro, **kwargs):
    if asyncio.iscoroutine(coro) and not isinstance(coro, _CoroShim):
        coro = _CoroShim(coro)
    return asyncio.Task(coro, loop=loop, **kwargs)


class SanitizingEventLoopPolicy(asyncio.DefaultEventLoopPolicy):
    """Event-loop policy whose loops wrap every task for the sanitizer."""

    def new_event_loop(self):
        loop = super().new_event_loop()
        loop.set_task_factory(_sanitizing_task_factory)
        return loop


__all__ = [
    "AsyncTryLock",
    "LockOrderSanitizer",
    "SanitizedAsyncLock",
    "SanitizedThreadLock",
    "SanitizingEventLoopPolicy",
    "enabled",
    "get_sanitizer",
    "lock_try_acquire_nowait",
    "make_async_lock",
    "make_thread_lock",
]
