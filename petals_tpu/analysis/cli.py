"""swarmlint CLI: ``python -m petals_tpu.analysis petals_tpu/``.

v2 runs the interprocedural engine (call graph + effect summaries) over the
whole tree by default; ``--no-interp`` falls back to the per-function rules.

Exit status: 0 when every finding is suppressed (reasoned pragma) or already
in the committed baseline; 1 on new unsuppressed findings; 2 on operational
failure (unreadable baseline, ``--max-seconds`` exceeded).

Machine-readable output: ``--json`` (one object per finding, with the
baseline fingerprint) and ``--sarif`` (SARIF 2.1.0 for code-scanning UIs).
The committed-baseline gate (``--baseline BASELINE_SWARMLINT.json``) fails
only on findings whose fingerprint count exceeds the baseline's, so CI
flags *new* debt while the recorded kind is burned down incrementally;
``--update-baseline`` rewrites the file from the current tree.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Dict, List, Optional

from .engine import (
    ALL_RULE_NAMES,
    check_paths,
    check_project,
    fingerprint,
    unsuppressed,
)
from .findings import Finding

BASELINE_VERSION = 1


def _load_baseline(path: str) -> Dict[str, int]:
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or data.get("version") != BASELINE_VERSION:
        raise ValueError(f"unsupported baseline format in {path}")
    counts = data.get("counts", {})
    return {str(k): int(v) for k, v in counts.items()}


def _write_baseline(path: str, failures: List[Finding]) -> None:
    counts: Dict[str, int] = {}
    for f in failures:
        fp = fingerprint(f)
        counts[fp] = counts.get(fp, 0) + 1
    payload = {"version": BASELINE_VERSION, "counts": dict(sorted(counts.items()))}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")


def _apply_baseline(
    failures: List[Finding], baseline: Dict[str, int]
) -> List[Finding]:
    """Findings that are NEW relative to the baseline: per fingerprint, only
    occurrences beyond the recorded count fail the build."""
    remaining = dict(baseline)
    new: List[Finding] = []
    for f in failures:
        fp = fingerprint(f)
        if remaining.get(fp, 0) > 0:
            remaining[fp] -= 1
        else:
            new.append(f)
    return new


def _findings_json(findings: List[Finding]) -> List[dict]:
    return [
        {
            "rule": f.rule,
            "path": f.path,
            "line": f.line,
            "message": f.message,
            "suppressed": f.suppressed,
            "suppress_reason": f.suppress_reason,
            "fingerprint": fingerprint(f),
        }
        for f in findings
    ]


def _sarif(findings: List[Finding]) -> dict:
    rules = sorted({f.rule for f in findings} | set(ALL_RULE_NAMES))
    return {
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "swarmlint",
                        "informationUri": "https://github.com/bigscience-workshop/petals",
                        "rules": [{"id": r} for r in rules],
                    }
                },
                "results": [
                    {
                        "ruleId": f.rule,
                        "level": "note" if f.suppressed else "error",
                        "message": {"text": f.message},
                        "suppressions": (
                            [{"kind": "inSource", "justification": f.suppress_reason}]
                            if f.suppressed
                            else []
                        ),
                        "partialFingerprints": {"swarmlint/v1": fingerprint(f)},
                        "locations": [
                            {
                                "physicalLocation": {
                                    "artifactLocation": {"uri": f.path},
                                    "region": {"startLine": max(f.line, 1)},
                                }
                            }
                        ],
                    }
                    for f in findings
                ],
            }
        ],
    }


def _dump(path: str, payload: object) -> None:
    text = json.dumps(payload, indent=2, sort_keys=False) + "\n"
    if path == "-":
        sys.stdout.write(text)
    else:
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(text)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m petals_tpu.analysis",
        description="swarmlint: concurrency + tracer-safety invariants for petals_tpu",
    )
    parser.add_argument("paths", nargs="+", help="files or directories to check")
    parser.add_argument(
        "--rule",
        action="append",
        choices=sorted(ALL_RULE_NAMES),
        help="run only these rules (repeatable); default: all",
    )
    parser.add_argument(
        "--show-suppressed",
        action="store_true",
        help="also print findings silenced by pragmas (with their reasons)",
    )
    parser.add_argument(
        "--no-interp",
        action="store_true",
        help="per-function v1 rules only (skip call graph + summaries)",
    )
    parser.add_argument(
        "--jobs",
        type=int,
        default=1,
        metavar="N",
        help="parallel fact-extraction workers (0 = one per core)",
    )
    parser.add_argument(
        "--json",
        metavar="PATH",
        help="write findings as JSON to PATH ('-' for stdout)",
    )
    parser.add_argument(
        "--sarif",
        metavar="PATH",
        help="write findings as SARIF 2.1.0 to PATH ('-' for stdout)",
    )
    parser.add_argument(
        "--baseline",
        metavar="PATH",
        help="committed baseline: fail only on findings not already recorded",
    )
    parser.add_argument(
        "--update-baseline",
        action="store_true",
        help="rewrite --baseline from the current tree and exit 0",
    )
    parser.add_argument(
        "--max-seconds",
        type=float,
        default=None,
        metavar="S",
        help="exit 2 if the whole run takes longer than S seconds (CI budget)",
    )
    args = parser.parse_args(argv)
    if args.update_baseline and not args.baseline:
        parser.error("--update-baseline requires --baseline PATH")

    start = time.monotonic()
    if args.no_interp:
        findings = check_paths(args.paths, rules=args.rule)
    else:
        findings = check_project(
            args.paths, rules=args.rule, jobs=args.jobs, interp=True
        )
    failures = unsuppressed(findings)

    if args.json:
        _dump(args.json, _findings_json(findings))
    if args.sarif:
        _dump(args.sarif, _sarif(findings))

    if args.baseline and args.update_baseline:
        _write_baseline(args.baseline, failures)
        print(
            f"swarmlint: baseline {args.baseline} updated "
            f"({len(failures)} finding(s) recorded)",
            file=sys.stderr,
        )
        return 0

    gated = failures
    if args.baseline:
        try:
            baseline = _load_baseline(args.baseline)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"swarmlint: cannot read baseline: {e}", file=sys.stderr)
            return 2
        gated = _apply_baseline(failures, baseline)

    shown = findings if args.show_suppressed else gated
    for f in shown:
        print(f.format())
    n_sup = len(findings) - len(failures)
    n_baselined = len(failures) - len(gated)
    extra = f", {n_baselined} baselined" if args.baseline else ""
    elapsed = time.monotonic() - start
    print(
        f"swarmlint: {len(gated)} finding(s), {n_sup} suppressed{extra} "
        f"({len(ALL_RULE_NAMES)} rules, {elapsed:.1f}s)",
        file=sys.stderr,
    )
    if args.max_seconds is not None and elapsed > args.max_seconds:
        print(
            f"swarmlint: run took {elapsed:.1f}s > --max-seconds "
            f"{args.max_seconds:.0f} budget",
            file=sys.stderr,
        )
        return 2
    return 1 if gated else 0


if __name__ == "__main__":
    sys.exit(main())
