"""swarmlint CLI: ``python -m petals_tpu.analysis petals_tpu/``.

Exit status 0 iff every finding is suppressed (with a reasoned pragma).
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from .engine import check_paths, unsuppressed
from .rules import RULES


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m petals_tpu.analysis",
        description="swarmlint: concurrency + tracer-safety invariants for petals_tpu",
    )
    parser.add_argument("paths", nargs="+", help="files or directories to check")
    parser.add_argument(
        "--rule",
        action="append",
        choices=sorted(RULES),
        help="run only these rules (repeatable); default: all",
    )
    parser.add_argument(
        "--show-suppressed",
        action="store_true",
        help="also print findings silenced by pragmas (with their reasons)",
    )
    args = parser.parse_args(argv)

    findings = check_paths(args.paths, rules=args.rule)
    failures = unsuppressed(findings)
    shown = findings if args.show_suppressed else failures
    for f in shown:
        print(f.format())
    n_sup = len(findings) - len(failures)
    print(
        f"swarmlint: {len(failures)} finding(s), {n_sup} suppressed "
        f"({len(list(RULES))} rules)",
        file=sys.stderr,
    )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
