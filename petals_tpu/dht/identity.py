"""Cryptographic peer identities for the swarm plane.

The reference inherits this from libp2p (peer ids derived from keypairs) and
hivemind's RSASignatureValidator (signed per-peer DHT subkey records,
src/petals/cli/run_dht.py + hivemind dht/validation.py behavior). This build
implements the same guarantees on Ed25519:

- a PeerID is the SHA-256 of the node's Ed25519 public key — you cannot claim
  an id you don't hold the private key for;
- RPC hellos are challenge/response: each side signs the other's nonce, so a
  connection's remote_peer_id is only set when PROVEN;
- per-peer DHT announcements (subkey records) are signed over a canonical
  form of (uid, subkey, payload, expiration); storers and readers both verify
  and reject records whose subkey doesn't match the verified writer.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Optional

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
except ModuleNotFoundError:  # hosts without `cryptography`: RFC 8032 in Python
    from petals_tpu.dht._ed25519_fallback import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
        InvalidSignature,
    )

from petals_tpu.data_structures import PeerID

_HELLO_CONTEXT = b"ptu-hello-v1|"
_ANNOUNCE_CONTEXT = b"ptu-announce-v1|"


class Identity:
    """An Ed25519 keypair whose public-key hash IS the peer id."""

    __slots__ = ("_private", "_public_bytes", "_peer_id")

    def __init__(self, private: Ed25519PrivateKey):
        self._private = private
        self._public_bytes = private.public_key().public_bytes_raw()
        self._peer_id = peer_id_of(self._public_bytes)

    @classmethod
    def generate(cls) -> "Identity":
        return cls(Ed25519PrivateKey.generate())

    @classmethod
    def from_seed(cls, seed: bytes) -> "Identity":
        """Deterministic identity (test swarms with stable multiaddrs,
        reference tests/bootstrap.id pattern)."""
        return cls(Ed25519PrivateKey.from_private_bytes(hashlib.sha256(seed).digest()))

    @property
    def peer_id(self) -> PeerID:
        return self._peer_id

    @property
    def public_bytes(self) -> bytes:
        return self._public_bytes

    def sign(self, message: bytes) -> bytes:
        return self._private.sign(message)


def peer_id_of(public_bytes: bytes) -> PeerID:
    return PeerID(hashlib.sha256(public_bytes).digest())


def verify(public_bytes: bytes, signature: bytes, message: bytes) -> bool:
    try:
        Ed25519PublicKey.from_public_bytes(public_bytes).verify(signature, message)
        return True
    except (InvalidSignature, ValueError, TypeError):
        return False


# ------------------------------------------------------------------ hello auth


def hello_challenge_message(
    signer_public: bytes, peer_public: bytes, peer_nonce: bytes
) -> bytes:
    """What a node signs to prove its identity to ``peer``: its OWN public key
    bound together with the peer's key and nonce. Binding the signer's key is
    what stops a man-in-the-middle from relaying an honest peer's proof as its
    own (the relayed signature never verifies against the attacker's key)."""
    return _HELLO_CONTEXT + signer_public + b"|" + peer_public + peer_nonce


# ------------------------------------------------------------------ announcements


def announce_message(uid: str, subkey: str, payload: Any, expiration: float) -> bytes:
    """Canonical signing form of one DHT announcement. Uses sorted-key JSON of
    msgpack-safe plain types so writer and verifier serialize identically."""
    body = json.dumps(
        {"uid": uid, "subkey": subkey, "payload": payload, "exp": round(float(expiration), 3)},
        sort_keys=True,
        separators=(",", ":"),
        ensure_ascii=True,
    )
    return _ANNOUNCE_CONTEXT + body.encode()


def sign_announcement(
    identity: Identity, uid: str, payload: Any, expiration: float
) -> dict:
    """Wrap ``payload`` in a signed record for subkey = our peer id."""
    subkey = identity.peer_id.to_string()
    message = announce_message(uid, subkey, payload, expiration)
    return {
        "uid": uid,
        "payload": payload,
        "pub": identity.public_bytes.hex(),
        "sig": identity.sign(message).hex(),
    }


def verify_announcement(value: Any, subkey: Optional[str], expiration: float) -> bool:
    """True iff ``value`` is a well-formed signed record whose signature is
    valid AND whose signer's key hashes to ``subkey`` — nobody can overwrite
    another peer's announcements (the attack ADVICE.md flags)."""
    if not isinstance(value, dict) or subkey is None:
        return False
    try:
        public_bytes = bytes.fromhex(value["pub"])
        signature = bytes.fromhex(value["sig"])
        uid = value["uid"]
        payload = value["payload"]
    except (KeyError, TypeError, ValueError):
        return False
    if peer_id_of(public_bytes).to_string() != subkey:
        return False
    message = announce_message(uid, subkey, payload, expiration)
    return verify(public_bytes, signature, message)
