"""Pure-Python Ed25519 (RFC 8032) drop-in for the `cryptography` package.

Loaded by dht/identity.py only when `cryptography` is not installed: the
swarm's identity plane (peer-id derivation, hello challenge/response, signed
DHT announcements) keeps its real signature semantics instead of the whole
server plane failing at import. Wire-compatible with the C implementation —
same seeds produce the same keys and signatures — so mixed swarms interop.

Python-bigint group ops cost a few ms per sign/verify; identities sign a
handful of hellos and announcements per session, so this is plenty for dev
and test hosts. Production swarms should install `cryptography`.
"""

from __future__ import annotations

import hashlib
import os

_P = 2**255 - 19
_L = 2**252 + 27742317777372353535851937790883648493
_D = (-121665 * pow(121666, _P - 2, _P)) % _P
_SQRT_M1 = pow(2, (_P - 1) // 4, _P)

# base point B (extended homogeneous coordinates x, y, z, t)
_BY = 4 * pow(5, _P - 2, _P) % _P
_BX = 15112221349535400772501151409588531511454012693041857206046113283949847762202
_B = (_BX, _BY, 1, _BX * _BY % _P)
_IDENT = (0, 1, 1, 0)


class InvalidSignature(Exception):
    pass


def _add(p, q):
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % _P
    b = (y1 + x1) * (y2 + x2) % _P
    c = 2 * t1 * t2 * _D % _P
    d = 2 * z1 * z2 % _P
    e, f, g, h = b - a, d - c, d + c, b + a
    return e * f % _P, g * h % _P, f * g % _P, e * h % _P


def _mul(s, p):
    q = _IDENT
    while s:
        if s & 1:
            q = _add(q, p)
        p = _add(p, p)
        s >>= 1
    return q


def _compress(p):
    x, y, z, _ = p
    zi = pow(z, _P - 2, _P)
    x, y = x * zi % _P, y * zi % _P
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def _decompress(s: bytes):
    if len(s) != 32:
        return None
    y = int.from_bytes(s, "little")
    sign = y >> 255
    y &= (1 << 255) - 1
    if y >= _P:
        return None
    # recover x from the curve equation: x^2 = (y^2 - 1) / (d y^2 + 1)
    y2 = y * y % _P
    u, v = (y2 - 1) % _P, (_D * y2 + 1) % _P
    x = u * pow(v, _P - 2, _P) % _P
    x = pow(x, (_P + 3) // 8, _P)
    if x * x % _P != u * pow(v, _P - 2, _P) % _P:
        x = x * _SQRT_M1 % _P
    if x * x % _P != u * pow(v, _P - 2, _P) % _P:
        return None
    if x == 0 and sign:
        return None
    if x & 1 != sign:
        x = _P - x
    return (x, y, 1, x * y % _P)


def _points_equal(p, q):
    x1, y1, z1, _ = p
    x2, y2, z2, _ = q
    return (x1 * z2 - x2 * z1) % _P == 0 and (y1 * z2 - y2 * z1) % _P == 0


def _scalars(seed: bytes):
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


class Ed25519PublicKey:
    __slots__ = ("_raw",)

    def __init__(self, raw: bytes):
        self._raw = bytes(raw)

    @classmethod
    def from_public_bytes(cls, data: bytes) -> "Ed25519PublicKey":
        if len(data) != 32 or _decompress(data) is None:
            raise ValueError("invalid Ed25519 public key")
        return cls(data)

    def public_bytes_raw(self) -> bytes:
        return self._raw

    def verify(self, signature: bytes, data: bytes) -> None:
        if len(signature) != 64:
            raise InvalidSignature
        a = _decompress(self._raw)
        r = _decompress(signature[:32])
        s = int.from_bytes(signature[32:], "little")
        if a is None or r is None or s >= _L:
            raise InvalidSignature
        k = int.from_bytes(
            hashlib.sha512(signature[:32] + self._raw + data).digest(), "little"
        ) % _L
        if not _points_equal(_mul(s, _B), _add(r, _mul(k, a))):
            raise InvalidSignature


class Ed25519PrivateKey:
    __slots__ = ("_seed", "_a", "_prefix", "_public")

    def __init__(self, seed: bytes):
        self._seed = bytes(seed)
        self._a, self._prefix = _scalars(self._seed)
        self._public = _compress(_mul(self._a, _B))

    @classmethod
    def generate(cls) -> "Ed25519PrivateKey":
        return cls(os.urandom(32))

    @classmethod
    def from_private_bytes(cls, data: bytes) -> "Ed25519PrivateKey":
        if len(data) != 32:
            raise ValueError("Ed25519 private keys are 32 bytes")
        return cls(data)

    def public_key(self) -> Ed25519PublicKey:
        return Ed25519PublicKey(self._public)

    def sign(self, data: bytes) -> bytes:
        r = int.from_bytes(hashlib.sha512(self._prefix + data).digest(), "little") % _L
        enc_r = _compress(_mul(r, _B))
        k = int.from_bytes(
            hashlib.sha512(enc_r + self._public + data).digest(), "little"
        ) % _L
        s = (r + k * self._a) % _L
        return enc_r + int.to_bytes(s, 32, "little")
