"""Kademlia routing table: XOR metric over 256-bit peer ids, k-buckets.

This build replaces hivemind's libp2p/Go-daemon DHT (reference SURVEY.md §2.3,
L0) with an in-framework Kademlia over the asyncio RPC transport. The directory
semantics the reference builds on top (store_many with subkeys + expirations,
reference src/petals/utils/dht.py:28-131) are implemented in dht/storage.py and
dht/node.py.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional

from petals_tpu.data_structures import PeerID

KEY_BITS = 256
DEFAULT_BUCKET_SIZE = 20


@dataclasses.dataclass(frozen=True)
class PeerAddr:
    """Contact info of a DHT peer. Textual form: host:port/peer_id_hex
    (the framework's "multiaddr"). ``relayed=True`` means host:port is a
    RELAY (rpc/relay.py) through which the peer must be dialed — the analogue
    of the reference's libp2p relay circuit addresses; textual form
    relay+host:port/peer_id_hex."""

    host: str
    port: int
    peer_id: PeerID
    relayed: bool = False

    def to_string(self) -> str:
        prefix = "relay+" if self.relayed else ""
        return f"{prefix}{self.host}:{self.port}/{self.peer_id.to_string()}"

    @classmethod
    def from_string(cls, s: str) -> "PeerAddr":
        relayed = s.startswith("relay+")
        if relayed:
            s = s[len("relay+"):]
        hostport, peer_hex = s.rsplit("/", 1)
        host, port = hostport.rsplit(":", 1)
        return cls(host=host, port=int(port), peer_id=PeerID.from_string(peer_hex), relayed=relayed)

    def to_wire(self) -> list:
        wire = [self.host, self.port, self.peer_id.to_string()]
        if self.relayed:
            wire.append(True)  # omitted when direct: wire compat with old peers
        return wire

    @classmethod
    def from_wire(cls, obj) -> "PeerAddr":
        return cls(
            host=obj[0], port=int(obj[1]), peer_id=PeerID.from_string(obj[2]),
            relayed=bool(obj[3]) if len(obj) > 3 else False,
        )


def xor_distance(a: PeerID, b: PeerID) -> int:
    return int.from_bytes(a.to_bytes(), "big") ^ int.from_bytes(b.to_bytes(), "big")


def bucket_index(own: PeerID, other: PeerID) -> int:
    """Index = position of the highest differing bit (0 if ids are equal)."""
    dist = xor_distance(own, other)
    return dist.bit_length() - 1 if dist > 0 else 0


@dataclasses.dataclass
class _Contact:
    addr: PeerAddr
    last_seen: float


class RoutingTable:
    def __init__(self, own_id: PeerID, bucket_size: int = DEFAULT_BUCKET_SIZE):
        self.own_id = own_id
        self.bucket_size = bucket_size
        self._buckets: Dict[int, Dict[PeerID, _Contact]] = {}

    def add(self, addr: PeerAddr) -> None:
        if addr.peer_id == self.own_id:
            return
        idx = bucket_index(self.own_id, addr.peer_id)
        bucket = self._buckets.setdefault(idx, {})
        if addr.peer_id in bucket or len(bucket) < self.bucket_size:
            bucket[addr.peer_id] = _Contact(addr, time.monotonic())
        else:
            # Full bucket: replace the stalest contact (simplified eviction;
            # classic Kademlia pings it first — failures also evict via remove()).
            stalest = min(bucket, key=lambda pid: bucket[pid].last_seen)
            del bucket[stalest]
            bucket[addr.peer_id] = _Contact(addr, time.monotonic())

    def remove(self, peer_id: PeerID) -> None:
        idx = bucket_index(self.own_id, peer_id)
        self._buckets.get(idx, {}).pop(peer_id, None)

    def get(self, peer_id: PeerID) -> Optional[PeerAddr]:
        idx = bucket_index(self.own_id, peer_id)
        contact = self._buckets.get(idx, {}).get(peer_id)
        return contact.addr if contact else None

    def nearest(self, target: PeerID, k: int) -> List[PeerAddr]:
        contacts = [c.addr for bucket in self._buckets.values() for c in bucket.values()]
        contacts.sort(key=lambda a: xor_distance(a.peer_id, target))
        return contacts[:k]

    def __len__(self) -> int:
        return sum(len(b) for b in self._buckets.values())

    def all_peers(self) -> List[PeerAddr]:
        return [c.addr for bucket in self._buckets.values() for c in bucket.values()]
