from petals_tpu.dht.node import DHTNode
from petals_tpu.dht.routing import PeerAddr

__all__ = ["DHTNode", "PeerAddr"]
