"""Kademlia DHT node over the framework's asyncio RPC transport.

Replaces hivemind's DHT/DHTNode + Go p2pd daemon (reference L0, SURVEY.md §2.3)
with an in-framework implementation providing the API surface the directory
layer needs:

- ``store(key, value, expiration_time, subkey=None)`` — replicated to the K
  peers nearest to sha256(key); per-subkey merge with per-record expirations
  (what reference utils/dht.py:65-71 relies on for per-peer announcements).
- ``get(key)`` — local + iterative find_value; returns (value, expiration).
- ``client_mode=True`` — query-only node that runs no listener (reference's
  DHT client mode for NAT'd peers, server.py:137-150).

One ``RpcServer`` can be shared with other services (a model server registers
its transformer RPCs on the same listener).
"""

from __future__ import annotations

import asyncio
import hashlib
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from petals_tpu.data_structures import PeerID
from petals_tpu.dht.routing import DEFAULT_BUCKET_SIZE, PeerAddr, RoutingTable, xor_distance
from petals_tpu.dht.storage import DHTStorage, SubkeyDict
from petals_tpu.rpc.pool import ConnectionPool
from petals_tpu.rpc.server import RpcContext, RpcServer
from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)

DHTKey = Union[str, bytes]


def dht_time() -> float:
    """Wall-clock used for expirations (hivemind get_dht_time analogue)."""
    return time.time()


def key_id(key: DHTKey) -> bytes:
    if isinstance(key, str):
        key = key.encode()
    return hashlib.sha256(key).digest()


class DHTNode:
    def __init__(self):
        raise RuntimeError("Use `await DHTNode.create(...)`")

    @classmethod
    async def create(
        cls,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        initial_peers: Sequence[Union[str, PeerAddr]] = (),
        identity=None,  # dht.identity.Identity (keypair); peer id = hash(pubkey)
        identity_seed: Optional[bytes] = None,
        client_mode: bool = False,
        bucket_size: int = DEFAULT_BUCKET_SIZE,
        replication: int = 5,
        alpha: int = 3,
        rpc_server: Optional[RpcServer] = None,
        request_timeout: float = 5.0,
        maintenance_period: float = 30.0,
    ) -> "DHTNode":
        from petals_tpu.dht.identity import Identity

        self = object.__new__(cls)
        if identity is None:
            identity = Identity.from_seed(identity_seed) if identity_seed else Identity.generate()
        self.identity = identity
        peer_id = identity.peer_id
        self.peer_id = peer_id
        self.client_mode = client_mode
        self.replication = replication
        self.alpha = alpha
        self.request_timeout = request_timeout
        self.table = RoutingTable(peer_id, bucket_size)
        self.storage = DHTStorage()
        self.pool = ConnectionPool(identity=identity)
        self._owns_server = rpc_server is None and not client_mode
        self._maintenance_task: Optional[asyncio.Task] = None

        if client_mode:
            self.server = None
        else:
            self.server = rpc_server or RpcServer(identity=identity, host=host, port=port)
            self._register_handlers(self.server)
            if self._owns_server:
                await self.server.start()

        await self._bootstrap([p if isinstance(p, PeerAddr) else PeerAddr.from_string(p) for p in initial_peers])
        self._maintenance_task = asyncio.create_task(self._maintenance_loop(maintenance_period))
        return self

    # ------------------------------------------------------------------ public API

    @property
    def own_addr(self) -> Optional[PeerAddr]:
        if self.server is None:
            return None
        return PeerAddr(self.server.host, self.server.port, self.peer_id)

    async def store(
        self,
        key: DHTKey,
        value: Any,
        expiration_time: float,
        subkey: Optional[str] = None,
    ) -> bool:
        """Store on the K nearest peers (and locally if we are one of them)."""
        kid = key_id(key)
        nearest = await self.find_nearest_peers(kid, k=self.replication)
        entry = [kid.hex(), subkey, value, expiration_time]
        ok_any = False
        if self._stores_locally(kid, nearest):
            from petals_tpu.dht.identity import verify_announcement

            # same rule as _handle_store: subkey records enter ANY storage
            # (ours included) only with a valid signature from the subkey owner
            if subkey is None or verify_announcement(value, subkey, expiration_time):
                ok_any |= self.storage.store(kid, value, expiration_time, subkey)
        results = await asyncio.gather(
            *(self._rpc_store(addr, [entry]) for addr in nearest), return_exceptions=True
        )
        ok_any |= any(r is True for r in results)
        return ok_any

    async def get(self, key: DHTKey) -> Optional[Tuple[Any, float]]:
        """Latest value for key: local record or iterative find_value."""
        kid = key_id(key)
        best = self.storage.get(kid)
        found = await self._iterative_find_value(kid)
        for candidate in found:
            best = _merge_records(best, candidate)
        return best

    async def ping(self, addr: PeerAddr) -> bool:
        try:
            client = await self.pool.get(addr.host, addr.port)
            result = await client.call("dht.ping", {"sender": self._sender_wire()}, timeout=self.request_timeout)
            remote = PeerID.from_string(result["peer_id"])
            self.table.add(PeerAddr(addr.host, addr.port, remote))
            return True
        except Exception:
            self.pool.invalidate(addr.host, addr.port)
            self.table.remove(addr.peer_id)
            return False

    async def find_nearest_peers(self, target: bytes, k: Optional[int] = None) -> List[PeerAddr]:
        """Iterative Kademlia lookup for the k peers nearest to ``target``."""
        k = k or self.replication
        target_pid = PeerID(target)
        shortlist: Dict[PeerID, PeerAddr] = {a.peer_id: a for a in self.table.nearest(target_pid, k * 2)}
        queried: set = set()

        while True:
            # Kademlia convergence: only pursue unqueried peers among the k
            # closest currently known — once those are all queried, stop. This
            # keeps lookups O(log N) instead of flooding the whole swarm.
            k_closest = sorted(
                shortlist.values(), key=lambda a: xor_distance(a.peer_id, target_pid)
            )[:k]
            candidates = [a for a in k_closest if a.peer_id not in queried][: self.alpha]
            if not candidates:
                break
            results = await asyncio.gather(
                *(self._rpc_find_node(addr, target) for addr in candidates), return_exceptions=True
            )
            for addr, result in zip(candidates, results):
                queried.add(addr.peer_id)
                if isinstance(result, Exception) or result is None:
                    shortlist.pop(addr.peer_id, None)
                    continue
                for peer in result:
                    if peer.peer_id != self.peer_id:
                        shortlist.setdefault(peer.peer_id, peer)
                        self.table.add(peer)

        out = sorted(shortlist.values(), key=lambda a: xor_distance(a.peer_id, target_pid))
        return out[:k]

    async def shutdown(self) -> None:
        if self._maintenance_task is not None:
            self._maintenance_task.cancel()
            try:
                await self._maintenance_task
            except asyncio.CancelledError:
                pass
        await self.pool.close()
        if self.server is not None and self._owns_server:
            await self.server.stop()

    # ------------------------------------------------------------------ RPC client side

    def _sender_wire(self) -> Optional[list]:
        addr = self.own_addr
        return addr.to_wire() if addr is not None else None

    async def _rpc_store(self, addr: PeerAddr, entries: List[list]) -> bool:
        if addr.peer_id == self.peer_id:
            return False  # local store handled by caller
        try:
            client = await self.pool.get(addr.host, addr.port)
            result = await client.call(
                "dht.store", {"entries": entries, "sender": self._sender_wire()}, timeout=self.request_timeout
            )
            return any(result.get("ok", []))
        except Exception as e:
            logger.debug(f"store to {addr} failed: {e}")
            self.pool.invalidate(addr.host, addr.port)
            self.table.remove(addr.peer_id)
            return False

    async def _rpc_find_node(self, addr: PeerAddr, target: bytes) -> Optional[List[PeerAddr]]:
        if addr.peer_id == self.peer_id:
            return []
        try:
            client = await self.pool.get(addr.host, addr.port)
            result = await client.call(
                "dht.find_node",
                {"target": target.hex(), "k": self.replication * 2, "sender": self._sender_wire()},
                timeout=self.request_timeout,
            )
            return [PeerAddr.from_wire(p) for p in result.get("peers", [])]
        except Exception:
            self.pool.invalidate(addr.host, addr.port)
            self.table.remove(addr.peer_id)
            return None

    async def _rpc_find_value(self, addr: PeerAddr, kid: bytes) -> Optional[Tuple[Any, float]]:
        if addr.peer_id == self.peer_id:
            return None
        try:
            client = await self.pool.get(addr.host, addr.port)
            result = await client.call(
                "dht.find_value",
                {"key": kid.hex(), "sender": self._sender_wire()},
                timeout=self.request_timeout,
            )
            if result.get("value") is None:
                for peer in result.get("peers", []):
                    self.table.add(PeerAddr.from_wire(peer))
                return None
            value, expiration = result["value"]
            return _wire_to_record(value), expiration
        except Exception:
            self.pool.invalidate(addr.host, addr.port)
            self.table.remove(addr.peer_id)
            return None

    async def _iterative_find_value(self, kid: bytes) -> List[Tuple[Any, float]]:
        nearest = await self.find_nearest_peers(kid, k=self.replication)
        results = await asyncio.gather(*(self._rpc_find_value(a, kid) for a in nearest))
        return [r for r in results if r is not None]

    def _stores_locally(self, kid: bytes, nearest: List[PeerAddr]) -> bool:
        if self.client_mode:
            return False
        if len(nearest) < self.replication:
            return True
        own_dist = xor_distance(self.peer_id, PeerID(kid))
        worst = xor_distance(nearest[-1].peer_id, PeerID(kid))
        return own_dist <= worst

    # ------------------------------------------------------------------ RPC server side

    def _register_handlers(self, server: RpcServer) -> None:
        from petals_tpu.utils.bandwidth import BandwidthProtocol

        BandwidthProtocol().register(server)  # all listening nodes answer probes
        server.add_unary_handler("dht.ping", self._handle_ping)
        server.add_unary_handler("dht.store", self._handle_store)
        server.add_unary_handler("dht.find_node", self._handle_find_node)
        server.add_unary_handler("dht.find_value", self._handle_find_value)

    def _note_sender(self, payload) -> None:
        sender = (payload or {}).get("sender")
        if sender:
            try:
                self.table.add(PeerAddr.from_wire(sender))
            except Exception:
                pass

    async def _handle_ping(self, payload, ctx: RpcContext):
        self._note_sender(payload)
        return {"peer_id": self.peer_id.to_string()}

    async def _handle_store(self, payload, ctx: RpcContext):
        self._note_sender(payload)
        from petals_tpu.dht.identity import verify_announcement

        ok = []
        for kid_hex, subkey, value, expiration in payload["entries"]:
            # per-peer subkey records must be SIGNED by the subkey's keyholder
            # (hivemind RSASignatureValidator semantics): an unsigned or
            # mis-signed record cannot overwrite another peer's announcements
            if subkey is not None and not verify_announcement(value, subkey, float(expiration)):
                logger.debug(f"Rejecting unsigned/invalid subkey record for {subkey!r}")
                ok.append(False)
                continue
            ok.append(self.storage.store(bytes.fromhex(kid_hex), value, float(expiration), subkey))
        return {"ok": ok}

    async def _handle_find_node(self, payload, ctx: RpcContext):
        self._note_sender(payload)
        target = PeerID(bytes.fromhex(payload["target"]))
        peers = self.table.nearest(target, int(payload.get("k", self.replication * 2)))
        out = [p.to_wire() for p in peers]
        if self.own_addr is not None:
            out.append(self.own_addr.to_wire())
        return {"peers": out}

    async def _handle_find_value(self, payload, ctx: RpcContext):
        self._note_sender(payload)
        kid = bytes.fromhex(payload["key"])
        record = self.storage.get(kid)
        if record is not None:
            return {"value": [_record_to_wire(record[0]), record[1]]}
        target = PeerID(kid)
        return {"value": None, "peers": [p.to_wire() for p in self.table.nearest(target, self.replication * 2)]}

    # ------------------------------------------------------------------ internals

    async def _bootstrap(self, peers: List[PeerAddr]) -> None:
        if not peers:
            return
        results = await asyncio.gather(*(self.ping(p) for p in peers))
        if not any(results):
            logger.warning(f"Could not reach any of {len(peers)} initial peers")
            return
        # populate the table with peers near our own id
        await self.find_nearest_peers(self.peer_id.to_bytes(), k=self.replication)

    async def _maintenance_loop(self, period: float) -> None:
        while True:
            await asyncio.sleep(period)
            self.storage.remove_expired()


def _merge_records(a: Optional[Tuple[Any, float]], b: Optional[Tuple[Any, float]]) -> Optional[Tuple[Any, float]]:
    """Combine records from multiple peers: subkey dicts merge per-subkey by
    freshness; plain values keep the fresher one."""
    if a is None:
        return b
    if b is None:
        return a
    av, ae = a
    bv, be = b
    if isinstance(av, SubkeyDict) and isinstance(bv, SubkeyDict):
        merged = SubkeyDict(av)
        for sk, (v, e) in bv.items():
            if sk not in merged or merged[sk][1] < e:
                merged[sk] = (v, e)
        return merged, max(ae, be)
    return a if ae >= be else b


def _record_to_wire(value: Any) -> Any:
    if isinstance(value, SubkeyDict):  # {subkey: (value, expiration)}
        return {"__subkeys__": {sk: [v, e] for sk, (v, e) in value.items()}}
    return {"__plain__": value}


def _wire_to_record(obj: Any) -> Any:
    if isinstance(obj, dict) and "__subkeys__" in obj:
        return SubkeyDict({sk: (v, e) for sk, (v, e) in obj["__subkeys__"].items()})
    if isinstance(obj, dict) and "__plain__" in obj:
        return obj["__plain__"]
    return obj
