"""Local DHT record storage with expirations and subkeys.

Record model mirrors what the reference's directory layer needs
(src/petals/utils/dht.py:28-131): a key maps either to a plain value or to a
dictionary of subkeys (one per announcing peer), each with its own expiration
time (unix seconds). Newer expiration wins on conflict.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Tuple

ValueWithExpiration = Tuple[Any, float]


class SubkeyDict(dict):
    """Marker type distinguishing a per-subkey record ({subkey: (value, exp)})
    from a plain value that happens to be a dict."""


class DHTStorage:
    def __init__(self, maxsize: int = 100_000):
        self.maxsize = maxsize
        # key -> (value | {subkey: (value, expiration)}, expiration)
        self._records: Dict[bytes, Tuple[Any, float]] = {}

    def store(
        self, key: bytes, value: Any, expiration: float, subkey: Optional[str] = None
    ) -> bool:
        now = time.time()
        if expiration <= now:
            return False
        self._evict_expired_if_full()
        existing = self._records.get(key)
        if subkey is None:
            # A plain write replaces an existing record (of either kind) only
            # if it is fresher — never silently wipes live announcements.
            if existing is not None and existing[1] > expiration:
                return False
            self._records[key] = (value, expiration)
            return True

        if existing is not None and isinstance(existing[0], SubkeyDict):
            subdict, top_exp = existing
        elif existing is not None and existing[1] > expiration:
            return False  # fresher plain record wins over this subkey write
        else:
            subdict, top_exp = SubkeyDict(), 0.0
        prev = subdict.get(subkey)
        if prev is not None and prev[1] > expiration:
            return False
        subdict[subkey] = (value, expiration)
        self._records[key] = (subdict, max(top_exp, expiration))
        return True

    def get(self, key: bytes) -> Optional[ValueWithExpiration]:
        record = self._records.get(key)
        if record is None:
            return None
        value, expiration = record
        now = time.time()
        if isinstance(value, SubkeyDict):
            live = SubkeyDict({sk: (v, e) for sk, (v, e) in value.items() if e > now})
            if not live:
                del self._records[key]
                return None
            return live, max(e for _, e in live.values())
        if expiration <= now:
            del self._records[key]
            return None
        return value, expiration

    def remove_expired(self) -> None:
        now = time.time()
        for key in list(self._records):
            value, expiration = self._records[key]
            if isinstance(value, SubkeyDict):
                live = SubkeyDict({sk: (v, e) for sk, (v, e) in value.items() if e > now})
                if live:
                    self._records[key] = (live, max(e for _, e in live.values()))
                else:
                    del self._records[key]
            elif expiration <= now:
                del self._records[key]

    def _evict_expired_if_full(self) -> None:
        if len(self._records) >= self.maxsize:
            self.remove_expired()
        if len(self._records) >= self.maxsize:
            # still full: drop the soonest-to-expire record
            victim = min(self._records, key=lambda k: self._records[k][1])
            del self._records[victim]

    def __len__(self) -> int:
        return len(self._records)
