"""Closed-loop swarm elasticity: the autoscaler policy and controller.

:mod:`petals_tpu.swarm.policy` is a PURE deterministic decision function
over swarm-aggregate snapshots (no I/O, no clocks, no randomness) —
that's what makes decisions replayable and their journals byte-identical
across runs. :mod:`petals_tpu.swarm.autoscaler` wraps it in a controller
that samples a live swarm (via :class:`~petals_tpu.utils.health.HealthMonitor`
state), journals every decision with its evidence, and hands decisions to
a pluggable actuator. ``python -m petals_tpu.cli.run_autoscaler`` runs it
against a real swarm; ``benchmarks/bench_swarm_scale.py`` closes the loop
in-process and gates it in CI.
"""

from petals_tpu.swarm.autoscaler import Autoscaler, CallbackActuator
from petals_tpu.swarm.policy import (
    AutoscalerPolicy,
    Decision,
    PolicyConfig,
    ServerSample,
    SwarmSnapshot,
)

__all__ = [
    "Autoscaler",
    "AutoscalerPolicy",
    "CallbackActuator",
    "Decision",
    "PolicyConfig",
    "ServerSample",
    "SwarmSnapshot",
]
