"""Closed-loop autoscaler controller: snapshot → policy → actuator.

The controller owns everything IMPURE around the pure policy: sampling
the swarm (a :class:`~petals_tpu.utils.health.HealthMonitor`'s refreshed
state or any snapshot callable), journaling decisions into the telemetry
journal, exporting gauges, and dispatching decisions to an actuator.
Actuators are pluggable because what "spawn a replica" means differs by
deployment: the benchmark boots in-process Servers, the CLI shells out
to operator-provided commands (or just journals in advisory mode).

An actuator failure is journaled and COUNTED but never re-raised into
the control loop — a failed spawn must not kill the controller that
would retry after the cooldown.
"""

from __future__ import annotations

import asyncio
import inspect
from typing import Awaitable, Callable, List, Optional, Tuple, Union

from petals_tpu.swarm.policy import AutoscalerPolicy, Decision, PolicyConfig, SwarmSnapshot
from petals_tpu.telemetry import get_journal
from petals_tpu.telemetry import instruments as tm
from petals_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# a callback may be sync or async; the controller awaits either
_MaybeAsync = Union[Callable[..., Awaitable[object]], Callable[..., object]]


async def _invoke(fn: _MaybeAsync, *args) -> object:
    result = fn(*args)
    if inspect.isawaitable(result):
        result = await result
    return result


def _accepts_n_args(fn, n: int) -> bool:
    """Whether ``fn`` can be called with ``n`` positional args — used to
    pass the decision's phase tier to scale_out callbacks that declare a
    second parameter, without breaking single-arg legacy callbacks."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    count = 0
    for p in sig.parameters.values():
        if p.kind is p.VAR_POSITIONAL:
            return True
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
            count += 1
    return count >= n


class CallbackActuator:
    """Dispatch decisions to per-action callbacks (sync or async).

    ``scale_out(span)`` / ``scale_in(peer)`` / ``resize(peer, span)``;
    a missing callback makes that action advisory (journaled, not acted
    on). Returns whether the action was actually performed."""

    def __init__(
        self,
        *,
        scale_out: Optional[_MaybeAsync] = None,
        scale_in: Optional[_MaybeAsync] = None,
        resize: Optional[_MaybeAsync] = None,
    ):
        self._callbacks = {"scale_out": scale_out, "scale_in": scale_in, "resize": resize}

    async def apply(self, decision: Decision) -> bool:
        fn = self._callbacks.get(decision.action)
        if fn is None:
            return False
        if decision.action == "scale_out":
            tier = getattr(decision, "tier", None)
            if tier is not None and _accepts_n_args(fn, 2):
                # tier-aware spawners boot the replica with --phase_tier
                await _invoke(fn, decision.span, tier)
            else:
                await _invoke(fn, decision.span)
        elif decision.action == "scale_in":
            await _invoke(fn, decision.target)
        else:
            await _invoke(fn, decision.target, decision.span)
        return True


class Autoscaler:
    """Drives the policy: one :meth:`step` per snapshot, or :meth:`run`
    to loop against a snapshot source on a fixed period."""

    def __init__(
        self,
        snapshot_fn: Optional[_MaybeAsync] = None,
        *,
        actuator: Optional[CallbackActuator] = None,
        config: Optional[PolicyConfig] = None,
        interval_s: float = 5.0,
    ):
        self.policy = AutoscalerPolicy(config)
        self.actuator = actuator
        self.snapshot_fn = snapshot_fn  # tick:int -> SwarmSnapshot (sync or async)
        self.interval_s = interval_s
        self.tick = 0
        self.decisions: List[Decision] = []
        # (decision, applied) pairs — what the actuator actually did
        self.applied: List[Tuple[Decision, bool]] = []

    async def step(self, snapshot: SwarmSnapshot) -> List[Decision]:
        """Feed one snapshot through the policy; journal + act on the
        decisions. The journal event carries the full evidence so an
        operator can answer "why did it scale?" from telemetry alone."""
        decisions = self.policy.observe(snapshot)
        tm.AUTOSCALE_HOT_STREAK.set(self.policy._hot_streak)
        tm.AUTOSCALE_REPLICAS.set(snapshot.replica_count())
        for decision in decisions:
            tm.AUTOSCALE_DECISIONS.labels(action=decision.action).inc()
            entry = decision.to_journal()
            get_journal().event("autoscale_decision", **entry)
            logger.info(
                f"autoscale[{decision.tick}] {decision.action} "
                f"target={decision.target} span={decision.span}: {decision.reason}"
            )
            self.decisions.append(decision)
            applied = False
            if self.actuator is not None:
                try:
                    applied = bool(await self.actuator.apply(decision))
                except Exception as e:
                    tm.AUTOSCALE_APPLY_FAILED.inc()
                    get_journal().event(
                        "autoscale_apply_failed",
                        action=decision.action,
                        target=decision.target,
                        error=repr(e),
                    )
                    logger.warning(
                        f"autoscale actuator failed for {decision.action}: {e!r}"
                    )
                else:
                    if applied:
                        get_journal().event(
                            "autoscale_applied",
                            action=decision.action,
                            target=decision.target,
                            span=list(decision.span) if decision.span else None,
                        )
            self.applied.append((decision, applied))
        return decisions

    async def run_once(self) -> List[Decision]:
        """Sample the snapshot source once and step the policy."""
        if self.snapshot_fn is None:
            raise RuntimeError("Autoscaler.run_once needs a snapshot_fn")
        snapshot = await _invoke(self.snapshot_fn, self.tick)
        self.tick += 1
        if snapshot is None:
            return []
        return await self.step(snapshot)

    async def run(self, *, max_ticks: Optional[int] = None) -> None:
        """Control loop: sample every ``interval_s`` until cancelled (or
        ``max_ticks`` ticks, for tests)."""
        while max_ticks is None or self.tick < max_ticks:
            try:
                await self.run_once()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # a failed sample (DHT timeout, chaos-dropped lookup) skips
                # the tick; the controller must outlive transient failures
                logger.warning(f"autoscale tick {self.tick} failed: {e!r}")
                self.tick += 1
            await asyncio.sleep(self.interval_s)
